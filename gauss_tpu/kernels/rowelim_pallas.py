"""Row-elimination Pallas kernel: one pivot step over an HBM-resident matrix.

This is the BASELINE.json north-star kernel: "the row-reduction inner loop of
Gaussian elimination (pivot-row broadcast + per-row SAXPY elimination)
becomes a Pallas kernel over HBM-resident float32 matrices". It is the TPU
re-expression of the reference's ``subtractElim`` hot loop
(reference Pthreads/Version-1/gauss_internal_input.c:140-164): where a pthread
strides rows ``i+1+tid, i+1+tid+T, ...``, here a (rows, cols) grid of programs
each owns one VMEM tile; the pivot row arrives in every column-tile's program
via a dynamically-indexed (1, bn) block (the broadcast), the multiplier column
via a (bm, 1) block, and the update is one fused VPU FMA per tile.

The pivot *selection* and row swap stay outside the kernel in jnp (they are
O(n) work on one column; the kernel is the O(n^2) part), exactly as the
reference keeps ``getPivot`` serial while parallelizing only the elimination.

``gauss_solve_rowelim`` chains n kernel steps under one ``fori_loop`` — the
whole solve is still a single compiled program. That per-step form is kept
as the step-for-step analog of the reference's algorithmic shape, but it is
HBM-bound by construction: every pivot step reads and writes the whole
matrix, n full passes per solve (~62 ms at n=2048 on v5e — VERDICT round 1
weak #5).

``gauss_solve_rowelim_batched`` is the performance form of the same engine:
k pivot steps per launch. The (npad, k) column strip is factored in one
VMEM-resident Pallas program (kernels.panel_pallas — pivot selection and
swaps INSIDE the kernel), and the k accumulated eliminations hit the matrix
as ONE rank-k SAXPY — an (bm, k) x (k, bn) MXU dot per tile in the
``_rankk_kernel`` below — so the matrix makes n/k full HBM passes instead
of n. Same pivoting policy, same verification, ~k-fold less traffic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret
# Elimination-kernel tile shape: seeded from the autotuner space (single
# source — tune.space.ROWELIM_TILE_SEED), the measured v5e default.
from gauss_tpu.tune.space import ROWELIM_TILE_SEED

DEFAULT_BM, DEFAULT_BN = ROWELIM_TILE_SEED


def _elim_kernel(i_ref, piv_ref, m_ref, prow_ref, pcol_ref, out_ref, *, bm, bn):
    i = i_ref[0]
    inv_piv = 1.0 / piv_ref[0, 0]
    r = pl.program_id(0)
    c = pl.program_id(1)
    rows = r * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)[:, 0]
    cols = c * bn + lax.broadcasted_iota(jnp.int32, (1, bn), 1)[0, :]

    # Scaled pivot row, diagonal pinned to exactly 1 (see core.gauss).
    prow = jnp.where(cols == i, jnp.ones((), m_ref.dtype),
                     prow_ref[0, :] * inv_piv.astype(m_ref.dtype))
    # Multipliers: current column-i values of rows below the pivot.
    f = jnp.where(rows > i, pcol_ref[:, 0], jnp.zeros((), m_ref.dtype))

    new = m_ref[:] - f[:, None] * prow[None, :]
    # Rows in this tile equal to the pivot row receive the scaled pivot row.
    out_ref[:] = jnp.where((rows == i)[:, None], prow[None, :], new)


@partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def eliminate_step_pallas(m: jax.Array, i: jax.Array, *, bm: int = DEFAULT_BM,
                          bn: int = DEFAULT_BN, interpret: bool | None = None) -> jax.Array:
    """One elimination step on the (already pivot-swapped) augmented matrix.

    m: (nrows, ncols) with nrows % bm == 0 == ncols % bn (caller pads).
    i: dynamic pivot index. Returns the updated matrix.
    """
    interpret = _auto_interpret(interpret)
    nrows, ncols = m.shape
    if nrows % bm or ncols % bn:
        raise ValueError(f"matrix {m.shape} not a multiple of tiles ({bm}, {bn})")
    i = jnp.asarray(i, jnp.int32).reshape(1)
    # Pre-extract the pivot row / multiplier column as standalone arrays: TPU
    # block shapes must be (8k, 128k) or equal to the array dims, so a
    # dynamically-positioned (1, bn) block of the big matrix is not lowerable,
    # but a (1, bn) block of a (1, ncols) array is. The two dynamic slices are
    # O(n) against the kernel's O(n^2).
    zero = jnp.zeros((), jnp.int32)
    prow = lax.dynamic_slice(m, (i[0], zero), (1, ncols))
    pcol = lax.dynamic_slice(m, (zero, i[0]), (nrows, 1))
    piv = lax.dynamic_slice(prow, (zero, i[0]), (1, 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nrows // bm, ncols // bn),
        in_specs=[
            # index_map signature: (*grid_ids, *scalar_prefetch_refs)
            pl.BlockSpec((1, 1), lambda r, c, i_ref: (0, 0),
                         memory_space=pltpu.SMEM),          # pivot value
            pl.BlockSpec((bm, bn), lambda r, c, i_ref: (r, c)),  # tile
            pl.BlockSpec((1, bn), lambda r, c, i_ref: (0, c)),   # pivot row
            pl.BlockSpec((bm, 1), lambda r, c, i_ref: (r, 0)),   # pivot col
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda r, c, i_ref: (r, c)),
    )
    return pl.pallas_call(
        partial(_elim_kernel, bm=bm, bn=bn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(m.shape, m.dtype),
        interpret=interpret,
    )(i, piv, m, prow, pcol)


@partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def gauss_solve_rowelim(a: jax.Array, b: jax.Array, *, bm: int = DEFAULT_BM,
                        bn: int = DEFAULT_BN, interpret: bool | None = None) -> jax.Array:
    """Full solve with the per-step elimination kernel (partial pivoting).

    Pivot select + two-row swap in jnp per step; the O(n^2) elimination in the
    Pallas kernel; back-substitution from the core oracle.
    """
    from gauss_tpu.core.gauss import back_substitute

    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype)
    n = a.shape[0]
    npad = -(-n // bm) * bm
    wpad = -(-(npad + 1) // bn) * bn  # width rounded up to hold the RHS column
    m = jnp.zeros((npad, wpad), a.dtype)
    m = m.at[:n, :n].set(a)
    if npad != n:
        m = m.at[jnp.arange(n, npad), jnp.arange(n, npad)].set(
            jnp.asarray(1.0, a.dtype))
    m = m.at[:n, npad].set(b)
    ridx = jnp.arange(npad)

    def step(i, m):
        col = m[:, i]
        cand = jnp.where(ridx >= i, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(cand)
        row_i, row_p = m[i], m[p]
        m = m.at[i].set(row_p).at[p].set(row_i)
        return eliminate_step_pallas(m, i, bm=bm, bn=bn, interpret=interpret)

    m = lax.fori_loop(0, npad, step, m)
    x = back_substitute(m[:npad, :npad], m[:, npad])
    return x[:n]


def _rankk_kernel(m_ref, f_ref, u_ref, out_ref):
    """One output tile of m - F @ U: the k accumulated pivot-row SAXPYs of a
    batch, fused into a single MXU dot (the rank-k form of _elim_kernel's
    rank-1 update)."""
    out_ref[:] = m_ref[:] - jnp.dot(f_ref[:], u_ref[:],
                                    preferred_element_type=m_ref.dtype,
                                    precision=lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def rankk_update_pallas(m: jax.Array, f: jax.Array, u: jax.Array, *,
                        bm: int = DEFAULT_BM, bn: int = DEFAULT_BN,
                        interpret: bool | None = None) -> jax.Array:
    """``m - f @ u`` tiled onto the MXU: m (R, C), f (R, k), u (k, C);
    R % bm == 0 == C % bn (caller pads)."""
    interpret = _auto_interpret(interpret)
    R, C = m.shape
    k = f.shape[1]
    if R % bm or C % bn:
        raise ValueError(f"matrix {m.shape} not a multiple of tiles ({bm}, {bn})")
    return pl.pallas_call(
        _rankk_kernel,
        grid=(R // bm, C // bn),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda r, c: (r, c)),
            pl.BlockSpec((bm, k), lambda r, c: (r, 0)),
            pl.BlockSpec((k, bn), lambda r, c: (0, c)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda r, c: (r, c)),
        out_shape=jax.ShapeDtypeStruct(m.shape, m.dtype),
        interpret=interpret,
    )(m, f, u)


# Back-substitution trace form threshold: unrolled below (fusable static
# dots), lax.scan at or above (O(1) trace size in nb) — mirrors
# core.blocked.LU_SOLVE_UNROLL_MAX_NB.
ROWELIM_UNROLL_MAX_NB = 16


def auto_rowelim_k(n: int) -> int:
    """Pivot steps per launch, from n (VERDICT round 2 weak #4: the fixed
    k=128 over-padded small systems and n=512 ran slower than n=1024).

    Measured on v5e (round-3 sweep, slope-timed, interleaved best-of-5):
    k=256 wins or ties at every size — 0.35 ms at n=512 (vs 1.11 ms at
    k=128), ~1.0 ms at n=1024, 3.3 ms at n=2048 (vs 3.8 ms at k=128) —
    fewer groups means fewer serial panel steps and the rank-256 update
    still feeds the MXU full tiles. Falls to narrower k only where the
    in-kernel panel factorization's VMEM block no longer fits (same
    working-set model as core.blocked.auto_panel: k=256 to n~12k, 128 to
    ~20k, 64 beyond)."""
    from gauss_tpu.core.blocked import panel_fits_vmem

    # With the round-5 aliased kernel the width ladder is monotone in
    # reach (64's ceiling ~34.7k now EXTENDS past 128's ~21.1k — the old
    # two-buffer model inverted that), so 64 is a real rung, carrying
    # in-kernel pivoting to the HBM ceiling. This engine slices its
    # panels from the full-width augmented matrix, which is immune to
    # the group-width fusion hazards of the chunked route (compile-probed
    # at 24576/32768).
    for k in (256, 128, 64):
        if panel_fits_vmem(n, k):
            return k
    # Nothing fits (academic on one chip — HBM binds first): the engine's
    # shared panel-impl resolution routes every panel to the stock-JAX
    # factorizer, which has no VMEM ceiling. There the WIDEST k wins
    # (fewer serial groups, fuller rank-k MXU updates), so return 256 —
    # never a narrow k that panel_fits_vmem has not approved anyway
    # (ADVICE r3 #2 / VERDICT r4 weak #3).
    return 256


@partial(jax.jit, static_argnames=("k", "bm", "bn", "interpret", "panel_impl"))
def gauss_solve_rowelim_batched(a: jax.Array, b: jax.Array, *,
                                k: int | None = None,
                                bm: int = DEFAULT_BM, bn: int = DEFAULT_BN,
                                interpret: bool | None = None,
                                panel_impl: str = "auto") -> jax.Array:
    """Full solve, k pivot steps per launch (VERDICT round 1 #5).

    Each group: the (npad, k) column strip is factored with partial pivoting
    in one VMEM-resident Pallas program (pivot select + swap in-kernel), the
    group's row permutation is applied as one gather, and the k eliminations
    land as a single rank-k Pallas MXU update. Row semantics are identical
    to :func:`gauss_solve_rowelim` (scaled unit-diagonal pivot rows, zeros
    below), so verification is unchanged; only the launch/traffic structure
    differs — n/k matrix passes instead of n.

    ``k=None`` resolves through :func:`auto_rowelim_k`.
    """
    from gauss_tpu.core.blocked import (_factor_panel, _fold_transpositions,
                                        _resolve_panel_impl, unit_lower_inv,
                                        upper_inv)

    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype)
    dtype = a.dtype
    n = a.shape[0]
    if k is None:
        k = auto_rowelim_k(n)
    blk = max(bm, k)
    if blk % k or blk % bm:
        raise ValueError(
            f"k={k} and bm={bm} must nest (one a multiple of the other) so "
            f"the padded size is a multiple of both")
    npad = -(-n // blk) * blk
    wpad = -(-(npad + 1) // bn) * bn
    m = jnp.zeros((npad, wpad), dtype)
    m = m.at[:n, :n].set(a)
    if npad != n:
        m = m.at[jnp.arange(n, npad), jnp.arange(n, npad)].set(
            jnp.asarray(1.0, dtype))
    m = m.at[:n, npad].set(b)

    rows = jnp.arange(npad)
    cols = jnp.arange(wpad)
    jcol = jnp.arange(k)
    zero = jnp.zeros((), dtype)
    eye_k = jnp.eye(k, dtype=dtype)
    nb = npad // k
    # "auto" falls back to the stock-JAX panel past the VMEM ceiling; an
    # explicit pallas request there raises a sizing error inside
    # _resolve_panel_impl (ADVICE r3 — shared with every core.blocked
    # entry point).
    panel_impl_resolved = _resolve_panel_impl(
        panel_impl, npad, k, jnp.dtype(dtype).itemsize)

    def group(g, carry):
        m, uinvs = carry
        kb = g * k
        p, ipiv, perm_local, _ = _factor_panel(m, kb, npad, k,
                                               panel_impl_resolved)
        if perm_local is None:
            perm_local = _fold_transpositions(ipiv, kb, npad, k)
        m = m[perm_local]

        dblk = lax.dynamic_slice(p, (kb, 0), (k, k))
        lmask = jcol[:, None] > jcol[None, :]
        linv = unit_lower_inv(jnp.where(lmask, dblk, zero) + eye_k)
        d = jnp.sum(dblk * eye_k, axis=1)          # U11 diagonal (pivots)

        # u12 = L11^-1 @ (post-swap block rows): its panel columns are U11,
        # its trailing columns the updated block-row tail. The block rows of
        # m are rewritten wholesale from u12 below, so the rank-k update
        # only needs multipliers for the rows BELOW the block.
        block_row = lax.dynamic_slice(m, (kb, 0), (k, wpad))
        u12 = jnp.dot(linv, block_row, precision=lax.Precision.HIGHEST)

        below = rows >= kb + k
        right = cols >= kb + k
        f = jnp.where(below[:, None], p, zero)
        u_masked = jnp.where(right[None, :], u12, zero)
        m = rankk_update_pallas(m, f, u_masked, bm=bm, bn=bn,
                                interpret=interpret)

        # Rewrite the block rows in rowelim semantics: unit diagonal, scaled
        # U11 above it in the panel columns, scaled U12 tail; and zero the
        # panel columns below the block.
        inv_d = (jnp.asarray(1.0, dtype) / d)[:, None]
        new_block = jnp.where(right[None, :], u12 * inv_d, zero)
        u11 = lax.dynamic_slice(u12, (0, kb), (k, k))
        pan = jnp.where(jcol[:, None] < jcol[None, :], u11 * inv_d, zero)
        pan = pan + eye_k
        new_block = lax.dynamic_update_slice(new_block, pan, (0, kb))
        m = lax.dynamic_update_slice(m, new_block, (kb, 0))
        pan_all = lax.dynamic_slice(m, (0, kb), (npad, k))
        pan_all = jnp.where(below[:, None], zero, pan_all)
        m = lax.dynamic_update_slice(m, pan_all, (0, kb))
        # Inverse of the scaled unit-upper diagonal block, for the blockwise
        # back-substitution below (an O(n)-step scalar recurrence would cost
        # as much as the whole elimination — measured 7.5 ms at n=2048).
        uinvs = lax.dynamic_update_slice(uinvs, upper_inv(pan)[None],
                                         (g, 0, 0))
        return m, uinvs

    m, uinvs = lax.fori_loop(0, nb, group,
                             (m, jnp.zeros((nb, k, k), dtype)))

    # Blockwise back-substitution: x_i = Uinv_ii (y_i - U_{i,>i} x_{>i}) —
    # MXU matvecs, not a scalar chain. Up to ROWELIM_UNROLL_MAX_NB blocks
    # the chain unrolls at trace time (every dot static and fusable);
    # beyond it one lax.scan keeps the trace O(1) in nb — the unrolled
    # form's ~2*nb distinctly-shaped dots were the reason this engine had
    # no n=16384 cell in round 3 (VERDICT weak #4; same fix as
    # core.blocked._blockwise_substitution_scan, the full-width row dot
    # meets zeros at every unsolved block so no masking is needed).
    if nb > ROWELIM_UNROLL_MAX_NB:
        def bstep(x, i):
            blk = lax.dynamic_slice(m, (i * k, 0), (k, npad))
            r = lax.dynamic_slice(m, (i * k, npad), (k, 1))[:, 0]
            r = r - jnp.dot(blk, x, precision=lax.Precision.HIGHEST)
            xi = jnp.dot(uinvs[i], r, precision=lax.Precision.HIGHEST)
            return lax.dynamic_update_slice(x, xi, (i * k,)), i

        x, _ = lax.scan(bstep, jnp.zeros((npad,), dtype),
                        jnp.arange(nb - 1, -1, -1))
        return x[:n]
    xblocks = [None] * nb
    for i in range(nb - 1, -1, -1):
        kb = i * k
        block = m[kb:kb + k]
        r = block[:, npad]
        if i < nb - 1:
            x_suffix = jnp.concatenate(xblocks[i + 1:])
            r = r - jnp.dot(block[:, (i + 1) * k:npad], x_suffix,
                            precision=lax.Precision.HIGHEST)
        xblocks[i] = jnp.dot(uinvs[i], r, precision=lax.Precision.HIGHEST)
    x = jnp.concatenate(xblocks)
    return x[:n]
