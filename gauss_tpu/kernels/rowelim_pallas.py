"""Row-elimination Pallas kernel: one pivot step over an HBM-resident matrix.

This is the BASELINE.json north-star kernel: "the row-reduction inner loop of
Gaussian elimination (pivot-row broadcast + per-row SAXPY elimination)
becomes a Pallas kernel over HBM-resident float32 matrices". It is the TPU
re-expression of the reference's ``subtractElim`` hot loop
(reference Pthreads/Version-1/gauss_internal_input.c:140-164): where a pthread
strides rows ``i+1+tid, i+1+tid+T, ...``, here a (rows, cols) grid of programs
each owns one VMEM tile; the pivot row arrives in every column-tile's program
via a dynamically-indexed (1, bn) block (the broadcast), the multiplier column
via a (bm, 1) block, and the update is one fused VPU FMA per tile.

The pivot *selection* and row swap stay outside the kernel in jnp (they are
O(n) work on one column; the kernel is the O(n^2) part), exactly as the
reference keeps ``getPivot`` serial while parallelizing only the elimination.

``gauss_solve_rowelim`` chains n kernel steps under one ``fori_loop`` — the
whole solve is still a single compiled program. The blocked path
(core.blocked) remains the throughput engine; this one matches the
reference's algorithmic shape step-for-step.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret


def _elim_kernel(i_ref, piv_ref, m_ref, prow_ref, pcol_ref, out_ref, *, bm, bn):
    i = i_ref[0]
    inv_piv = 1.0 / piv_ref[0, 0]
    r = pl.program_id(0)
    c = pl.program_id(1)
    rows = r * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)[:, 0]
    cols = c * bn + lax.broadcasted_iota(jnp.int32, (1, bn), 1)[0, :]

    # Scaled pivot row, diagonal pinned to exactly 1 (see core.gauss).
    prow = jnp.where(cols == i, jnp.ones((), m_ref.dtype),
                     prow_ref[0, :] * inv_piv.astype(m_ref.dtype))
    # Multipliers: current column-i values of rows below the pivot.
    f = jnp.where(rows > i, pcol_ref[:, 0], jnp.zeros((), m_ref.dtype))

    new = m_ref[:] - f[:, None] * prow[None, :]
    # Rows in this tile equal to the pivot row receive the scaled pivot row.
    out_ref[:] = jnp.where((rows == i)[:, None], prow[None, :], new)


@partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def eliminate_step_pallas(m: jax.Array, i: jax.Array, *, bm: int = 256,
                          bn: int = 256, interpret: bool | None = None) -> jax.Array:
    """One elimination step on the (already pivot-swapped) augmented matrix.

    m: (nrows, ncols) with nrows % bm == 0 == ncols % bn (caller pads).
    i: dynamic pivot index. Returns the updated matrix.
    """
    interpret = _auto_interpret(interpret)
    nrows, ncols = m.shape
    if nrows % bm or ncols % bn:
        raise ValueError(f"matrix {m.shape} not a multiple of tiles ({bm}, {bn})")
    i = jnp.asarray(i, jnp.int32).reshape(1)
    # Pre-extract the pivot row / multiplier column as standalone arrays: TPU
    # block shapes must be (8k, 128k) or equal to the array dims, so a
    # dynamically-positioned (1, bn) block of the big matrix is not lowerable,
    # but a (1, bn) block of a (1, ncols) array is. The two dynamic slices are
    # O(n) against the kernel's O(n^2).
    zero = jnp.zeros((), jnp.int32)
    prow = lax.dynamic_slice(m, (i[0], zero), (1, ncols))
    pcol = lax.dynamic_slice(m, (zero, i[0]), (nrows, 1))
    piv = lax.dynamic_slice(prow, (zero, i[0]), (1, 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nrows // bm, ncols // bn),
        in_specs=[
            # index_map signature: (*grid_ids, *scalar_prefetch_refs)
            pl.BlockSpec((1, 1), lambda r, c, i_ref: (0, 0),
                         memory_space=pltpu.SMEM),          # pivot value
            pl.BlockSpec((bm, bn), lambda r, c, i_ref: (r, c)),  # tile
            pl.BlockSpec((1, bn), lambda r, c, i_ref: (0, c)),   # pivot row
            pl.BlockSpec((bm, 1), lambda r, c, i_ref: (r, 0)),   # pivot col
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda r, c, i_ref: (r, c)),
    )
    return pl.pallas_call(
        partial(_elim_kernel, bm=bm, bn=bn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(m.shape, m.dtype),
        interpret=interpret,
    )(i, piv, m, prow, pcol)


@partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def gauss_solve_rowelim(a: jax.Array, b: jax.Array, *, bm: int = 256,
                        bn: int = 256, interpret: bool | None = None) -> jax.Array:
    """Full solve with the per-step elimination kernel (partial pivoting).

    Pivot select + two-row swap in jnp per step; the O(n^2) elimination in the
    Pallas kernel; back-substitution from the core oracle.
    """
    from gauss_tpu.core.gauss import back_substitute

    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype)
    n = a.shape[0]
    npad = -(-n // bm) * bm
    wpad = -(-(npad + 1) // bn) * bn  # width rounded up to hold the RHS column
    m = jnp.zeros((npad, wpad), a.dtype)
    m = m.at[:n, :n].set(a)
    if npad != n:
        m = m.at[jnp.arange(n, npad), jnp.arange(n, npad)].set(
            jnp.asarray(1.0, a.dtype))
    m = m.at[:n, npad].set(b)
    ridx = jnp.arange(npad)

    def step(i, m):
        col = m[:, i]
        cand = jnp.where(ridx >= i, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(cand)
        row_i, row_p = m[i], m[p]
        m = m.at[i].set(row_p).at[p].set(row_i)
        return eliminate_step_pallas(m, i, bm=bm, bn=bn, interpret=interpret)

    m = lax.fori_loop(0, npad, step, m)
    x = back_substitute(m[:npad, :npad], m[:, npad])
    return x[:n]
