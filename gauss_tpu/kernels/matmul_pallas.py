"""Tiled Pallas matmul — the CUDA Version-2 engine rebuilt for the MXU.

The reference's best matmul kernel assigns one thread per output cell over a
2-D grid (reference CUDA_and_OpenMP/Version-2/cuda_matmul.cu:89-101, launch
:155). The TPU analog assigns one *program* per output MXU tile over a 3-D
grid (m, n, k), accumulating partial products in a VMEM scratch accumulator
across the k dimension — XLA's own matmul lowering uses the same shape, so
this kernel exists (a) as the hand-written-engine capability the reference
demonstrates with CUDA and (b) as the building block for fused variants.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Single shared precision-name mapping lives in core.matmul; re-exported
# here for existing importers (core.blocked, tests).
from gauss_tpu.core.matmul import PRECISIONS, resolve_precision  # noqa: F401


def _auto_interpret(interpret):
    if interpret is None:
        # These kernels use TPU-only Mosaic features (pltpu grid specs, SMEM);
        # anything that is not a real TPU runs the interpreter.
        return jax.default_backend() != "tpu"
    return interpret


def _mm_kernel(a_ref, b_ref, o_ref, acc_ref, *, precision, k_axis,
               bf16x3=False):
    """Shared accumulate kernel; k_axis names the grid axis that walks K
    (2 for the 3-D tiled variant, 1 for the 2-D row-stripe variant)."""
    @pl.when(pl.program_id(k_axis) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Precision: the MXU's default single bf16 pass fails the reference's
    # eps=1e-4 comparator for f32 inputs at n >= 512. The bf16x3 "high"
    # scheme passes it (see core.matmul, which defaults to it), but Mosaic
    # rejects precision=HIGH inside kernels ("Unsupported dot precision") —
    # so "high" is built BY HAND here (VERDICT r3 next #3): split each f32
    # tile into a bf16 hi part and a bf16 lo remainder, run three
    # single-pass MXU dots (hi*lo + lo*hi + hi*hi, small terms first), and
    # accumulate in f32. Same arithmetic XLA emits for precision=HIGH; the
    # splits are VPU-cheap against the dots. Round 3 ran these kernels
    # 6-pass "highest"-only and lost 2.2-2.5x to the XLA engine for that
    # reason alone.
    if bf16x3:
        a = a_ref[:]
        b = b_ref[:]
        a_hi = a.astype(jnp.bfloat16)
        a_lo = (a - a_hi.astype(a.dtype)).astype(jnp.bfloat16)
        b_hi = b.astype(jnp.bfloat16)
        b_lo = (b - b_hi.astype(b.dtype)).astype(jnp.bfloat16)
        acc = acc_ref.dtype
        acc_ref[:] += (jnp.dot(a_hi, b_lo, preferred_element_type=acc)
                       + jnp.dot(a_lo, b_hi, preferred_element_type=acc)
                       + jnp.dot(a_hi, b_hi, preferred_element_type=acc))
    else:
        acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                              preferred_element_type=acc_ref.dtype,
                              precision=precision)

    @pl.when(pl.program_id(k_axis) == pl.num_programs(k_axis) - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _kernel_precision(precision: str, dtype):
    """(lax_precision_or_None, bf16x3_flag) for an in-kernel dot. "high"
    maps to the manual bf16x3 path for f32 inputs (Mosaic rejects
    lax.Precision.HIGH in-kernel, for every dtype); for non-f32 inputs
    "high" falls back to HIGHEST — exact for bf16 operands (the MXU
    multiplies bf16 natively) and the only in-kernel option for f64."""
    if precision == "high":
        if dtype == jnp.float32:
            return None, True
        return lax.Precision.HIGHEST, False
    return resolve_precision(precision), False


MM_VMEM_BUDGET = 14 * 1024 * 1024  # tile working set, under the ~16 MB limit


def _mm_blocks(bm: int, bn: int, bk: int, itemsize: int, acc_itemsize: int,
               frozen=(False, False, False)) -> tuple:
    """Shrink the non-``frozen`` tile dims until the working set —
    double-buffered operand blocks, double-buffered output block,
    accumulator scratch — fits VMEM. The defaults are sized for f32
    (~11 MB) and pass through unchanged; f64 doubles every term and would
    exceed the budget at the same tiles (ADVICE r4 #2), so bk halves first
    (pipeline granularity only), then bn, then bm. Explicitly requested
    dims are frozen — they are measured as named, and a past-budget
    combination fails at compile, loudly."""
    def vmem(bm, bn, bk):
        return ((2 * (bm * bk + bk * bn) + 2 * bm * bn) * itemsize
                + bm * bn * acc_itemsize)

    req = (bm, bn, bk)
    while vmem(bm, bn, bk) > MM_VMEM_BUDGET and bk > 128 and not frozen[2]:
        bk //= 2
    while vmem(bm, bn, bk) > MM_VMEM_BUDGET and bn > 128 and not frozen[1]:
        bn //= 2
    while vmem(bm, bn, bk) > MM_VMEM_BUDGET and bm > 8 and not frozen[0]:
        bm //= 2
    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.record_vmem_estimate(
        "matmul_pallas_tiles", bm=bm, bn=bn, bk=bk, requested_bm=req[0],
        requested_bn=req[1], requested_bk=req[2], bytes=vmem(bm, bn, bk),
        budget=MM_VMEM_BUDGET, clamped=(bm, bn, bk) != req)
    return bm, bn, bk


def _pad2(x, bm, bn):
    m, n = x.shape
    mp = -(-m // bm) * bm
    np_ = -(-n // bn) * bn
    if (mp, np_) == (m, n):
        return x
    return jnp.zeros((mp, np_), x.dtype).at[:m, :n].set(x)


@partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret", "precision"))
def matmul_pallas(a: jax.Array, b: jax.Array, *, bm: int | None = None,
                  bn: int | None = None, bk: int | None = None,
                  interpret: bool | None = None,
                  precision: str = "high") -> jax.Array:
    """C = A @ B with an explicit (m, n, k) tile grid. Any shapes; inputs are
    zero-padded to tile multiples (zeros contribute nothing to the products).
    Accumulation is float32 for sub-f64 dtypes, float64 for f64 inputs.
    Default precision "high" = the manual in-kernel bf16x3 scheme (see
    _mm_kernel), matching the XLA engine's default (core.matmul).

    Default tiles (512, 512, 1024): operand streaming traffic scales as
    mp*np*K*(1/bm + 1/bn) bytes, so the 512-wide output tile halves the HBM
    traffic of the former 256x256 default — measured on v5e (sweep_mm_tiles
    r4): n=8192 27.5 -> 18.25 ms (1.04x the XLA engine, from 1.57x), n=4096
    3.53 -> 2.54 ms, n=2048 0.43 -> 0.36 ms. ~11 MB VMEM with Mosaic's
    double buffering; 1024-wide tiles exceed the 16 MB budget and fail to
    compile."""
    interpret = _auto_interpret(interpret)
    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"bad matmul shapes {a.shape} x {b.shape}")
    m, k = a.shape
    _, n = b.shape
    # Explicit tiles are honored verbatim (a tile sweep must measure the
    # config it names); dims left at their None defaults resolve from the
    # tuned store when one exists (keyed by the problem's largest extent),
    # else the tune.space seed (512, 512, 1024), and still route through
    # the VMEM clamp — f32 seeds pass through, wider dtypes shrink
    # (ADVICE r4).
    frozen = (bm is not None, bn is not None, bk is not None)
    if not all(frozen):
        from gauss_tpu.tune import apply as _tune
        from gauss_tpu.tune.space import MM_TILE_SEED

        nmax = max(m, n, k)
        dt = str(jnp.dtype(a.dtype))
        bm = bm or _tune.override("matmul", nmax, "bm", dtype=dt) \
            or MM_TILE_SEED[0]
        bn = bn or _tune.override("matmul", nmax, "bn", dtype=dt) \
            or MM_TILE_SEED[1]
        bk = bk or _tune.override("matmul", nmax, "bk", dtype=dt) \
            or MM_TILE_SEED[2]
    bm_ = min(bm, max(m, 8))
    bn_ = min(bn, max(n, 128))
    bk_ = min(bk, max(k, 128))
    if not all(frozen):
        acc_itemsize = 8 if a.dtype == jnp.float64 else 4
        bm_, bn_, bk_ = _mm_blocks(bm_, bn_, bk_,
                                   jnp.dtype(a.dtype).itemsize, acc_itemsize,
                                   frozen)
    ap = _pad2(a, bm_, bk_)
    bp = _pad2(b, bk_, bn_)
    mp, kp = ap.shape
    np_ = bp.shape[1]
    acc_dtype = jnp.float32 if a.dtype != jnp.float64 else jnp.float64

    prec, bf16x3 = _kernel_precision(precision, a.dtype)
    grid = (mp // bm_, np_ // bn_, kp // bk_)
    out = pl.pallas_call(
        partial(_mm_kernel, precision=prec, k_axis=2, bf16x3=bf16x3),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm_, bk_), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk_, bn_), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm_, bn_), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm_, bn_), acc_dtype)],
        interpret=interpret,
    )(ap, bp)
    return out[:m, :n]


STRIPE_VMEM_BUDGET = 12 * 1024 * 1024  # leave slack under the ~16 MB limit


def _stripe_vmem_bytes(bm: int, bk: int, n: int, itemsize: int) -> int:
    """Stripe-kernel VMEM estimate: A tile + B slab + output stripe, each
    double-buffered by the Mosaic pipeline, plus the accumulator scratch."""
    return (2 * (bm * bk + bk * n) + 3 * bm * n) * itemsize


def _stripe_blocks(m: int, k: int, n: int, bm: int, bk: int,
                   itemsize: int) -> tuple:
    """Shrink the requested (bm, bk) until the stripe working set fits VMEM.

    The full-width stripe is the point of the V1 layout, so N never tiles;
    bk halves first (it only gates pipeline granularity), then bm (it costs
    output-stripe parallelism). Raises when even the minimum blocks cannot
    hold the stripe — that is V2 (matmul_pallas) territory.
    """
    bm_, bk_ = min(bm, max(m, 8)), min(bk, max(k, 128))
    npad = -(-n // 128) * 128
    while (_stripe_vmem_bytes(bm_, bk_, npad, itemsize) > STRIPE_VMEM_BUDGET
           and bk_ > 128):
        bk_ = max(128, bk_ // 2)
    while (_stripe_vmem_bytes(bm_, bk_, npad, itemsize) > STRIPE_VMEM_BUDGET
           and bm_ > 8):
        bm_ = max(8, bm_ // 2)
    if _stripe_vmem_bytes(bm_, bk_, npad, itemsize) > STRIPE_VMEM_BUDGET:
        raise ValueError(
            f"stripe kernel cannot hold an n={n} output stripe in VMEM even "
            f"at minimum blocks; use matmul_pallas (the tiled V2 analog)")
    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.record_vmem_estimate(
        "matmul_pallas_stripe", bm=bm_, bk=bk_, n=n,
        bytes=_stripe_vmem_bytes(bm_, bk_, npad, itemsize),
        budget=STRIPE_VMEM_BUDGET, clamped=(bm_, bk_) != (bm, bk))
    return bm_, bk_


@partial(jax.jit, static_argnames=("bm", "bk", "interpret", "precision"))
def matmul_pallas_stripe(a: jax.Array, b: jax.Array, *, bm: int = 256,
                         bk: int = 512, interpret: bool | None = None,
                         precision: str = "high") -> jax.Array:
    """Row-stripe variant: each program owns a full (bm, N) output stripe.

    The MXU re-expression of CUDA Version-1's one-block-per-output-row layout
    (reference CUDA_and_OpenMP/Version-1/cuda_matmul.cu:89-103, launch :156):
    the N dimension is never tiled, so B's (bk, N) slab and the stripe
    accumulator must fit VMEM: bm/bk are treated as upper bounds and shrunk
    until the working set (with Mosaic's double buffering) fits the ~16 MB
    budget — workable to N ~ 4096, the regime where the reference ran V1.
    The 3-D-grid :func:`matmul_pallas` (the V2 analog) is the
    general-purpose kernel.
    """
    interpret = _auto_interpret(interpret)
    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"bad matmul shapes {a.shape} x {b.shape}")
    m, k = a.shape
    _, n = b.shape
    bm_, bk_ = _stripe_blocks(m, k, n, bm, bk, jnp.dtype(a.dtype).itemsize)
    ap = _pad2(a, bm_, bk_)
    bp = _pad2(b, bk_, 128)
    mp, kp = ap.shape
    np_ = bp.shape[1]
    acc_dtype = jnp.float32 if a.dtype != jnp.float64 else jnp.float64
    prec, bf16x3 = _kernel_precision(precision, a.dtype)

    out = pl.pallas_call(
        partial(_mm_kernel, precision=prec, k_axis=1, bf16x3=bf16x3),
        grid=(mp // bm_, kp // bk_),
        in_specs=[
            pl.BlockSpec((bm_, bk_), lambda i, kk: (i, kk)),
            pl.BlockSpec((bk_, np_), lambda i, kk: (kk, 0)),
        ],
        out_specs=pl.BlockSpec((bm_, np_), lambda i, kk: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm_, np_), acc_dtype)],
        interpret=interpret,
    )(ap, bp)
    return out[:m, :n]
