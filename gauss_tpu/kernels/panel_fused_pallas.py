"""Fused panel-factor + trailing-update kernel — the single-chip record path.

The blocked factorization's inner step is two device launches today: the
VMEM-resident panel factor (kernels.panel_pallas) writes the factored
(h, panel) block back to HBM, and the trailing update reads it right back —
as the L21 operand of the masked trailing GEMM plus the L11^-1-based U12
solve (core.blocked._install_and_update). That HBM round-trip between the
launches, and the XLA glue steps around it, are pure overhead at the sizes
where the whole working set pipelines through VMEM anyway — and the doctor
diff (reports/doctor_r3_vs_r5.json) charges the n=2048 regression to
exactly this class of between-launch host/HBM traffic.

This module fuses the two into ONE kernel:

- **Grid step 0** runs the panel factor — the *same* step loop as
  ``panel_pallas._factor_body`` (shared code, so the factored panel is
  bit-identical to ``panel_factor_pallas`` at a matching ``seg``) — and
  additionally records each step's multiplier lane vector and pivot
  one-hot into persistent (panel, h) VMEM scratch.
- **Every grid step** then updates one (h, ct) trailing column tile from
  that scratch: per ``fseg``-wide segment of the panel, the pivot-row
  values are extracted with one-hot dots, the segment's unit-triangular
  coupling is inverted by the factored Neumann series (the deferred-update
  scheme of panel_pallas, commuting factors of powers of one nilpotent
  matrix), and the rank-``fseg`` update lands as MXU dots. Sequential
  elimination applied segment-at-a-time: the pivot rows come out holding
  U12 and the live rows A22 - L21 @ U12 — the entire
  ``_install_and_update`` trailing math — without the factored panel ever
  leaving VMEM.

The factored panel's L/U values therefore feed the trailing GEMM in the
same grid; the only HBM traffic is one streamed read+write of the trailing
block (which the unfused GEMM pays too). Tiles left of the panel pass
through untouched; row permutations stay logical (the done-mask scheme of
panel_pallas) and are applied by the caller as one gather, as before.

**The unfused pair** (the fallback when :func:`core.blocked.fused_fits_vmem`
rejects the working set, and the bit-identity reference): the classic
``panel_factor_pallas`` launch followed by :func:`trailing_update_pallas`,
a second kernel applying the identical trailing math from the multiplier/
pivot rows reconstructed — exactly, gathers and selects only — from the
factored panel (:func:`reconstruct_mult_pt`). Fused and unfused share
``_factor_body`` and ``_trailing_tile_update`` verbatim, so their outputs
are bit-identical at matching (seg, fseg, ct) tiles (tested).

Tile/segment axes (``ct``, ``fseg``, ``seg``) are declared in
``tune.space`` (op ``panel_fused``) and consulted through ``tune.apply`` —
seeded with the shipped constants, swept per (n-bucket, dtype, device
kind) by ``gauss-tune``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret
from gauss_tpu.kernels.panel_pallas import _factor_body
from gauss_tpu.tune.space import FUSED_CT_SEED, FUSED_FSEG_SEED


def _trailing_tile_update(t, mult_ref, pt_ref, *, panel, fseg, dtype):
    """Apply the panel's recorded eliminations to one (h, ct) row-major
    trailing tile ``t`` (rows on sublanes, columns on lanes), segment by
    segment. Shared VERBATIM by the fused kernel and
    :func:`trailing_update_pallas` — the bit-identity contract.

    Per segment [s0, s1): with M the (w, h) multiplier rows and P the
    (w, h) pivot one-hots, U0 = P @ T extracts the pivot-row values (exact
    at HIGHEST against one-hot operands), L^T = P M^T is the strictly-lower
    step coupling (L^T[j, i] = M[i, p_j], i < j), and the sequential
    pivot-row recurrence U = U0 - L^T U inverts as
    U = (I - L^T)(I + L^T^2)(I + L^T^4)... U0 — powers of one nilpotent
    matrix commute, so the factored Neumann series is exact in exact
    arithmetic and order-free. The tile then takes T - M^T U on live rows
    and the U values themselves — scattered exactly through the one-hots —
    on the segment's pivot rows (which sequential elimination retires:
    later segments' M is zero there, so they are never touched again).

    **Precision contract (ISSUE 11):** at bfloat16 storage every dot
    accumulates in float32 (``preferred_element_type`` — the MXU's native
    bf16-in/f32-out mode), the Neumann chain stays in f32, and the tile
    rounds ONCE per segment on store; the float32 path is bit-identical
    to the pre-contract code (its accumulate dtype is itself and every
    cast is an identity)."""
    hi = lax.Precision.HIGHEST
    acc = jnp.float32 if dtype == jnp.bfloat16 else dtype  # accumulate
    dn_row = (((1,), (0,)), ((), ()))   # (w, h) x (h, ct) -> (w, ct)
    dn_lan = (((1,), (1,)), ((), ()))   # (w, h) x (w, h) contract h -> (w, w)
    dn_col = (((0,), (0,)), ((), ()))   # (w, h) x (w, ct) contract w -> (h, ct)
    for s0 in range(0, panel, fseg):
        s1 = min(s0 + fseg, panel)
        w = s1 - s0
        ms = mult_ref[pl.ds(s0, w), :]                        # (w, h)
        ps = pt_ref[pl.ds(s0, w), :]                          # (w, h)
        u = lax.dot_general(ps, t, dn_row, precision=hi,
                            preferred_element_type=acc)       # U0 (w, ct)
        lpt = lax.dot_general(ps, ms, dn_lan, precision=hi,
                              preferred_element_type=acc)     # L^T (w, w)
        e = 1
        p2 = None
        while e < w:
            term = lpt if e == 1 else p2
            corr = jnp.dot(term, u, precision=hi,
                           preferred_element_type=acc)
            u = u - corr if e == 1 else u + corr
            if e * 2 < w:
                p2 = jnp.dot(term, term, precision=hi,
                             preferred_element_type=acc)
            e *= 2
        # Rank-fseg application: storage-dtype operands into the MXU,
        # f32 accumulation, one rounding on the tile store below.
        ulow = u.astype(dtype)
        upd = lax.dot_general(ms, ulow, dn_col, precision=hi,
                              preferred_element_type=acc)     # L21-weighted
        uset = lax.dot_general(ps, ulow, dn_col, precision=hi,
                               preferred_element_type=acc)    # U rows placed
        sel = lax.dot_general(ps, jnp.ones((w, 1), dtype), dn_col,
                              precision=hi,
                              preferred_element_type=acc)     # (h, 1) 0/1
        t = jnp.where(sel > 0, uset, t.astype(acc) - upd).astype(dtype)
    return t


def _fused_kernel(scal_ref, pt_in_ref, blk_ref, out_ref, ipiv_ref, inv_ref,
                  minpiv_ref, chosen_ref, blkout_ref, done_ref, mult_ref,
                  ptv_ref, *, h, panel, ct, seg, fseg):
    col0 = scal_ref[0]     # panel's column offset within the block
    kbrow = scal_ref[1]    # panel's diagonal row offset
    i = pl.program_id(0)
    dtype = blk_ref.dtype

    @pl.when(i == 0)
    def _factor():
        _factor_body(kbrow, pt_in_ref, out_ref, ipiv_ref, inv_ref,
                     minpiv_ref, chosen_ref, done_ref, mult_ref, ptv_ref,
                     h=h, panel=panel, seg=seg, defer=False, record=True)

    # Columns at or left of the panel pass through (L multipliers of
    # earlier panels, and the panel's own columns — installed factored by
    # the caller); columns right of it take the recorded eliminations.
    lanes = lax.broadcasted_iota(jnp.int32, (1, ct), 1)
    gcol = i * ct + lanes
    live = gcol >= col0 + panel

    @pl.when((i + 1) * ct > col0 + panel)
    def _update():
        t0 = blk_ref[:]
        t = _trailing_tile_update(t0, mult_ref, ptv_ref, panel=panel,
                                  fseg=fseg, dtype=dtype)
        blkout_ref[:] = jnp.where(live, t, t0)

    @pl.when((i + 1) * ct <= col0 + panel)
    def _copy():
        blkout_ref[:] = blk_ref[:]


def _resolve_tiles(h: int, wtot: int, panel: int, dtype,
                   ct, seg, fseg):
    """Resolve the fused kernel's (ct, seg, fseg) — explicit values are
    honored verbatim; None consults the tuned store (op ``panel_fused``,
    keyed by the block height) and falls back to the tune.space seeds.
    ``ct`` is clamped to a panel multiple that divides the block width (a
    panel-multiple width always admits ct=panel)."""
    from gauss_tpu.tune import apply as _tune

    dt = str(jnp.dtype(dtype))
    if ct is None:
        ct = int(_tune.override("panel_fused", h, "ct", dtype=dt)
                 or FUSED_CT_SEED)
    if seg is None:
        from gauss_tpu.kernels.panel_pallas import DEFAULT_SEG

        seg = int(_tune.override("panel_fused", h, "seg", dtype=dt)
                  or DEFAULT_SEG)
    if fseg is None:
        fseg = int(_tune.override("panel_fused", h, "fseg", dtype=dt)
                   or FUSED_FSEG_SEED)
    ct = max(panel, (min(ct, wtot) // panel) * panel)
    if wtot % ct:
        ct = panel
    return ct, min(max(1, seg), panel), min(max(1, fseg), panel)


@partial(jax.jit, static_argnames=("panel", "ct", "seg", "fseg",
                                   "interpret"))
def panel_trailing_fused_pallas(block, col0, kbrow, *, panel: int,
                                ct: int | None = None,
                                seg: int | None = None,
                                fseg: int | None = None,
                                interpret: bool | None = None):
    """Factor the (h, panel) column block of ``block`` whose columns start
    at ``col0`` and whose diagonal sits at row ``kbrow``, AND apply its
    eliminations to every column right of it — one kernel launch.

    Returns ``(p, ipiv, perm_local, min_abs_pivot, block_upd)``: the
    factored panel already row-permuted (getrf layout, as
    ``panel_factor_pallas`` returns it), the pivot-choice sequence, the
    permutation as gather indices, the singularity witness, and the full
    (h, wtot) block with every trailing column updated — pivot rows
    holding U12, live rows holding A22 - L21 @ U12 — in ORIGINAL row
    order (apply ``perm_local`` as one gather, then install ``p``).
    Columns at or left of ``col0 + panel`` come back untouched.

    ``ct``/``seg``/``fseg`` resolve through the tuned store (tune.space op
    ``panel_fused``) when None. ``col0``/``kbrow`` may be traced."""
    interpret = _auto_interpret(interpret)
    h, wtot = block.shape
    if panel > wtot:
        raise ValueError(f"panel ({panel}) exceeds the block width "
                         f"({wtot}); the fused kernel factors a column "
                         f"block of the operand")
    dtype = block.dtype
    ct, seg, fseg = _resolve_tiles(h, wtot, panel, dtype, ct, seg, fseg)
    scal = jnp.stack([jnp.asarray(col0, jnp.int32),
                      jnp.asarray(kbrow, jnp.int32)])
    # The transposed panel operand, standalone (the optimization barrier
    # keeps the slice+transpose from fusing into the aliased call — the
    # panel_pallas VMEM double-count lesson).
    p_t = lax.optimization_barrier(
        lax.dynamic_slice(block, (jnp.asarray(0, jnp.int32),
                                  jnp.asarray(col0, jnp.int32)),
                          (h, panel)).T)
    block = lax.optimization_barrier(block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(wtot // ct,),
        in_specs=[
            pl.BlockSpec((panel, h), lambda i, s: (0, 0)),
            pl.BlockSpec((h, ct), lambda i, s: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((panel, h), lambda i, s: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, s: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, s: (0, 0)),
            pl.BlockSpec((h, ct), lambda i, s: (0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, h), jnp.int32),      # done mask
            pltpu.VMEM((panel, h), dtype),      # recorded multipliers
            pltpu.VMEM((panel, h), dtype),      # recorded pivot one-hots
        ],
    )
    out_t, ipiv, inv, minpiv, chosen, block_upd = pl.pallas_call(
        partial(_fused_kernel, h=h, panel=panel, ct=ct, seg=seg, fseg=fseg),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((panel, h), dtype),
            jax.ShapeDtypeStruct((panel,), jnp.int32),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
            jax.ShapeDtypeStruct((1,), dtype),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
            jax.ShapeDtypeStruct((h, wtot), dtype),
        ],
        # The transposed panel aliases its factored output (the
        # panel_pallas scheme) and the block aliases its updated output:
        # the trailing stream is in-place, one read + one write of HBM.
        # Operand indices count the scalar-prefetch argument.
        input_output_aliases={1: 0, 2: 5},
        interpret=interpret,
    )(scal, p_t, block)
    perm_local = _perm_from_inv(inv, chosen, jnp.asarray(kbrow, jnp.int32),
                                h, panel)
    return out_t.T[perm_local], ipiv, perm_local, minpiv[0], block_upd


def _perm_from_inv(inv, chosen, kbrow, h: int, panel: int):
    """Gather indices from the kernel's inverse-position vector — the same
    rank-fill scheme as panel_factor_pallas (unchosen rows keep their
    original relative order after the chosen pivots)."""
    rows = jnp.arange(h, dtype=jnp.int32)
    unch = (rows >= kbrow) & (chosen[:, 0] == 0)
    rank = jnp.cumsum(unch.astype(jnp.int32))
    inv = jnp.where(unch, kbrow + panel - 1 + rank, inv[:, 0])
    return jnp.zeros((h,), jnp.int32).at[inv].set(rows)


# -- the unfused pair: reconstruction + standalone trailing kernel ---------


def reconstruct_mult_pt(p_perm, ipiv, perm_local, kbrow, panel: int):
    """The (panel, h) multiplier rows and pivot one-hots of a factored
    panel, reconstructed EXACTLY (gathers, comparisons, and selects only —
    no arithmetic) from ``panel_factor_pallas`` outputs.

    Row ``r`` of the original panel was retired at step
    ``inv[r] - kbrow`` when chosen (``inv`` is the inverse of
    ``perm_local``); its stored value in column j is the multiplier the
    kernel computed at step j exactly when the row was still live there
    (``inv[r] > kbrow + j``), and zero otherwise — the same zero the
    kernel's done-mask wrote."""
    h = p_perm.shape[0]
    rows = jnp.arange(h, dtype=jnp.int32)
    inv = jnp.zeros((h,), jnp.int32).at[perm_local].set(rows)
    p_raw = p_perm[inv]                                      # original order
    steps = jnp.asarray(kbrow, jnp.int32) + jnp.arange(panel,
                                                       dtype=jnp.int32)
    live = inv[None, :] > steps[:, None]                     # (panel, h)
    mult = jnp.where(live, p_raw.T, jnp.zeros((), p_perm.dtype))
    pt = (ipiv[:, None] == rows[None, :]).astype(p_perm.dtype)
    return mult, pt


def _trailing_kernel(scal_ref, mult_ref, pt_ref, blk_ref, blkout_ref, *,
                     h, panel, ct, fseg):
    col0 = scal_ref[0]
    i = pl.program_id(0)
    dtype = blk_ref.dtype
    lanes = lax.broadcasted_iota(jnp.int32, (1, ct), 1)
    live = i * ct + lanes >= col0 + panel

    @pl.when((i + 1) * ct > col0 + panel)
    def _update():
        t0 = blk_ref[:]
        t = _trailing_tile_update(t0, mult_ref, pt_ref, panel=panel,
                                  fseg=fseg, dtype=dtype)
        blkout_ref[:] = jnp.where(live, t, t0)

    @pl.when((i + 1) * ct <= col0 + panel)
    def _copy():
        blkout_ref[:] = blk_ref[:]


@partial(jax.jit, static_argnames=("ct", "fseg", "interpret"))
def trailing_update_pallas(block, mult, pt, col0, *, ct: int | None = None,
                           fseg: int | None = None,
                           interpret: bool | None = None):
    """The trailing half of the pair, as its own launch: apply the
    (panel, h) recorded eliminations ``mult``/``pt`` (from
    :func:`reconstruct_mult_pt`) to every column of ``block`` right of
    ``col0 + panel``. Identical tile math to the fused kernel (shared
    ``_trailing_tile_update``), so fused == factor-launch + this launch,
    bit for bit, at matching (ct, fseg) — the round-trip between the two
    launches is exactly what the fused form deletes."""
    interpret = _auto_interpret(interpret)
    h, wtot = block.shape
    panel = mult.shape[0]
    dtype = block.dtype
    ct, _, fseg = _resolve_tiles(h, wtot, panel, dtype, ct, 1, fseg)
    scal = jnp.asarray(col0, jnp.int32).reshape(1)
    block = lax.optimization_barrier(block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(wtot // ct,),
        in_specs=[
            pl.BlockSpec((panel, h), lambda i, s: (0, 0)),
            pl.BlockSpec((panel, h), lambda i, s: (0, 0)),
            pl.BlockSpec((h, ct), lambda i, s: (0, i)),
        ],
        out_specs=[pl.BlockSpec((h, ct), lambda i, s: (0, i))],
        scratch_shapes=[],
    )
    (out,) = pl.pallas_call(
        partial(_trailing_kernel, h=h, panel=panel, ct=ct, fseg=fseg),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((h, wtot), dtype)],
        input_output_aliases={3: 0},
        interpret=interpret,
    )(scal, mult, pt, block)
    return out
