"""Blocked right-looking Cholesky — the SPD half-price factorization.

An SPD system solved by general LU pays for pivoting it provably does not
need (Cholesky is unconditionally stable on SPD input) and for a full
L *and* U it could have gotten as L and L^T. This engine is the blocked
factorization :mod:`gauss_tpu.core.blocked` runs for LU, restructured for
symmetry:

- per panel: one small dense ``lax.linalg.cholesky`` of the diagonal block
  (the panel factor), one GEMM ``L21 = A21 @ L11^-T`` (against the stored
  explicit inverse — the same TRTRI+GEMM move the LU path uses), and one
  SYRK-shaped trailing update ``A22 -= L21 @ L21^T`` on the MXU;
- no pivot contest, no per-panel whole-matrix permutation gather (the
  single largest non-GEMM cost of the LU loop), no U12 triangular solve;
- identity padding to a panel multiple — an identity extension of an SPD
  matrix is SPD, so the padded factorization is well-posed (the same
  argument :func:`core.blocked._pad_to_panel` makes for LU, without
  needing the pivoting half of it).

Two trace forms mirror the LU policy: a flat ``fori_loop`` with masked
full-size updates (flat compile payload — the CPU/large-n form) and a
trace-time unrolled form whose trailing block genuinely shrinks (true
n^3/3 FLOPs — the TPU form up to ``UNROLL_MAX_N``), resolved by
:func:`resolve_chol_factor`.

Failure is TYPED: a non-SPD operand surfaces inside the factorization as a
non-positive (or NaN) diagonal of some ``L11``; the host entry points check
``min_diag`` once and raise :class:`NotSPDError` — the router's signal to
demote to general LU. Inside jit nothing raises (the NaN-as-0 fold makes
``min_diag`` the witness), same contract as ``BlockedLU.min_abs_pivot``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np


class NotSPDError(RuntimeError):
    """The Cholesky factorization found a non-positive pivot: the matrix is
    not positive definite (or not symmetric enough to pretend). ``min_diag``
    carries the witness value (0.0 stands in for NaN)."""

    def __init__(self, message: str, min_diag: float = 0.0):
        super().__init__(message)
        self.min_diag = min_diag


class BlockedCholesky(NamedTuple):
    """A = L @ L^T factorization state (identity-padded to a panel multiple).

    m:    (npad, npad); L on and below the diagonal. Entries above the
          diagonal are untouched input (never read by the solve — the
          blockwise substitution's zero-meets argument masks them for free).
    linv: (nb, panel, panel) explicit inverses of the diagonal L blocks, so
          both substitution sweeps run as GEMM chains (cf. BlockedLU.linv).
    min_diag: min over the diagonal of L; <= 0 means not SPD (NaN folds
          to 0 so the witness is always comparable).
    abft_err: only set by the ``abft=True`` checksum-carrying form — the
          per-panel column-checksum mismatch magnitudes plus the final
          ``e^T A = (e^T L) L^T`` identity (cf. BlockedLU.abft_err).
    """

    m: object
    linv: object
    min_diag: object
    abft_err: object = None


def _chol_panel(d, panel: int, dtype):
    """Factor one (panel, panel) diagonal block: L11, its inverse, and the
    block's min diagonal (NaN -> 0). Single source for both trace forms."""
    import jax.numpy as jnp
    from jax import lax

    l11 = lax.linalg.cholesky(d)
    dg = jnp.diagonal(l11)
    dg = jnp.where(jnp.isnan(dg), jnp.zeros((), dtype), dg)
    mind = jnp.min(dg)
    # A non-SPD block yields NaNs; zero them so min_diag stays the single
    # witness and downstream GEMMs cannot spray NaN past the typed check.
    l11 = jnp.where(jnp.isnan(l11), jnp.zeros((), dtype), l11)
    linv = lax.linalg.triangular_solve(
        l11 + jnp.eye(panel, dtype=dtype) * (mind <= 0).astype(dtype),
        jnp.eye(panel, dtype=dtype), left_side=True, lower=True)
    return l11, linv, mind


def cholesky_factor_blocked(a, panel: int | None = None,
                            gemm_precision: str = "highest",
                            abft: bool = False):
    """Flat-fori blocked Cholesky (jitted; masked full-size updates).

    Returns a :class:`BlockedCholesky`; never raises on non-SPD input —
    check ``min_diag`` (the host entries :func:`cholesky_factor` /
    :func:`solve_spd_refined` do, and raise :class:`NotSPDError`).

    ``abft``: carry the Huang-Abraham column-checksum row (for Cholesky
    the update is ``c' = c - (c1 @ L11^-T) @ L21^T`` — the symmetric
    analog of the LU rider, see ``core.blocked``'s ABFT block) and verify
    the trailing block after every panel; mismatch magnitudes return in
    ``BlockedCholesky.abft_err`` ((nb + 1,), last entry the whole-factor
    ``e^T A = (e^T L) L^T`` identity). The factor arrays are bit-identical
    to ``abft=False``, and the off path traces the pre-ABFT program.
    """
    return _cholesky_factor_fori(a, panel=panel,
                                 gemm_precision=gemm_precision, abft=abft)


def _chol_panel_step(m, min_diag, kb, panel: int, prec, crow=None):
    """One panel of the flat (masked) blocked Cholesky: factor the diagonal
    block at ``kb``, install L11/L21, apply the self-masking SYRK trailing
    update — and, when an ABFT checksum row ``crow`` rides along, its
    symmetric-rider update plus the trailing-block verification. Returns
    ``(m, min_diag, linv, crow, err)`` (``crow``/``err`` None when off).
    Single source for the fori body below and the host-stepped ABFT
    runner (gauss_tpu.resilience.abft) — they must stay in numerical
    lockstep; ``kb`` may be traced (fori) or static (runner)."""
    import jax.numpy as jnp
    from jax import lax

    dtype = m.dtype
    npad = m.shape[0]
    rows = jnp.arange(npad)
    d = lax.dynamic_slice(m, (kb, kb), (panel, panel))
    l11, linv, mind = _chol_panel(d, panel, dtype)
    min_diag = jnp.minimum(min_diag, mind)
    # L21 = A21 @ L11^-T, masked to the rows below the panel; the masked
    # operand makes the SYRK update self-masking (the outer product is
    # zero outside the trailing block).
    colblk = lax.dynamic_slice(m, (0, kb), (npad, panel))
    below = (rows >= kb + panel)[:, None]
    l21 = jnp.dot(jnp.where(below, colblk, jnp.zeros((), dtype)),
                  linv.T, precision=prec)
    in_panel = ((rows >= kb) & (rows < kb + panel))[:, None]
    l11_full = jnp.zeros((npad, panel), dtype)
    l11_full = lax.dynamic_update_slice(l11_full, l11, (kb, 0))
    colblk = jnp.where(in_panel, l11_full,
                       jnp.where(below, l21, colblk))
    m = lax.dynamic_update_slice(m, colblk, (0, kb))
    m = m - jnp.dot(l21, l21.T, precision=prec)
    err = None
    if crow is not None:
        # Symmetric checksum rider: s = c1 @ L11^-T is e^T [L11; L21]
        # (the checksum row's "multipliers"), and the trailing checksum
        # update is s @ L21^T — the rider of the SYRK above. The check
        # reads the symmetrized-from-lower trailing view (what the
        # algorithm reads; see _csum_sym_init).
        c1 = lax.dynamic_slice(crow, (0, kb), (1, panel))
        s = jnp.dot(c1, linv.T, precision=prec)
        crow = crow - jnp.dot(s, l21.T, precision=prec)
        err, _ = _csum_sym_trailing_err(m, crow, kb + panel)
        # Panel-column identity: c1 == (e^T [L11; L21]) @ L11^T — exact in
        # the corruption, where the trailing check only sees panel-column
        # corruption through L11^-T-attenuated propagation (cf.
        # core.blocked._csum_group_col_err).
        el = jnp.sum(jnp.where((rows >= kb)[:, None], colblk,
                               jnp.zeros((), dtype)), axis=0)
        pred = jnp.dot(el[None, :], l11.T, precision=prec)
        gdiff = pred[0] - c1[0]
        gdiff = jnp.where(jnp.isnan(gdiff), jnp.inf, jnp.abs(gdiff))
        err = jnp.maximum(err, jnp.max(gdiff))
    return m, min_diag, linv, crow, err


def _csum_sym_init(m):
    """Initial Cholesky checksum row: column sums of the SYMMETRIZED-from-
    lower view ``tril(m) + tril(m, -1)^T`` — the matrix the factorization
    actually reads (potrf never touches the strict upper triangle). On a
    symmetric operand this equals the plain column sums to rounding; on an
    asymmetric one it keeps the checksum consistent with the computation,
    so a non-SPD operand fails as NotSPD / residual-gate demotion exactly
    like the plain engine instead of masquerading as unrepairable SDC."""
    import jax.numpy as jnp

    npad = m.shape[0]
    rows = jnp.arange(npad)
    lower = rows[:, None] >= rows[None, :]
    lt = jnp.where(lower, m, jnp.zeros((), m.dtype))
    strict = jnp.where(rows[:, None] > rows[None, :], m,
                       jnp.zeros((), m.dtype))
    return (jnp.sum(lt, axis=0) + jnp.sum(strict, axis=1))[None, :]


def _csum_sym_trailing_err(m, crow, split):
    """Trailing-block checksum check over the symmetrized-from-lower view
    (cf. core.blocked._csum_trailing_err; ``split`` may be traced). A flip
    in the trailing LOWER triangle perturbs two column sums at its own
    magnitude; the never-read strict upper triangle is — correctly —
    invisible (dead memory)."""
    import jax.numpy as jnp

    npad = m.shape[0]
    rows = jnp.arange(npad)
    live = rows >= split
    live2 = live[:, None] & live[None, :]
    lower = rows[:, None] >= rows[None, :]
    lt = jnp.where(live2 & lower, m, jnp.zeros((), m.dtype))
    strict = jnp.where(live2 & (rows[:, None] > rows[None, :]), m,
                       jnp.zeros((), m.dtype))
    colsum = jnp.sum(lt, axis=0) + jnp.sum(strict, axis=1)
    diff = jnp.where(live, colsum - crow[0], jnp.zeros((), m.dtype))
    diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
    return jnp.max(diff), jnp.argmax(diff)


def _csum_final_err_chol(m, crow0):
    """The post-factor identity ``e^T A = (e^T L) @ L^T`` — the symmetric
    analog of core.blocked._csum_final_err_lu (column sums of the padded
    SPD operand vs the L-column-sum-weighted rows of L^T)."""
    import jax.numpy as jnp
    from jax import lax

    npad = m.shape[0]
    rows = jnp.arange(npad)
    lower = rows[:, None] >= rows[None, :]
    lt = jnp.where(lower, m, jnp.zeros((), m.dtype))
    el = jnp.sum(lt, axis=0)
    pred = jnp.dot(el[None, :], lt.T, precision=lax.Precision.HIGHEST)
    diff = pred[0] - crow0[0]
    diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
    return jnp.max(diff), jnp.argmax(diff)


def _factor_impl(a, panel, gemm_precision, unrolled: bool,
                 abft: bool = False):
    import jax.numpy as jnp
    from jax import lax

    from gauss_tpu.core import blocked
    from gauss_tpu.kernels.matmul_pallas import resolve_precision

    prec = resolve_precision(gemm_precision)
    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    panel = blocked._resolve_panel(n, panel, itemsize)
    m = blocked._pad_to_panel(a, panel)
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype

    if unrolled:
        if abft:
            raise ValueError("abft=True is supported on the flat fori form "
                             "(cholesky_factor_blocked) and the host-stepped "
                             "ABFT runner, not the unrolled trace form")
        min_diag = jnp.asarray(jnp.inf, dtype)
        linvs = []
        for kb in range(0, npad, panel):
            d = m[kb:kb + panel, kb:kb + panel]
            l11, linv, mind = _chol_panel(d, panel, dtype)
            min_diag = jnp.minimum(min_diag, mind)
            linvs.append(linv)
            m = m.at[kb:kb + panel, kb:kb + panel].set(l11)
            if kb + panel < npad:
                a21 = m[kb + panel:, kb:kb + panel]
                l21 = jnp.dot(a21, linv.T, precision=prec)
                m = m.at[kb + panel:, kb:kb + panel].set(l21)
                trail = m[kb + panel:, kb + panel:]
                m = m.at[kb + panel:, kb + panel:].set(
                    trail - jnp.dot(l21, l21.T, precision=prec))
        return BlockedCholesky(m=m, linv=jnp.stack(linvs), min_diag=min_diag)

    def outer(k, carry):
        if abft:
            m, min_diag, linvs, crow, errs = carry
        else:
            m, min_diag, linvs = carry
        kb = k * panel
        m, min_diag, linv, crow, err = _chol_panel_step(
            m, min_diag, kb, panel, prec,
            crow=crow if abft else None)
        # The panel's own rows/cols met a zero operand in the step, so only
        # the trailing block actually changed — restore nothing.
        linvs = lax.dynamic_update_slice(linvs, linv[None], (k, 0, 0))
        if abft:
            errs = lax.dynamic_update_slice(errs, err[None], (k,))
            return m, min_diag, linvs, crow, errs
        return m, min_diag, linvs

    init = (m, jnp.asarray(jnp.inf, dtype),
            jnp.zeros((nb, panel, panel), dtype))
    if abft:
        crow0 = _csum_sym_init(m)
        m, min_diag, linvs, _, errs = lax.fori_loop(
            0, nb, outer, init + (crow0, jnp.zeros((nb,), dtype)))
        fe, _ = _csum_final_err_chol(m, crow0)
        return BlockedCholesky(m=m, linv=linvs, min_diag=min_diag,
                               abft_err=jnp.concatenate([errs, fe[None]]))
    m, min_diag, linvs = lax.fori_loop(0, nb, outer, init)
    return BlockedCholesky(m=m, linv=linvs, min_diag=min_diag)


_JITTED = {}


def _get_jitted(unrolled: bool):
    """jit lazily so importing this module never imports jax."""
    fn = _JITTED.get(unrolled)
    if fn is None:
        import jax

        fn = jax.jit(partial(_factor_impl, unrolled=unrolled),
                     static_argnames=("panel", "gemm_precision", "abft"))
        _JITTED[unrolled] = fn
    return fn


def _cholesky_factor_fori(a, panel=None, gemm_precision="highest",
                          abft=False):
    return _get_jitted(False)(a, panel=panel, gemm_precision=gemm_precision,
                              abft=abft)


def cholesky_factor_blocked_unrolled(a, panel: int | None = None,
                                     gemm_precision: str = "highest"):
    """Trace-time unrolled blocked Cholesky: the trailing block genuinely
    shrinks (true n^3/3 FLOPs, no masks) at the cost of one traced GEMM
    shape per panel — the same trade as ``lu_factor_blocked_unrolled``."""
    return _get_jitted(True)(a, panel=panel, gemm_precision=gemm_precision)


def resolve_chol_factor(n: int, unroll="auto"):
    """Factor-form policy, mirroring :func:`core.blocked.resolve_factor`:
    unrolled on TPU up to the LU unroll ceiling (true triangular work),
    flat fori everywhere else (flat compile payload)."""
    import jax

    from gauss_tpu.core import blocked

    if unroll == "auto":
        if (jax.default_backend() == "tpu"
                and n <= blocked.UNROLL_MAX_N):
            return cholesky_factor_blocked_unrolled
        return cholesky_factor_blocked
    if isinstance(unroll, str):
        raise ValueError(f"unknown unroll {unroll!r}; options: "
                         "(True, False, 'auto')")
    return (cholesky_factor_blocked_unrolled if unroll
            else cholesky_factor_blocked)


def cholesky_factor(a, panel: int | None = None, unroll="auto",
                    gemm_precision: str = "highest") -> BlockedCholesky:
    """Host entry: factor and CHECK — raises :class:`NotSPDError` when the
    factorization's min diagonal is not strictly positive."""
    fac = resolve_chol_factor(np.shape(a)[0], unroll)(
        a, panel=panel, gemm_precision=gemm_precision)
    mind = float(np.asarray(fac.min_diag))
    if not mind > 0.0:
        raise NotSPDError(
            f"matrix is not positive definite (Cholesky min diagonal "
            f"{mind:g}); route to general LU", min_diag=mind)
    return fac


def cholesky_solve(fac: BlockedCholesky, b):
    """Solve A x = b given A = L L^T: forward then transposed substitution,
    both blockwise through the stored diagonal-block inverses — the
    LU path's scan form (`core.blocked._blockwise_substitution_scan`)
    reused verbatim: the backward sweep is the forward machinery run on
    ``m.T`` with the transposed inverses (L^T's stale lower triangle meets
    the still-zero solution region, the same zero-meets argument)."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    m = fac.m
    npad = m.shape[0]
    nb, panel, _ = fac.linv.shape
    b = jnp.asarray(b, dtype=m.dtype)
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    if b2.ndim != 2:
        raise ValueError(f"b must be (n,) or (n, k), got {b.shape}")
    n, k = b2.shape
    bp = jnp.zeros((npad, k), dtype=m.dtype).at[:n].set(b2)
    linv_t = jnp.swapaxes(fac.linv, 1, 2)
    y = blocked._blockwise_substitution_scan(m, fac.linv, bp, lower=True)
    x = blocked._blockwise_substitution_scan(m.T, linv_t, y, lower=False)
    x = x[:n]
    return x[:, 0] if was_vector else x


def solve_spd(a, b, panel: int | None = None, unroll="auto"):
    """One f32-native factor + solve (no refinement); raises
    :class:`NotSPDError` on non-SPD input. The structured sibling of
    ``gauss_solve_blocked``."""
    fac = cholesky_factor(a, panel=panel, unroll=unroll)
    return cholesky_solve(fac, b)


def solve_spd_refined(a, b, panel: int | None = None, iters: int = 2,
                      dtype=None, unroll="auto", tol: float = 0.0):
    """Mixed-precision SPD solve: f32 blocked Cholesky + host-f64 iterative
    refinement — the product path, mirroring ``blocked.solve_refined``
    contract for contract (x float64, ``(x, factors)`` return, ``tol``
    early-exit). Raises :class:`NotSPDError` before any refinement work
    when the factorization rejects the operand."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    a64 = np.asarray(a, dtype=np.float64)
    b64 = np.asarray(b, dtype=np.float64)
    fac = cholesky_factor(jnp.asarray(a64, dtype), panel=panel,
                          unroll=unroll)
    x = np.asarray(cholesky_solve(fac, jnp.asarray(b64, dtype)),
                   dtype=np.float64)
    tol_eff = tol * min(1.0, float(np.linalg.norm(b64))) if tol > 0.0 else 0.0
    for _ in range(iters):
        r = b64 - a64 @ x
        if tol > 0.0 and float(np.linalg.norm(r)) <= tol_eff:
            break
        d = np.asarray(cholesky_solve(fac, jnp.asarray(r, dtype)),
                       dtype=np.float64)
        x = x + d
    return x, fac


def solve_spd_ds(a, b, iters: int | None = None, panel: int | None = None,
                 unroll="auto"):
    """Fully on-device SPD solve: f32 Cholesky + double-single refinement
    (``core.dsfloat.refine_ds`` with this engine's solve threaded in) —
    residuals never leave the device, the device-span timing form.
    Returns ``(x float64, factors)``; raises :class:`NotSPDError`."""
    import jax.numpy as jnp

    from gauss_tpu.core import dsfloat

    if iters is None:
        iters = dsfloat.DS_REFINE_STEPS
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    fac = cholesky_factor(jnp.asarray(a64, jnp.float32), panel=panel,
                          unroll=unroll)
    b_ds = dsfloat.to_ds(b64)
    x0 = cholesky_solve(fac, b_ds.hi)
    x = dsfloat.refine_ds(fac, dsfloat.to_ds(a64.T), b_ds, x0, iters=iters,
                          solve_fn=cholesky_solve)
    return dsfloat.ds_to_f64(x), fac
