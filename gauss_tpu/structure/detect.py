"""Cheap structure classification for square systems.

One O(n^2) numpy scan of an in-memory matrix — or one O(nnz log nnz) pass
over a ``.dat`` coordinate stream, where the structure is visible *for free*
before anything is densified — produces a :class:`StructureInfo`:

- **symmetric**: exact elementwise ``A == A.T``. Exact on purpose: routing
  to Cholesky is only *correct* for symmetric matrices, and a near-SPD
  non-symmetric perturbation must classify dense (the router's demotion
  ladder exists for the cases detection refuses to bless).
- **spd_likely**: symmetric, positive diagonal, and every Gershgorin disc
  strictly inside the positive half-line (``a_ii > sum_{j != i} |a_ij|``).
  For a symmetric matrix that is a *proof* of positive definiteness, not a
  heuristic — the detector never certifies SPD on a hunch. Symmetric
  systems that fail Gershgorin can still be SPD; the router covers them
  with a *verified Cholesky attempt*: the factorization itself is the test
  (typed :class:`gauss_tpu.structure.cholesky.NotSPDError` demotes to LU).
- **bandwidth**: max |i - j| over nonzeros (0 = diagonal, n-1 = full).
- **blocks**: the contiguous block-diagonal partition — maximal prefix
  points k where no nonzero couples rows/cols <= k with rows/cols > k.
  A *permuted* block-diagonal matrix is deliberately NOT detected (the
  partition is only cheap for the contiguous layout; general symmetric
  permutation detection is a graph problem this classifier does not
  pretend to solve) — it classifies dense and takes general LU.
- **density**: nnz / n^2.

``kind`` is the routing class with precedence blockdiag > banded > sparse
> spd > dense: a block-diagonal matrix is also banded and possibly SPD,
but the batched small-block solve beats both; a banded SPD matrix takes
the O(n b^2) band engine over the O(n^3/3) Cholesky; and a matrix at or
below :data:`SPARSE_MAX_DENSITY` (with ``n >= SPARSE_MIN_N``) routes to
the matrix-free Krylov plane (``gauss_tpu.sparse``) whether or not it is
SPD — the certificate only picks WHICH Krylov head (CG vs GMRES).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

#: routing classes, in router/inject tag order (inject kind="mistag" indexes
#: this tuple via its float ``param``; "sparse" appended LAST so historical
#: mistag indices stay stable)
STRUCTURE_KINDS = ("spd", "banded", "blockdiag", "dense", "sparse")

#: a matrix is routed banded only when its bandwidth is at most n // this —
#: past that the n*b^2 band solve loses its margin over blocked LU (and the
#: unpivoted band factorization its numerical headroom)
BANDED_MAX_DIVISOR = 8

#: minimum number of contiguous diagonal blocks for the batched route
BLOCKDIAG_MIN_BLOCKS = 2

# Density at or below which a system routes to the sparse Krylov plane
# (gauss_tpu.sparse). Sourced from the declared tune axis so the routing
# boundary and the tuner's "sparse" op can never drift apart.
from gauss_tpu.tune.space import SPARSE_DENSITY_SEED as SPARSE_MAX_DENSITY  # noqa: E402

#: below this order the dense engines win outright (one small dispatch vs
#: staging + iteration), so low density alone never routes sparse — which
#: also keeps every historical small-n classification byte-stable.
SPARSE_MIN_N = 256


class StructureMismatchError(RuntimeError):
    """An engine was handed a matrix without the structure it requires
    (e.g. the banded rung on a full-bandwidth matrix, the block-diagonal
    rung on an unpartitionable one). Typed so the recovery ladder can
    demote to general LU instead of wasting a doomed factorization."""


@dataclasses.dataclass(frozen=True)
class StructureInfo:
    """What one scan learned about a square matrix."""

    n: int
    symmetric: bool
    spd_likely: bool          # Gershgorin-certified positive definite
    bandwidth: int            # max |i - j| over nonzeros
    blocks: Tuple[int, ...]   # contiguous diagonal-block partition sizes
    density: float            # nnz / n^2

    @property
    def kind(self) -> str:
        """Routing class: blockdiag > banded > sparse > spd > dense.
        Sparse sits below the exact-structure classes (a sparse banded
        matrix still wants the O(n b^2) direct factor over iteration)
        and above spd (a certified-SPD matrix at sparse density wants CG,
        not an n^3/3 Cholesky it cannot even allocate at scale)."""
        n = self.n
        if n <= 1:
            return "dense"  # trivial systems route straight through
        if len(self.blocks) >= BLOCKDIAG_MIN_BLOCKS:
            return "blockdiag"
        if self.bandwidth <= max(1, n // BANDED_MAX_DIVISOR):
            return "banded"
        if n >= SPARSE_MIN_N and 0.0 < self.density <= SPARSE_MAX_DENSITY:
            return "sparse"
        if self.spd_likely:
            return "spd"
        return "dense"


def _partition_from_reach(reach: np.ndarray) -> Tuple[int, ...]:
    """Block sizes from the per-index coupling reach: a block ends at k when
    no index <= k couples past k (running max of reach equals k)."""
    n = reach.shape[0]
    if n == 0:
        return ()
    running = np.maximum.accumulate(reach)
    ends = np.nonzero(running == np.arange(n))[0]
    starts = np.concatenate([[0], ends[:-1] + 1])
    return tuple(int(e - s + 1) for s, e in zip(starts, ends))


def detect_structure(a) -> StructureInfo:
    """Classify an in-memory square matrix (one O(n^2) numpy pass)."""
    a = np.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    if n == 0:
        return StructureInfo(n=0, symmetric=True, spd_likely=False,
                             bandwidth=0, blocks=(), density=0.0)
    nz = a != 0
    nnz = int(nz.sum())
    density = nnz / float(n * n)
    symmetric = bool(np.array_equal(a, a.T))
    diag = np.diagonal(a).astype(np.float64, copy=False)
    off = np.abs(a).sum(axis=1, dtype=np.float64) - np.abs(diag)
    spd_likely = bool(symmetric and (diag > off).all() and (diag > 0).all())
    idx = np.arange(n)
    if nnz:
        # Furthest column each row touches / furthest row each column
        # touches; -1 where empty so the arange floor wins.
        col_of = np.where(nz, idx[None, :], -1)
        row_of = np.where(nz, idx[:, None], -1)
        row_reach = col_of.max(axis=1)
        col_reach = row_of.max(axis=0)
        reach = np.maximum(np.maximum(row_reach, col_reach), idx)
        rows, cols = np.nonzero(a)
        bandwidth = int(np.abs(rows - cols).max())
    else:
        reach = idx
        bandwidth = 0
    return StructureInfo(n=n, symmetric=symmetric, spd_likely=spd_likely,
                         bandwidth=bandwidth,
                         blocks=_partition_from_reach(reach),
                         density=density)


def detect_structure_coords(n: int, rows, cols, vals) -> StructureInfo:
    """Classify from 0-indexed coordinate entries without densifying —
    byte-for-byte the same :class:`StructureInfo` :func:`detect_structure`
    computes from the densified matrix (asserted in tests). Duplicate
    coordinates are the caller's problem (the strict ``.dat`` reader
    already rejects them); explicit zeros are ignored, matching the dense
    scan's ``a != 0`` mask."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float64)
    if n == 0:
        return StructureInfo(n=0, symmetric=True, spd_likely=False,
                             bandwidth=0, blocks=(), density=0.0)
    keep = vals != 0
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    nnz = int(rows.size)
    density = nnz / float(n * n)
    # Symmetry: the (r, c)-sorted stream must equal the (c, r)-sorted one.
    o1 = np.lexsort((cols, rows))
    o2 = np.lexsort((rows, cols))
    symmetric = bool(np.array_equal(rows[o1], cols[o2])
                     and np.array_equal(cols[o1], rows[o2])
                     and np.array_equal(vals[o1], vals[o2]))
    diag = np.zeros(n, dtype=np.float64)
    dmask = rows == cols
    diag[rows[dmask]] = vals[dmask]
    off = np.zeros(n, dtype=np.float64)
    np.add.at(off, rows[~dmask], np.abs(vals[~dmask]))
    spd_likely = bool(symmetric and (diag > off).all() and (diag > 0).all())
    bandwidth = int(np.abs(rows - cols).max()) if nnz else 0
    reach = np.arange(n)
    if nnz:
        far = np.maximum(rows, cols)
        np.maximum.at(reach, rows, far)
        np.maximum.at(reach, cols, far)
    return StructureInfo(n=n, symmetric=symmetric, spd_likely=spd_likely,
                         bandwidth=bandwidth,
                         blocks=_partition_from_reach(reach),
                         density=density)


def detect_structure_dat(path_or_file, strict: bool = True) -> StructureInfo:
    """Classify a ``.dat`` file straight from its coordinate stream — the
    structure is decided before anything is densified, so a serving/dataset
    path can route by it at parse time for free."""
    from gauss_tpu.io.datfile import read_dat

    n, rows, cols, vals = read_dat(path_or_file, strict=strict)
    return detect_structure_coords(n, rows, cols, vals)


def structure_tag(a) -> str:
    """Shorthand: the routing class of ``a`` (one detection pass)."""
    return detect_structure(a).kind
