"""Block-diagonal solves: one vmap-batched device dispatch per size class.

A block-diagonal system is n/s independent (s, s) solves wearing one (n, n)
coat — running it through the dense path serializes work that is
embarrassingly batchable. This engine strips the coat: the contiguous
diagonal blocks are stacked into a (batch, s, s) operand and solved by ONE
``vmap``-batched blocked-LU dispatch — exactly the MAGMA-batched execution
shape the serving layer already compiles, so the executables come from the
SAME :class:`gauss_tpu.serve.cache.ExecutableCache` the server uses
(bucketed shapes, LRU, compile-once), not a private second cache.

Blocks are identity-extension padded to power-of-two bucket sizes
(``serve.buckets.pad_system`` — preserves solvability, solution tail
exactly zero) and grouped by bucket; a uniform partition (the common case,
e.g. 64 blocks of 32) is a single dispatch. Refinement is the batched
host-f64 kind ``BatchedExecutable.solve`` already implements.

Mis-tagged operands raise the typed
:class:`gauss_tpu.structure.detect.StructureMismatchError` (the recovery
ladder's demotion signal): entries OFF the promised partition would be
silently dropped, and silently dropping matrix entries is how wrong
answers are born.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from gauss_tpu.structure.detect import StructureMismatchError, \
    detect_structure

#: executable-cache capacity for the block lane (shapes are tiny and
#: bucketed, so a handful of entries covers a whole workload)
CACHE_CAPACITY = 16

_cache = None
_cache_lock = threading.Lock()


def _exe_cache():
    """The lazily-built module cache (the serve layer's cache class — one
    implementation of compile-once batched lanes, not two)."""
    global _cache
    with _cache_lock:
        if _cache is None:
            from gauss_tpu.serve.cache import ExecutableCache

            _cache = ExecutableCache(CACHE_CAPACITY)
        return _cache


def block_partition(a) -> Tuple[int, ...]:
    """The contiguous diagonal-block partition of ``a`` (one detect scan)."""
    return detect_structure(a).blocks


def solve_blockdiag(a, b, blocks: Optional[Sequence[int]] = None,
                    refine_steps: int = 1,
                    require_blocks: int = 2) -> np.ndarray:
    """Solve a block-diagonal system by batched small-block dispatches.

    ``blocks``: the partition sizes (detected when None). A partition that
    does not cover the matrix — off-partition nonzeros, wrong total — or
    one with fewer than ``require_blocks`` blocks raises the typed
    :class:`StructureMismatchError`. Returns x float64 with ``b``'s shape.
    """
    from gauss_tpu.serve import buckets
    from gauss_tpu.serve.cache import CacheKey

    a = np.asarray(a, dtype=np.float64)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    b = np.asarray(b, dtype=np.float64)
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    k = b2.shape[1]

    detected = block_partition(a)
    if blocks is None:
        blocks = detected
    blocks = tuple(int(s) for s in blocks)
    if sum(blocks) != n:
        raise StructureMismatchError(
            f"block partition {blocks} does not cover n={n}")
    # The promised partition must COARSEN the detected (finest) one: every
    # promised boundary must be a real decoupling point, or off-block
    # entries would be dropped. (The detected partition is the finest, so
    # its boundary set is the superset of every valid partition's.)
    starts = np.cumsum((0,) + blocks[:-1])
    det_bounds = set(np.cumsum(detected))
    bad = [int(s + w) for s, w in zip(starts, blocks)
           if int(s + w) not in det_bounds]
    if bad:
        raise StructureMismatchError(
            f"matrix couples across the promised block boundaries at "
            f"{bad[:4]}; not block-diagonal under this partition")
    if len(blocks) < require_blocks:
        raise StructureMismatchError(
            f"only {len(blocks)} diagonal block(s); the batched route "
            f"needs >= {require_blocks} — use the dense path")

    nrhs_b = buckets.pow2_bucket(k)
    x = np.empty((n, k), dtype=np.float64)
    # Group blocks by bucketed size: a uniform partition is ONE dispatch.
    by_bucket = {}
    for s, w in zip(starts, blocks):
        by_bucket.setdefault(buckets.pow2_bucket(w), []).append((int(s), w))
    cache = _exe_cache()
    for bucket_n, members in sorted(by_bucket.items()):
        batch_b = buckets.pow2_bucket(len(members))
        key = CacheKey(bucket_n=bucket_n, nrhs=nrhs_b, batch=batch_b,
                       dtype="float32", engine="blockdiag",
                       refine_steps=refine_steps, mesh=None)
        a_pad = np.broadcast_to(
            np.eye(bucket_n), (batch_b, bucket_n, bucket_n)).copy()
        b_pad = np.zeros((batch_b, bucket_n, nrhs_b))
        for i, (s, w) in enumerate(members):
            a_pad[i], b_pad[i] = buckets.pad_system(
                a[s:s + w, s:s + w], b2[s:s + w], bucket_n, nrhs_b)
        exe = cache.get(key)
        xb = exe.solve(a_pad, b_pad)
        for i, (s, w) in enumerate(members):
            x[s:s + w] = buckets.unpad_solution(xb[i], w, k,
                                                was_vector=False)
    return x[:, 0] if was_vector else x
