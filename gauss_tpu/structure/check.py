"""Structured-solve smoke gate: ``python -m gauss_tpu.structure.check``.

Runs detect -> route -> engine -> verify end to end for every structure
class the router knows (SPD, banded, block-diagonal, dense), on the
deterministic generators the matrix_gen CLI ships, and asserts:

- the detector classifies each generator into its class;
- ``solve_auto`` routes to the class's engine WITHOUT demotion;
- every solution passes the 1e-4 relative-residual gate (verified here,
  independently of the ladder's own gate).

The summary (``--summary-json``) is regress-ingestable
(``kind: structured_solve``): per class, seconds per solve and the
structured engine's FLOP ratio vs dense LU (structured / dense — LOWER is
better, so the slow-side sentinel gates a routing regression exactly like
a perf regression: a class silently demoting to LU shows up as
flops_ratio jumping to 1.0). ``make structure-check`` runs the CPU
configuration CI gates on.

Exit status: 2 when any class fails verification or routes to the wrong
engine, 1 when ``--regress-check`` finds an out-of-band metric, 0
otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms


def dense_lu_flops(n: int) -> float:
    """The general path's factor cost: ~2/3 n^3."""
    return (2.0 / 3.0) * n ** 3


def structured_flops(kind: str, n: int, bandwidth: int = 1,
                     block: int = 32) -> float:
    """The structured engine's factor cost model per class: Cholesky
    ~n^3/3, band LU ~3 n b^2, block-diagonal ~(n/s) * 2/3 s^3."""
    if kind == "spd":
        return n ** 3 / 3.0
    if kind == "banded":
        return 3.0 * n * max(1, bandwidth) ** 2
    if kind == "blockdiag":
        nb = -(-n // block)
        return nb * (2.0 / 3.0) * block ** 3
    return dense_lu_flops(n)


def run_class(kind: str, a: np.ndarray, seed: int, gate: float,
              repeats: int) -> Dict:
    """Solve one class's system ``repeats`` times through solve_auto;
    returns its summary row (best wall-clock, engine, residual)."""
    from gauss_tpu.structure import detect_structure, solve_auto
    from gauss_tpu.structure.router import ENGINE_FOR_TAG
    from gauss_tpu.verify import checks

    n = a.shape[0]
    rng = np.random.default_rng(np.random.SeedSequence((seed, n)))
    b = rng.standard_normal(n)
    info = detect_structure(a)
    best = None
    res = None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        res = solve_auto(a, b, info=info, gate=gate)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    rel = checks.residual_norm(a, res.x, b, relative=True)
    return {
        "n": n, "detected": info.kind, "expected": kind,
        "engine": res.rung, "demoted": bool(res.rung_index > 0),
        "s_per_solve": round(best, 6),
        "rel_residual": float(rel),
        "verified": bool(np.isfinite(rel) and rel <= gate),
        "routed_ok": (info.kind == kind
                      and res.rung == ENGINE_FOR_TAG[kind]),
        "bandwidth": info.bandwidth, "blocks": len(info.blocks),
        "flops_ratio": round(
            structured_flops(kind, n, info.bandwidth,
                             max(info.blocks) if info.blocks else n)
            / dense_lu_flops(n), 6),
    }


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records for the regression history — s_per_solve
    and the flops ratio per class, both slow-side-gated (a class demoting
    to dense LU raises BOTH)."""
    out: List[Tuple[str, float, str]] = []
    for kind, row in (summary.get("classes") or {}).items():
        if isinstance(row.get("s_per_solve"), (int, float)):
            out.append((f"structure:{kind}/s_per_solve",
                        row["s_per_solve"], "s"))
        if isinstance(row.get("flops_ratio"), (int, float)):
            out.append((f"structure:{kind}/flops_ratio",
                        row["flops_ratio"], "ratio"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.structure.check",
        description="Structured-solve smoke gate: detect -> route -> "
                    "engine -> 1e-4 verify across all four structure "
                    "classes (the make structure-check CI configuration).")
    p.add_argument("--spd-n", type=int, default=96)
    p.add_argument("--banded-n", type=int, default=512)
    p.add_argument("--banded-bw", type=int, default=1)
    p.add_argument("--blockdiag-n", type=int, default=96)
    p.add_argument("--block", type=int, default=16,
                   help="block size for the block-diagonal class")
    p.add_argument("--dense-n", type=int, default=96)
    p.add_argument("--repeats", type=int, default=3,
                   help="timed solves per class (best-of; the first rep "
                        "pays the jit compile, so >= 2 is meaningful)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="append the run's obs JSONL stream here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the regress-ingestable summary "
                        "(kind=structured_solve)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate against the history baselines (exit 1 when "
                        "out of band)")
    p.add_argument("--band", type=float, default=1.5,
                   help="slow-side noise band for --regress-check "
                        "(default 1.5: the smoke's per-class timings are "
                        "millisecond-scale CPU numbers — jittery — while "
                        "the regressions this gate exists for, a class "
                        "demoting to dense LU, move s_per_solve and "
                        "flops_ratio by orders of magnitude)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.io import synthetic
    from gauss_tpu.obs import regress

    systems = {
        "spd": synthetic.spd_matrix(args.spd_n),
        "banded": synthetic.banded_matrix(args.banded_n, args.banded_bw),
        "blockdiag": synthetic.blockdiag_matrix(args.blockdiag_n,
                                                args.block),
        "dense": synthetic.dense_matrix(args.dense_n),
    }
    t0 = time.perf_counter()
    classes: Dict[str, Dict] = {}
    with obs.run(metrics_out=args.metrics_out, tool="structure_check",
                 seed=args.seed) as rec:
        for kind, a in systems.items():
            with obs.span(f"structure_check_{kind}", n=a.shape[0]):
                classes[kind] = run_class(kind, a, args.seed, args.gate,
                                          args.repeats)
    wall = round(time.perf_counter() - t0, 3)
    bad = [k for k, row in classes.items()
           if not (row["verified"] and row["routed_ok"])]
    summary = {"kind": "structured_solve", "seed": args.seed,
               "gate": args.gate, "classes": classes, "wall_s": wall,
               "ok": not bad}

    for kind, row in classes.items():
        print(f"structure-check [{kind:9s}] n={row['n']:5d} detected="
              f"{row['detected']:9s} engine={row['engine']:9s} "
              f"s_per_solve={row['s_per_solve']:.4f} "
              f"flops_ratio={row['flops_ratio']:.4f} "
              f"rel_residual={row['rel_residual']:.2e} "
              f"{'OK' if row['verified'] and row['routed_ok'] else 'FAIL'}")
    print(f"structure-check: {len(classes)} class(es) in {wall} s"
          + (f"; FAILED: {bad}" if bad else "; all verified at the "
             f"{args.gate:.0e} gate"))

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    # Run-id-tagged sources (cf. the fleet records): identical values from
    # DISTINCT epochs — flops ratios are deterministic — must accumulate
    # as separate baseline samples, not dedup into one.
    records = [{"metric": m, "value": v, "unit": u,
                "source": f"structure-{rec.run_id}",
                "kind": "structure"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path), band=args.band)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 and not bad:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if bad:
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
