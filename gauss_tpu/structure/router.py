"""solve_auto: detect -> route -> structured engine -> the same 1e-4 gate.

One entry point turns the structure subsystem into a solver: classify the
operand (:mod:`gauss_tpu.structure.detect`), pick the engine for its class,
and run it through :func:`gauss_tpu.resilience.recover.solve_resilient`
with the structured ladder (:func:`recover.structured_rungs`) — the
structured engine is just rung 0, and everything below it is the SAME
general-LU demotion chain every dense solve already has. The consequences
fall out instead of being re-implemented:

- every structured result passes the identical 1e-4 relative-residual gate
  as dense LU (the ladder's gate IS ``verify.checks.residual_norm``);
- a misclassified matrix — wrong tag, symmetric-but-indefinite, permuted
  "block-diagonal" — fails its rung with a TYPED error or a residual miss
  and demotes to general LU, ending verified or typed, never silently
  wrong, never hung;
- every escalation is an obs ``recovery`` event, and the routing decision
  itself is an obs ``structure`` event, so the summarizer reports
  per-structure lanes from the same stream as everything else.

Hook point ``structure.detect`` (gauss_tpu.resilience.inject, kind
``mistag``): forces the routing tag to ``STRUCTURE_KINDS[int(param)]`` —
the chaos campaign's way of proving, on demand, that a lying classifier
cannot produce a wrong answer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.resilience import recover
from gauss_tpu.structure.detect import (
    STRUCTURE_KINDS,
    StructureInfo,
    detect_structure,
)

#: which ladder rung counts as "the structured engine" per tag (anything
#: else that serves the solution means the route DEMOTED)
ENGINE_FOR_TAG = {"spd": "cholesky", "banded": "banded",
                  "blockdiag": "blockdiag", "dense": "blocked",
                  "sparse": "cg"}


def routed_tag(info: StructureInfo,
               structure: Optional[str] = None) -> str:
    """The tag :func:`solve_auto` will route on: the caller's override,
    else the detected class — then through the ``structure.detect``
    mis-tag hook (fault injection) when a plan is installed."""
    tag = structure if structure is not None else info.kind
    if tag not in STRUCTURE_KINDS:
        raise ValueError(f"unknown structure tag {tag!r}; options: "
                         f"{STRUCTURE_KINDS}")
    if _inject.enabled():
        sp = _inject.poll("structure.detect")
        if sp is not None and sp.kind == "mistag":
            tag = STRUCTURE_KINDS[int(sp.param) % len(STRUCTURE_KINDS)]
    return tag


def solve_auto(a, b, *, structure: Optional[str] = None,
               info: Optional[StructureInfo] = None,
               gate: float = recover.DEFAULT_GATE,
               panel: Optional[int] = None,
               refine_iters: int = 2) -> recover.ResilientResult:
    """Structure-routed solve of ``a @ x = b``.

    Returns the ladder's :class:`gauss_tpu.resilience.recover.
    ResilientResult` — ``.x`` float64 at the original shape, ``.rung`` the
    engine that actually served (``cholesky`` / ``banded`` / ``blockdiag``
    / ``blocked`` / deeper), ``.rung_index > 0`` meaning the route demoted.
    Raises :class:`recover.UnrecoverableSolveError` only when every rung —
    structured AND general — failed; ``ValueError`` for malformed requests.

    ``structure`` overrides detection (a serving layer that already knows
    its tag skips the scan); ``info`` supplies a precomputed
    :class:`StructureInfo` (e.g. from the ``.dat`` coordinate stream).
    An honest rung-0 solve is bit-identical to calling that engine
    directly — routing adds classification, not arithmetic.
    """
    a64 = np.asarray(a, dtype=np.float64)
    b64 = np.asarray(b, dtype=np.float64)
    n = a64.shape[0]
    if a64.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a64.shape}")
    if b64.shape[:1] != (n,) or b64.ndim > 2:
        raise ValueError(f"b must be (n,) or (n, k) with n={n}, "
                         f"got {b64.shape}")
    if n == 0:
        # The empty system: one valid solution, nothing to verify.
        return recover.ResilientResult(
            x=np.zeros_like(b64), rung="empty", rung_index=0, attempts=0,
            rel_residual=0.0, escalations=[])
    if info is None:
        info = detect_structure(a64)
    tag = routed_tag(info, structure)
    obs.emit("structure", n=n, detected=info.kind, tag=tag,
             symmetric=info.symmetric, spd_likely=info.spd_likely,
             bandwidth=info.bandwidth, blocks=len(info.blocks),
             density=round(info.density, 6))
    if n == 1:
        # Trivial 1x1: the host rung alone (a zero "matrix" is typed by
        # the ladder, not a crash).
        res = recover.solve_resilient(a64, b64, gate=gate,
                                      rungs=("numpy_f64",))
    else:
        # Mixed-precision head for the dense lane (ISSUE 11): when an
        # offline sweep recorded a converging lowered (dtype,
        # refine_steps) pair for this size on this hardware, the ladder
        # STARTS at the bf16/bf16x3 rung and demotes typed to the same
        # f32 chain as before — an untuned checkout (dtype seed float32)
        # never changes ladders, and a non-converging lowered solve can
        # only ever cost an escalation, never an unverified answer.
        low = False
        if tag == "dense":
            from gauss_tpu.core import lowered as _lowered

            low = _lowered.lowered_enabled(n)
        res = recover.solve_resilient(
            a64, b64, gate=gate, panel=panel, refine_iters=refine_iters,
            rungs=recover.structured_rungs(tag, lowered=low))
    honest = {ENGINE_FOR_TAG.get(tag, res.rung)}
    if tag == "dense":
        # The mixed-precision head serving IS the dense route working as
        # tuned (its internal dtype demotion already ends at the same f32
        # path "blocked" is); only a rung BELOW the heads counts demoted.
        honest.add("lowered")
    elif tag == "sparse":
        # Any Krylov rung serving IS the sparse route working: CG heads
        # the ladder only for Gershgorin-certified operands, and the
        # general-system rungs under it (gmres, bicgstab) are the same
        # iterative lane — method selection, not a densified demotion.
        honest.update(("gmres", "bicgstab"))
    demoted = res.rung not in honest and n > 1
    obs.counter("structure.solves")
    if demoted:
        obs.counter("structure.demotions")
    obs.emit("structure_solve", n=n, tag=tag, engine=res.rung,
             demoted=demoted, rung_index=res.rung_index,
             rel_residual=res.rel_residual)
    return res
