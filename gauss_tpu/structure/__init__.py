"""Structure-aware solves: detection + specialized engines + one router.

The reference's L0 data layer is a *sparse-coordinate* format
(``matrix_gen.cc`` emits ``row col value`` triples), yet every engine in the
stack — like the reference's own 12 programs — densifies and runs general
O(n^3) elimination regardless of what the matrix actually is. This package
closes that gap (ROADMAP [scenarios]):

- ``detect``    — a cheap structure classifier (:class:`StructureInfo`):
                  symmetry, SPD-likelihood (Gershgorin), bandwidth,
                  contiguous block-diagonal partition, density — computed
                  for free from the ``.dat`` coordinate stream or from one
                  O(n^2) scan of an in-memory array.
- ``cholesky``  — blocked right-looking Cholesky (panel factor + SYRK
                  trailing update) on the core.blocked panel machinery:
                  ~2x fewer FLOPs than LU for SPD systems, no pivot
                  gathers, typed :class:`NotSPDError` on failure.
- ``banded``    — tridiagonal (``lax.associative_scan`` Thomas) and small-b
                  blocked band LU engines whose cost scales with n*b^2,
                  not n^3.
- ``blockdiag`` — vmap-batched small-block solves through the serving
                  layer's executable cache (one device dispatch for the
                  whole partition).
- ``router``    — :func:`solve_auto`: detect -> route -> engine -> the same
                  1e-4 verify gate as dense LU, with misclassification
                  demoting down the resilience recovery ladder to general
                  LU (verified solution or typed error, never a silent
                  wrong answer).

Importing this package is numpy-cheap; the engines import jax lazily.
"""

from gauss_tpu.structure.detect import (  # noqa: F401
    StructureInfo,
    StructureMismatchError,
    STRUCTURE_KINDS,
    detect_structure,
    detect_structure_coords,
    detect_structure_dat,
    structure_tag,
)
from gauss_tpu.structure.router import solve_auto  # noqa: F401

__all__ = [
    "StructureInfo",
    "StructureMismatchError",
    "STRUCTURE_KINDS",
    "detect_structure",
    "detect_structure_coords",
    "detect_structure_dat",
    "structure_tag",
    "solve_auto",
]
