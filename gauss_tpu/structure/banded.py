"""Banded solvers: O(n * b^2) where the dense path pays O(n^3).

Two engines behind one entry point (:func:`solve_banded`):

- **b == 1 (tridiagonal): scan-form Thomas.** The three classic Thomas
  recurrences (the pivot recurrence ``d'_i = d_i - l_i u_{i-1}``, the
  forward sweep, the back sweep) are each first-order linear — so each one
  runs as a ``lax.associative_scan`` in log depth instead of an n-step
  serial chain. The pivot recurrence is rational; it linearizes through the
  standard continuant trick (``d'_i = p_i / p_{i-1}`` with ``p`` a 3-term
  linear recurrence, i.e. a cumulative product of 2x2 matrices). Cumulative
  2x2 products over- or underflow for any nontrivial n, so the combine step
  normalizes each product by its max-|entry| — the recurrence only ever
  consumes RATIOS of the product's entries, which are scale-invariant
  (projectively, normalization keeps the operator associative).
- **b > 1: blocked band LU.** Any matrix of bandwidth b is block-
  tridiagonal in (b, b) blocks, so one ``lax.scan`` over the n/b block rows
  runs block Gaussian elimination with O(b^3) work per step — total
  O(n * b^2), with every shape static.

Neither engine pivots (pivoting would destroy the band). That is the
textbook trade: unconditionally correct for diagonally dominant or SPD
bands, and for everything else the ROUTER's 1e-4 residual gate catches a
bad factorization and demotes to general LU — the engine is allowed to be
fast-but-specialized precisely because the ladder above it is not.

A :class:`gauss_tpu.structure.detect.StructureMismatchError` is raised when
the operand's bandwidth exceeds what the caller promised — the typed
mis-tag signal the recovery ladder consumes.
"""

from __future__ import annotations

import functools

import numpy as np

from gauss_tpu.structure.detect import BANDED_MAX_DIVISOR, \
    StructureMismatchError


def bandwidth_of(a) -> int:
    """max |i - j| over nonzeros (0 for diagonal/empty)."""
    a = np.asarray(a)
    rows, cols = np.nonzero(a)
    return int(np.abs(rows - cols).max()) if rows.size else 0


def _affine_scan(coef, const, reverse: bool = False):
    """Solve ``y_i = coef_i * y_{i-1} + const_i`` (y_{-1} = 0) for all i via
    one associative scan over affine-map composition. ``coef`` is (n, 1),
    ``const`` (n, k); reverse runs the recurrence from the far end."""
    from jax import lax

    def combine(f, g):
        # g after f: x -> g.a * (f.a * x + f.c) + g.c
        fa, fc = f
        ga, gc = g
        return ga * fa, ga * fc + gc

    a, c = lax.associative_scan(combine, (coef, const), reverse=reverse)
    del a
    return c


def solve_tridiag(dl, d, du, b):
    """Thomas via associative scans: dl/d/du are the sub/main/super
    diagonals (dl[0] and du[-1] ignored), ``b`` is (n,) or (n, k).
    Unpivoted — meant for diagonally dominant tridiagonal systems; the
    router's residual gate owns everything else."""
    import jax.numpy as jnp
    from jax import lax

    d = jnp.asarray(d)
    dtype = d.dtype
    dl = jnp.asarray(dl, dtype)
    du = jnp.asarray(du, dtype)
    b = jnp.asarray(b, dtype)
    n = d.shape[0]
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    if n == 1:
        x = b2 / d[0]
        return x[:, 0] if was_vector else x

    # Pivot recurrence d'_i = d_i - dl_i * du_{i-1} / d'_{i-1} linearized:
    # p_i = d_i p_{i-1} - (dl_i du_{i-1}) p_{i-2}, d'_i = p_i / p_{i-1}.
    # Cumulative 2x2 products, normalized per combine (ratios are scale-
    # invariant) so the continuants never over/underflow.
    sub = dl[1:] * du[:-1]                      # (n-1,)
    mats = jnp.zeros((n - 1, 2, 2), dtype)
    mats = mats.at[:, 0, 0].set(d[1:])
    mats = mats.at[:, 0, 1].set(-sub)
    mats = mats.at[:, 1, 0].set(1.0)

    def mcombine(x, y):
        # y AFTER x (cumulative product from the left): P = y @ x, then
        # normalized by its max entry — the recurrence consumes only
        # ratios, which normalization leaves exact (projective scan).
        out = jnp.matmul(y, x)
        scale = jnp.max(jnp.abs(out), axis=(-2, -1), keepdims=True)
        return out / jnp.maximum(scale, jnp.asarray(1e-30, dtype))

    prods = lax.associative_scan(mcombine, mats)
    p_i = prods[:, 0, 0] * d[0] + prods[:, 0, 1]
    p_im1 = prods[:, 1, 0] * d[0] + prods[:, 1, 1]
    dp = jnp.concatenate([d[:1], p_i / p_im1])  # d'_i, i = 0..n-1

    # Forward sweep y_i = b_i - (dl_i / d'_{i-1}) y_{i-1}.
    l = jnp.concatenate([jnp.zeros((1,), dtype), dl[1:] / dp[:-1]])
    y = _affine_scan(-l[:, None], b2)
    # Back sweep x_i = y_i / d'_i - (du_i / d'_i) x_{i+1}.
    u = jnp.concatenate([du[:-1] / dp[:-1], jnp.zeros((1,), dtype)])
    x = _affine_scan(-u[:, None], y / dp[:, None], reverse=True)
    return x[:, 0] if was_vector else x


def _block_diagonals(a, s: int):
    """Identity-pad ``a`` to a multiple of ``s`` and return the block-
    tridiagonal diagonals: D (nb, s, s), E = sub (nb, s, s; E[0] zero),
    F = super (nb, s, s; F[-1] zero)."""
    import jax.numpy as jnp

    n = a.shape[0]
    nb = -(-n // s)
    npad = nb * s
    ap = np.zeros((npad, npad), dtype=np.asarray(a).dtype)
    ap[:n, :n] = np.asarray(a)
    ap[np.arange(n, npad), np.arange(n, npad)] = 1.0
    D = np.stack([ap[i * s:(i + 1) * s, i * s:(i + 1) * s]
                  for i in range(nb)])
    Z = np.zeros((1, s, s), dtype=ap.dtype)
    if nb > 1:
        E = np.concatenate([Z] + [ap[i * s:(i + 1) * s,
                                     (i - 1) * s:i * s][None]
                                  for i in range(1, nb)])
        F = np.concatenate([ap[i * s:(i + 1) * s,
                               (i + 1) * s:(i + 2) * s][None]
                            for i in range(nb - 1)] + [Z])
    else:
        E = F = np.zeros((1, s, s), dtype=ap.dtype)
    return jnp.asarray(D), jnp.asarray(E), jnp.asarray(F), npad


def solve_band_blocklu(a, b, bandwidth: int):
    """Blocked band LU: block-tridiagonal elimination with (b, b) blocks,
    one ``lax.scan`` each way — O(n * b^2) total, static shapes, no
    pivoting (the band's deal; see module docstring)."""
    import jax.numpy as jnp

    a = np.asarray(a)
    n = a.shape[0]
    s = max(1, int(bandwidth))
    D, E, F, npad = _block_diagonals(a, s)
    nb = D.shape[0]
    b = np.asarray(b)
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    k = b2.shape[1]
    bp = np.zeros((npad, k), dtype=b2.dtype)
    bp[:n] = b2
    B = jnp.asarray(bp.reshape(nb, s, k))

    x = _band_run_jit()(D, E, F, B)[:n]
    return x[:, 0] if was_vector else x


@functools.lru_cache(maxsize=None)
def _band_run_jit():
    """The blocked band LU's jitted two-scan program (built once per
    process instead of a fresh closure per call, so repeat solves reuse
    the compile cache). Module-level so the jaxpr auditor
    (gauss_tpu.core.entrypoints entry "banded/blocklu") can trace the
    exact program solve_band_blocklu dispatches; every shape/dtype it
    needs derives from its operands, so the traced program is unchanged
    from the original closure form."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(D, E, F, B):
        nb, s, _ = D.shape
        k = B.shape[2]
        dtype = D.dtype

        def fwd(carry, inp):
            dpinv_prev, y_prev = carry
            Di, Ei, Bi, Fprev = inp
            L = jnp.matmul(Ei, dpinv_prev)
            Dp = Di - jnp.matmul(L, Fprev)
            y = Bi - jnp.matmul(L, y_prev)
            dpinv = jnp.linalg.inv(Dp)
            return (dpinv, y), (dpinv, y)

        Fprev = jnp.concatenate([jnp.zeros((1, s, s), dtype), F[:-1]])
        init = (jnp.zeros((s, s), dtype), jnp.zeros((s, k), dtype))
        _, (dpinvs, ys) = lax.scan(fwd, init, (D, E, B, Fprev))

        def bwd(x_next, inp):
            dpinv, y, Fi = inp
            x = jnp.matmul(dpinv, y - jnp.matmul(Fi, x_next))
            return x, x

        _, xs = lax.scan(bwd, jnp.zeros((s, k), dtype),
                         (dpinvs, ys, F), reverse=True)
        return xs.reshape(nb * s, k)

    return run


def solve_banded(a, b, bandwidth: int | None = None,
                 max_bandwidth: int | None = None):
    """Route a banded system to the right engine by bandwidth.

    ``bandwidth=None`` measures it; a caller-supplied value is CHECKED
    against the operand (cheap) and a lie raises
    :class:`StructureMismatchError` — the typed mis-tag signal. When the
    true bandwidth exceeds ``max_bandwidth`` (default ``n //
    BANDED_MAX_DIVISOR``) the same typed error fires: the band engine
    refuses work the dense path does better, rather than quietly running
    an O(n^3)-grade "band" solve."""
    a = np.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    bw = bandwidth_of(a)
    if bandwidth is not None and bw > bandwidth:
        raise StructureMismatchError(
            f"matrix bandwidth {bw} exceeds the promised {bandwidth}")
    limit = (max(1, n // BANDED_MAX_DIVISOR) if max_bandwidth is None
             else max_bandwidth)
    if bw > limit:
        raise StructureMismatchError(
            f"bandwidth {bw} of this {n} x {n} matrix exceeds the band "
            f"engine's limit {limit}; route to general LU")
    if bw == 0:
        d = np.diagonal(a)
        if not np.all(d != 0):
            raise StructureMismatchError(
                "diagonal matrix with zero diagonal entries is singular")
        x = (np.asarray(b).T / d).T
        return x
    if bw == 1:
        return solve_tridiag(np.concatenate([[0.0], np.diagonal(a, -1)]),
                             np.diagonal(a).copy(),
                             np.concatenate([np.diagonal(a, 1), [0.0]]), b)
    return solve_band_blocklu(a, b, bw)


def solve_banded_refined(a, b, bandwidth: int | None = None, iters: int = 2,
                         dtype=np.float32):
    """f32-device band solve + host-f64 iterative refinement (re-solving
    the O(n * b^2) band system per correction is cheap), the same
    mixed-precision contract as ``blocked.solve_refined``. Returns x
    float64."""
    a64 = np.asarray(a, dtype=np.float64)
    b64 = np.asarray(b, dtype=np.float64)
    a32 = a64.astype(dtype)
    x = np.asarray(solve_banded(a32, b64.astype(dtype), bandwidth),
                   dtype=np.float64)
    for _ in range(iters):
        r = b64 - a64 @ x
        d = np.asarray(solve_banded(a32, r.astype(dtype), bandwidth),
                       dtype=np.float64)
        x = x + d
    return x
