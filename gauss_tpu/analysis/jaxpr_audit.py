"""Jaxpr auditor: the fast-path contracts, derived from traced programs.

The dynamic gates sample these contracts at a handful of sizes; this pass
re-derives them from the CLOSED JAXPR of every entry in the declared
registry (``gauss_tpu.core.entrypoints``), so they hold for the program
FORM, not the sampled cell:

- **callback-free plain path** (``jaxpr.callback``): no ``pure_callback``
  / ``io_callback`` / ``debug_callback``-family primitive anywhere in a
  registered entry's jaxpr unless the entry is registered host-stepped
  (checkpoint / out-of-core / ABFT replay — their host step is the
  feature). This is PR 10's fast-path contract as a static property: a
  hook creeping back into a traced program is caught at lint time, not
  when the forbidden-phase gate's smoke stream happens to cover it.
- **bf16 accumulation** (``jaxpr.bf16_accum``): every ``dot_general``
  consuming a bfloat16 operand must either declare
  ``preferred_element_type=float32`` or produce a float32 output — the
  PR-11 precision contract (one rounding on store) checked at every dot
  in every registered lowered form, not just the ``_gdot`` sites tests
  exercise.
- **f64 confinement** (``jaxpr.f64``): no float64-producing equation
  outside entries registered as refinement sites. TPUs are f32-native;
  an accidental f64 op in a fast-path program silently doubles itemsize
  (and on real TPUs decomposes into emulation).
- **donation survival** (``jaxpr.donation``): entries that declare buffer
  donation must carry the input/output alias in their LOWERING (and, for
  ``compile_check`` entries, in the compiled executable) — CPU honors
  donation in this container, but a silently-dropped alias (shape
  mismatch, refactored staging) would only show up as a memory
  regression nobody attributes.
- **registry completeness** (``registry.*``): every discovered public
  solve entry point is registered or explicitly exempted, and no
  registered name is stale.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from gauss_tpu.analysis import Finding

#: primitive-name fragments that mark a host callsite inside a jaxpr.
CALLBACK_MARKERS = ("callback", "debug_print")

#: where registry findings anchor (the registry is the fixable artifact).
REGISTRY_PATH = "gauss_tpu/core/entrypoints.py"


def _iter_eqns(jaxpr, seen: Optional[Set[int]] = None):
    """Every equation of ``jaxpr`` and its sub-jaxprs (pjit/scan/cond
    bodies ride in eqn params as Jaxpr or ClosedJaxpr values)."""
    if seen is None:
        seen = set()
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub, seen)


def _sub_jaxprs(v):
    inner = getattr(v, "jaxpr", None)
    if inner is not None:
        # ClosedJaxpr -> its Jaxpr; a bare Jaxpr has no .jaxpr attr
        yield inner
    elif hasattr(v, "eqns"):
        yield v
    elif isinstance(v, (list, tuple)):
        for w in v:
            yield from _sub_jaxprs(w)


def _aval_dtype(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def _trace_entry(entry) -> Tuple[object, Optional[str]]:
    """(closed jaxpr, error) for one registry entry."""
    import jax

    try:
        fn, args, kwargs = entry.trace()
        return jax.make_jaxpr(fn)(*args, **kwargs), None
    except Exception as e:  # noqa: BLE001 — a broken trace IS the finding
        return None, f"{type(e).__name__}: {e}"


def _anchor(entry) -> Tuple[str, int]:
    return entry.where if entry.where is not None else (REGISTRY_PATH, 1)


def audit_entry(entry) -> Tuple[List[Finding], int]:
    """All jaxpr findings for one registry entry; returns
    ``(findings, eqns_checked)``."""
    import numpy as np

    findings: List[Finding] = []
    if entry.trace is None:
        return findings, 0
    closed, err = _trace_entry(entry)
    apath, aline = _anchor(entry)
    if closed is None:
        findings.append(Finding(
            rule="jaxpr.trace_error", path=apath, line=aline,
            symbol=entry.name,
            message=f"entry '{entry.name}' failed to trace: {err}"))
        return findings, 0
    checked = 0
    f32 = np.dtype("float32")
    f64 = np.dtype("float64")
    try:
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover — jax always ships ml_dtypes
        bf16 = None
    for eqn in _iter_eqns(closed.jaxpr):
        checked += 1
        name = eqn.primitive.name
        if not entry.host_stepped and any(m in name
                                          for m in CALLBACK_MARKERS):
            findings.append(Finding(
                rule="jaxpr.callback", path=apath, line=aline,
                symbol=entry.name,
                message=f"entry '{entry.name}' traces a host callsite "
                        f"(primitive '{name}') but is not registered "
                        f"host-stepped — the fast-path contract forbids "
                        f"callbacks in this program"))
        if name == "dot_general" and bf16 is not None:
            in_dtypes = [_aval_dtype(v) for v in eqn.invars]
            if any(d == bf16 for d in in_dtypes):
                pref = eqn.params.get("preferred_element_type")
                outs = [_aval_dtype(v) for v in eqn.outvars]
                ok = (pref is not None and np.dtype(pref) == f32) or \
                    all(d == f32 for d in outs)
                if not ok:
                    findings.append(Finding(
                        rule="jaxpr.bf16_accum", path=apath,
                        line=aline, symbol=entry.name,
                        message=f"entry '{entry.name}': dot_general on "
                                f"bf16 operands without f32 accumulation "
                                f"(preferred_element_type={pref!r}, "
                                f"out={[str(d) for d in outs]}) — the "
                                f"precision contract requires "
                                f"accumulate-f32, one rounding on store"))
        if not entry.refinement:
            for v in eqn.outvars:
                if _aval_dtype(v) == f64:
                    findings.append(Finding(
                        rule="jaxpr.f64", path=apath, line=aline,
                        symbol=entry.name,
                        message=f"entry '{entry.name}': primitive "
                                f"'{name}' produces float64 outside a "
                                f"declared refinement site"))
                    break
    return findings, checked


def audit_donation(entry) -> List[Finding]:
    findings: List[Finding] = []
    if entry.lower_donating is None:
        return findings
    apath, aline = _anchor(entry)
    try:
        low = entry.lower_donating()
        text = low.as_text()
    except Exception as e:  # noqa: BLE001
        findings.append(Finding(
            rule="jaxpr.donation", path=apath, line=aline,
            symbol=entry.name,
            message=f"entry '{entry.name}' failed to lower for the "
                    f"donation check: {type(e).__name__}: {e}"))
        return findings
    if "tf.aliasing_output" not in text:
        findings.append(Finding(
            rule="jaxpr.donation", path=apath, line=aline,
            symbol=entry.name,
            message=f"entry '{entry.name}' declares donation but its "
                    f"lowering carries no input/output alias — the "
                    f"donation was silently dropped (shape-mismatched "
                    f"staging?)"))
        return findings
    if entry.compile_check:
        compiled = low.compile()
        ctext = compiled.as_text()
        if "alias" not in ctext.lower():
            findings.append(Finding(
                rule="jaxpr.donation", path=apath, line=aline,
                symbol=entry.name,
                message=f"entry '{entry.name}': the donation alias did "
                        f"not survive to the compiled executable"))
    return findings


def audit_registry() -> List[Finding]:
    """Completeness: every public solve entry point registered or
    exempted; no stale declarations."""
    from gauss_tpu.core import entrypoints as ep

    findings: List[Finding] = []
    known = ep.REGISTERED_FUNCS | set(ep.EXEMPT_FUNCS)
    for qual in ep.discover_public_solvers():
        if qual not in known:
            findings.append(Finding(
                rule="registry.unregistered", path=REGISTRY_PATH, line=1,
                symbol=qual,
                message=f"public solve entry point '{qual}' is neither "
                        f"registered nor exempted — add an EntryPoint "
                        f"(or an EXEMPT_FUNCS reason)"))
    for qual in ep.stale_declarations():
        findings.append(Finding(
            rule="registry.stale", path=REGISTRY_PATH, line=1,
            symbol=qual,
            message=f"registry declares '{qual}' but it no longer "
                    f"resolves — update REGISTERED_FUNCS/EXEMPT_FUNCS"))
    return findings


def run(extra_entries=()) -> Tuple[List[Finding], dict]:
    """The full pass. ``extra_entries``: additional EntryPoint objects
    (the seeded-violation path tests and ``gauss-lint --check-entry``
    use). Returns ``(findings, stats)``."""
    from gauss_tpu.core import entrypoints as ep

    findings: List[Finding] = []
    entries = list(ep.entry_points()) + list(extra_entries)
    eqns = 0
    traced = 0
    for entry in entries:
        got, checked = audit_entry(entry)
        findings.extend(got)
        findings.extend(audit_donation(entry))
        eqns += checked
        traced += 1 if entry.trace is not None else 0
    findings.extend(audit_registry())
    stats = {"entries": len(entries), "traced": traced,
             "eqns_checked": eqns, "findings": len(findings)}
    return findings, stats
