"""Seeded-violation fixtures: one deliberate violation per lint rule.

This module exists to FAIL gauss-lint. It is excluded from every default
scan (``driftlint.SELFTEST_FILE``; not in ``lockset.DEFAULT_FILES``; its
entries are not in ``core.entrypoints``) and is fed back explicitly:

    gauss-lint --check-file gauss_tpu/analysis/selftest.py \\
               --check-entry gauss_tpu.analysis.selftest:SELFTEST_ENTRIES

must exit nonzero with one finding per rule below, each anchored at this
file and the line the tables at the bottom record. tests/test_analysis.py
asserts exactly that — the fixtures are the proof that every rule can
actually fire (a lint gate that never fails is indistinguishable from a
gate that checks nothing), and the red-path half of the acceptance
criteria.

Nothing here runs on any production path; the functions are traced or
parsed, never called for effect.
"""

from __future__ import annotations

import threading

SELFTEST_PATH = "gauss_tpu/analysis/selftest.py"


# -- jaxpr-pass fixtures (traced via --check-entry) --------------------------

def _callback_entry():
    """A jitted program carrying a pure_callback — jaxpr.callback must
    flag it because the entry is NOT registered host-stepped."""
    def build():
        import jax
        import jax.numpy as jnp

        a = jnp.zeros((4, 4), jnp.float32)

        def fn(m):
            probe = jax.pure_callback(
                lambda x: x, jax.ShapeDtypeStruct((), jnp.float32),
                m[0, 0])
            return m + probe
        return fn, (a,), {}
    return build


def _bf16_dot_entry():
    """A dot_general on bf16 operands with neither
    preferred_element_type=f32 nor an f32 output — jaxpr.bf16_accum."""
    def build():
        import jax
        import jax.numpy as jnp

        a = jnp.zeros((8, 8), jnp.bfloat16)

        def fn(m):
            return jax.lax.dot_general(
                m, m, dimension_numbers=(((1,), (0,)), ((), ())))
        return fn, (a,), {}
    return build


def _f64_entry():
    """An f64-producing program on an entry NOT registered as a
    refinement site — jaxpr.f64."""
    def build():
        import jax
        import jax.numpy as jnp

        a = jnp.zeros((4,), jnp.float32)

        def fn(v):
            # x64 is off globally (the repo computes f64 on host); the
            # scoped enable is how an f64 op would sneak into a program.
            with jax.experimental.enable_x64():
                return jnp.cumsum(v.astype(jnp.float64))
        return fn, (a,), {}
    return build


def selftest_entries():
    """Fresh EntryPoint objects per call (the registry dataclass is
    frozen; building here keeps import of this module jax-free)."""
    from gauss_tpu.core.entrypoints import EntryPoint

    def where(builder):
        return (SELFTEST_PATH, builder.__code__.co_firstlineno)

    return [
        EntryPoint("selftest/callback", _callback_entry(),
                   where=where(_callback_entry)),
        EntryPoint("selftest/bf16_dot", _bf16_dot_entry(),
                   where=where(_bf16_dot_entry)),
        EntryPoint("selftest/f64", _f64_entry(),
                   where=where(_f64_entry)),
    ]


#: what --check-entry gauss_tpu.analysis.selftest:SELFTEST_ENTRIES loads.
#: (A property-style callable is not importable by name; the CLI accepts
#: a list, so materialize lazily through __getattr__ below.)
def __getattr__(name):
    if name == "SELFTEST_ENTRIES":
        return selftest_entries()
    raise AttributeError(name)


# -- lockset-pass fixtures (parsed via --check-file) -------------------------

class SelftestRacyCounter:
    """Every lockset rule in one class. Line numbers are recorded in
    EXPECTED_FINDINGS below; keep them in sync when editing."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0                  # guarded by: self._lock
        self.phantom = 0                # guarded by: self._ghost_lock
        self.inbox: list = []           # owned by: selftest_worker

    def bump(self):
        with self._lock:
            self.ticks += 1             # guarded — must NOT flag

    def racy_read(self):
        return self.ticks               # VIOLATION: lockset.unguarded

    def off_thread_touch(self):
        self.inbox.append(1)            # VIOLATION: lockset.thread

    # lockset: thread selftest_worker
    def worker_only(self):
        self.inbox.append(2)            # confined — must NOT flag

    def waived_read(self):
        return self.ticks               # lockset: ok — fixture for the waiver path


def selftest_unguarded_terminal(obs, req, result):
    """A terminal serve_request emission with no winning resolve() CAS
    around it — lockset.cas_terminal."""
    obs.emit("serve_request", status="ok", rid=req)
    return result


# -- drift-pass fixtures (scanned via --check-file) --------------------------

class SelftestCtor:
    pass


def selftest_falsy_default(cache=None):
    """The PR-12 anti-pattern verbatim — drift.falsy_default."""
    return cache or SelftestCtor()


def selftest_undocumented_event():
    """Emits an event name no docs/OBSERVABILITY.md row documents —
    drift.event_doc."""
    from gauss_tpu import obs

    obs.emit("selftest_phantom_event", value=1)


def selftest_unowned_kill_site():
    """Polls an inject kill/stall at a site no KILL_SITE_CAUSE row owns —
    drift.postmortem_owner."""
    from gauss_tpu.resilience import inject

    inject.maybe_kill("selftest.phantom.site")


def _lineno(obj) -> int:
    return obj.__code__.co_firstlineno


#: rule -> (path, line) the seeded violation must be reported at; the
#: red-path test drives gauss-lint and asserts each appears verbatim.
def expected_findings():
    return {
        "jaxpr.callback": (SELFTEST_PATH, _lineno(_callback_entry)),
        "jaxpr.bf16_accum": (SELFTEST_PATH, _lineno(_bf16_dot_entry)),
        "jaxpr.f64": (SELFTEST_PATH, _lineno(_f64_entry)),
        "lockset.unguarded":
            (SELFTEST_PATH, _lineno(SelftestRacyCounter.racy_read) + 1),
        "lockset.thread":
            (SELFTEST_PATH,
             _lineno(SelftestRacyCounter.off_thread_touch) + 1),
        "lockset.never_locked":
            (SELFTEST_PATH,
             _lineno(SelftestRacyCounter.__init__) + 3),
        "lockset.cas_terminal":
            (SELFTEST_PATH, _lineno(selftest_unguarded_terminal) + 3),
        "drift.falsy_default":
            (SELFTEST_PATH, _lineno(selftest_falsy_default) + 2),
        "drift.event_doc":
            (SELFTEST_PATH, _lineno(selftest_undocumented_event) + 5),
        "drift.postmortem_owner":
            (SELFTEST_PATH, _lineno(selftest_unowned_kill_site) + 5),
    }
