"""Lockset checker: guarded-by discipline for the concurrent serving core.

The serving plane holds ~47 lock sites across ``serve/`` and
``resilience/`` after PR 14, and the discipline that makes them correct —
which attribute is guarded by which lock, which field only one thread
ever touches, which emit may only run on the winning ``resolve()`` CAS —
lived entirely in reviewers' heads. This pass makes it DECLARED and
machine-checked:

**Annotation grammar** (full reference in docs/ANALYSIS.md):

- ``self.x = ...  # guarded by: self._lock`` — on the field's declaring
  assignment (same line or the line above): every later access to ``x``
  (any receiver: ``self.x``, ``lane.x``) must be lexically inside
  ``with <same-receiver>.<lock>:``.
- ``self.x = ...  # owned by: worker`` — thread confinement: accesses
  allowed only in methods annotated ``# lockset: thread worker`` (or in
  the declaring method).
- ``# lockset: holds self._lock`` — method-level: callers hold the lock,
  the whole body counts as guarded by it.
- ``# lockset: thread <name>`` — method-level: this method runs only on
  thread ``<name>``.
- ``... # lockset: ok — <reason>`` — line waiver for a deliberate racy
  access (stats snapshots, EWMA hint reads); the reason is mandatory
  culture, not syntax.

**Rules:**

- ``lockset.unguarded`` — a guarded field accessed outside its lock.
- ``lockset.thread`` — an owned field accessed off its owning thread.
- ``lockset.never_locked`` — a field annotated guarded-by a lock that is
  never taken in any ``with`` across the checked files: the annotation
  is wrong or the discipline is fictional; either way it must flag.
- ``lockset.cas_terminal`` — CAS discipline: an
  ``obs.emit("serve_request", ..., status=...)`` terminal emission that
  is not guarded by a winning ``resolve()`` — the exactly-one-terminal
  invariant requires every terminal event to sit on the CAS-won path
  (``if req.resolve(...):`` / ``won = ...resolve(...); if won:`` /
  ``if not ...resolve(...): return``).

The checker is lexical and intra-procedural by design: it proves the
DECLARED discipline is followed where the annotation says it applies,
and every deliberate exception is a visible, reasoned waiver in the
diff — not a heuristic race detector.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, List, Optional, Tuple

from gauss_tpu.analysis import Finding, rel, repo_root

#: the concurrent core the pass checks by default (repo-relative).
DEFAULT_FILES = (
    "gauss_tpu/serve/server.py",
    "gauss_tpu/serve/lanes.py",
    "gauss_tpu/serve/cache.py",
    "gauss_tpu/serve/admission.py",
    "gauss_tpu/serve/durable.py",
    "gauss_tpu/serve/net.py",
    "gauss_tpu/serve/router.py",
    "gauss_tpu/resilience/inject.py",
)


class GuardedField:
    def __init__(self, cls: str, attr: str, lock_attr: Optional[str],
                 owner: Optional[str], path: str, line: int,
                 declaring_method: str):
        self.cls = cls
        self.attr = attr
        self.lock_attr = lock_attr      # guarded-by lock attribute name
        self.owner = owner              # owned-by thread name
        self.path = path
        self.line = line
        self.declaring_method = declaring_method


def _comments_by_line(source: str) -> Dict[int, Tuple[str, bool]]:
    """line -> (comment text, own_line): a full-line comment may annotate
    the statement BELOW it; a trailing comment annotates its own line
    only."""
    out: Dict[int, Tuple[str, bool]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = (tok.string, tok.start[1] == 0
                                     or tok.line[:tok.start[1]].strip()
                                     == "")
    except tokenize.TokenizeError:  # pragma: no cover — ast parsed already
        pass
    return out


def _expr_src(node) -> Optional[str]:
    """Dotted-name source for receiver/lock matching ('self._lock',
    'lane.cond'); None for anything fancier."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_src(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _annotation(comments: Dict[int, Tuple[str, bool]], line: int,
                keys: Tuple[str, ...],
                end_line: Optional[int] = None) -> Optional[Tuple[str, str]]:
    """(key, value) from a trailing comment on any line of the statement
    (``line``..``end_line``), or a FULL-LINE comment on the line above.
    The value is the first token after the key — prose may follow."""
    lines = list(range(line, (end_line or line) + 1)) + [line - 1]
    for ln in lines:
        text, own_line = comments.get(ln, ("", False))
        if ln == line - 1 and not own_line:
            continue
        for key in keys:
            idx = text.find(key)
            if idx >= 0:
                toks = text[idx + len(key):].split()
                if toks:
                    return key, toks[0].rstrip(".,;")
    return None


def _method_annotations(comments, fn: ast.FunctionDef) -> Dict[str, str]:
    """lockset method annotations ('thread', 'holds') from comments on
    the def line(s), the line above, or the first body lines."""
    out: Dict[str, str] = {}
    first = fn.lineno
    if fn.body:
        head = fn.body[0]
        # a docstring pushes the annotation window past its closing quote
        is_doc = (isinstance(head, ast.Expr)
                  and isinstance(head.value, ast.Constant)
                  and isinstance(head.value.value, str))
        first = (head.end_lineno or head.lineno) + 1 if is_doc \
            else head.lineno
    for ln in range(fn.lineno - 1, first + 1):
        text = comments.get(ln, ("", False))[0]
        idx = text.find("lockset:")
        if idx < 0:
            continue
        rest = text[idx + len("lockset:"):].split()
        if len(rest) >= 2 and rest[0] in ("thread", "holds"):
            out[rest[0]] = rest[1].rstrip(".,;—")
    return out


def _waived(comments: Dict[int, Tuple[str, bool]], line: int) -> bool:
    return "lockset: ok" in comments.get(line, ("", False))[0]


def collect_fields(tree: ast.Module, comments: Dict[int, str],
                   path: str) -> List[GuardedField]:
    fields: List[GuardedField] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        for fn in [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    ann = _annotation(comments, node.lineno,
                                      ("guarded by:", "owned by:"),
                                      end_line=node.end_lineno)
                    if ann is None:
                        continue
                    key, value = ann
                    lock_attr = owner = None
                    if key == "guarded by:":
                        lock_attr = value.split(".")[-1]
                    else:
                        owner = value
                    fields.append(GuardedField(
                        cls.name, t.attr, lock_attr, owner, path,
                        node.lineno, fn.name))
    return fields


class _FunctionChecker(ast.NodeVisitor):
    """Check one function body: attribute accesses vs the with-lock
    stack, the method's holds/thread annotations, and waivers."""

    def __init__(self, checker: "LocksetChecker", path: str, source: str,
                 cls: Optional[str], fn: ast.FunctionDef,
                 comments: Dict[int, str]):
        self.c = checker
        self.path = path
        self.cls = cls
        self.fn = fn
        self.comments = comments
        ann = _method_annotations(comments, fn)
        self.thread = ann.get("thread")
        self.held: List[str] = [ann["holds"]] if "holds" in ann else []

    def run(self):
        for stmt in self.fn.body:
            self.visit(stmt)

    # Nested defs/lambdas keep the lexical lock stack (a closure called
    # elsewhere is beyond a lexical checker; the held stack is the
    # conservative-enough answer for the worker-loop closures here).
    def visit_FunctionDef(self, node):  # noqa: N802
        self.generic_visit(node)

    def visit_With(self, node):  # noqa: N802
        entered = []
        for item in node.items:
            src = _expr_src(item.context_expr)
            if src is not None:
                entered.append(src)
                self.c.locks_taken.add(src.split(".")[-1])
        self.held.extend(entered)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(entered):]

    def visit_Attribute(self, node):  # noqa: N802
        self._check_access(node)
        self.generic_visit(node)

    def _check_access(self, node: ast.Attribute):
        fields = self.c.fields_for(node.attr, self.cls,
                                   isinstance(node.value, ast.Name)
                                   and node.value.id == "self")
        if not fields:
            return
        recv = _expr_src(node.value)
        if recv is None:
            recv = "<expr>"
        for field in fields:
            if self._satisfies(node, recv, field):
                return
        if _waived(self.comments, node.lineno):
            return
        field = fields[0]
        if field.owner is not None:
            self.c.findings.append(Finding(
                rule="lockset.thread", path=self.path, line=node.lineno,
                symbol=f"{field.cls}.{field.attr}",
                message=f"'{recv}.{node.attr}' is owned by thread "
                        f"'{field.owner}' but '{self._where()}' is not "
                        f"annotated '# lockset: thread {field.owner}'"))
            return
        want = f"{recv}.{field.lock_attr}"
        self.c.findings.append(Finding(
            rule="lockset.unguarded", path=self.path, line=node.lineno,
            symbol=f"{field.cls}.{field.attr}",
            message=f"'{recv}.{node.attr}' accessed outside "
                    f"'with {want}:' in {self._where()} (guarded field; "
                    f"annotate a waiver with '# lockset: ok — reason' "
                    f"if the race is deliberate)"))

    def _satisfies(self, node, recv: str, field: GuardedField) -> bool:
        """One candidate discipline satisfied by this access?"""
        # the declaring method (construction precedes sharing) is exempt
        if (self.cls == field.cls
                and self.fn.name in (field.declaring_method, "__init__")):
            return True
        if field.owner is not None:
            return self.thread == field.owner
        if f"{recv}.{field.lock_attr}" in self.held:
            return True
        # a Condition built over the lock guards too (with lane.cond:)
        alt = {h for h in self.held if h.startswith(f"{recv}.")}
        return any(self.c.lock_aliases.get(h.split(".")[-1])
                   == field.lock_attr for h in alt)

    def _where(self) -> str:
        return (f"{self.cls}.{self.fn.name}" if self.cls
                else self.fn.name)


class LocksetChecker:
    def __init__(self):
        self.fields: List[GuardedField] = []
        self.findings: List[Finding] = []
        self.locks_taken: set = set()     # lock attr names seen in withs
        #: cond attr -> lock attr for Condition(self.lock) declarations
        self.lock_aliases: Dict[str, str] = {}
        self._parsed: List[Tuple[str, str, ast.Module,
                                 Dict[int, str]]] = []

    def fields_for(self, attr: str, cls: Optional[str],
                   is_self: bool) -> List[GuardedField]:
        """Candidate disciplines for an access to ``.attr``. Self
        accesses bind to the enclosing class's own annotation; a non-self
        receiver's class is unknown statically, so the access must
        satisfy at least ONE declaring class's discipline (conservative:
        an unguarded access fails every candidate and still flags)."""
        hits = [f for f in self.fields if f.attr == attr]
        if not hits:
            return []
        if is_self:
            return [f for f in hits if f.cls == cls]
        return hits

    def load(self, paths: List[str], root: str):
        for path in paths:
            with open(path) as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
            comments = _comments_by_line(source)
            rpath = rel(path, root)
            self._parsed.append((rpath, source, tree, comments))
            self.fields.extend(collect_fields(tree, comments, rpath))
            self._collect_aliases(tree)

    def _collect_aliases(self, tree: ast.Module):
        """self.cond = threading.Condition(self.lock) — with self.cond:
        acquires self.lock, so the cond attr aliases the lock attr."""
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)):
                continue
            call = node.value
            if not (isinstance(call, ast.Call)
                    and getattr(call.func, "attr", "") == "Condition"
                    and call.args):
                continue
            lock_src = _expr_src(call.args[0])
            if lock_src:
                self.lock_aliases[node.targets[0].attr] = \
                    lock_src.split(".")[-1]

    def check(self):
        for rpath, source, tree, comments in self._parsed:
            for cls in [n for n in ast.walk(tree)
                        if isinstance(n, ast.ClassDef)]:
                for fn in [n for n in cls.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]:
                    _FunctionChecker(self, rpath, source, cls.name, fn,
                                     comments).run()
            self._check_cas(rpath, tree)
        for f in self.fields:
            if f.lock_attr is not None and \
                    f.lock_attr not in self.locks_taken:
                self.findings.append(Finding(
                    rule="lockset.never_locked", path=f.path, line=f.line,
                    symbol=f"{f.cls}.{f.attr}",
                    message=f"'{f.cls}.{f.attr}' is annotated guarded by "
                            f"'{f.lock_attr}' but that lock is never "
                            f"taken in any 'with' across the checked "
                            f"files — the annotation (or the code) is "
                            f"wrong"))

    # -- CAS discipline ----------------------------------------------------

    def _check_cas(self, rpath: str, tree: ast.Module):
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            resolve_names = self._resolve_assigned_names(fn)
            for node in ast.walk(fn):
                if self._is_terminal_emit(node) and \
                        not self._cas_guarded(fn, node, resolve_names):
                    self.findings.append(Finding(
                        rule="lockset.cas_terminal", path=rpath,
                        line=node.lineno, symbol=fn.name,
                        message=f"terminal serve_request emission in "
                                f"'{fn.name}' is not guarded by a "
                                f"winning resolve() — terminal events "
                                f"may only be emitted on the CAS-won "
                                f"path (exactly-one-terminal "
                                f"invariant)"))

    @staticmethod
    def _is_terminal_emit(node) -> bool:
        return (isinstance(node, ast.Call)
                and getattr(node.func, "attr", "") == "emit"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "serve_request"
                and any(kw.arg == "status" for kw in node.keywords))

    @staticmethod
    def _contains_resolve(node) -> bool:
        return any(isinstance(n, ast.Call)
                   and getattr(n.func, "attr", "") == "resolve"
                   for n in ast.walk(node))

    @staticmethod
    def _resolve_assigned_names(fn) -> set:
        names = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and LocksetChecker._contains_resolve(node.value)):
                names.add(node.targets[0].id)
        return names

    def _cas_guarded(self, fn, emit, resolve_names: set) -> bool:
        # pattern a/b: an enclosing `if <resolve-call>` / `if <name>`
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            in_body = any(emit is d or any(emit is dd for dd in
                                           ast.walk(d))
                          for d in node.body)
            if not in_body:
                continue
            test = node.test
            if self._contains_resolve(test) and not \
                    isinstance(test, ast.UnaryOp):
                return True
            if isinstance(test, ast.Name) and test.id in resolve_names:
                return True
        # pattern c: an earlier `if not ...resolve(...): return`
        for node in ast.walk(fn):
            if (isinstance(node, ast.If) and node.lineno < emit.lineno
                    and isinstance(node.test, ast.UnaryOp)
                    and isinstance(node.test.op, ast.Not)
                    and self._contains_resolve(node.test)
                    and any(isinstance(s, ast.Return)
                            for s in node.body)):
                return True
        return False


def run(files=None, root: Optional[str] = None,
        ) -> Tuple[List[Finding], dict]:
    """The full pass over ``files`` (default: the serving core)."""
    root = root or repo_root()
    paths = [os.path.join(root, f) for f in (files or DEFAULT_FILES)]
    checker = LocksetChecker()
    checker.load([p for p in paths if os.path.exists(p)], root)
    checker.check()
    # dedupe repeated accesses on one line (load+store of an AugAssign,
    # two reads in one condition) — one finding per (rule, line, field)
    seen = set()
    findings = []
    for f in checker.findings:
        ident = (f.rule, f.path, f.line, f.symbol)
        if ident not in seen:
            seen.add(ident)
            findings.append(f)
    stats = {"files": len(checker._parsed),
             "guarded_fields": len(checker.fields),
             "locks_taken": len(checker.locks_taken),
             "findings": len(findings)}
    return findings, stats
