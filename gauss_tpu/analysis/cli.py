"""gauss-lint: run the static-analysis passes as one gate.

``python -m gauss_tpu.analysis.cli`` (installed as ``gauss-lint``) runs
the jaxpr auditor, the lockset checker, and the drift lint, prints every
finding as ``file:line: [rule] message``, and exits nonzero when any
finding is not covered by the committed baseline
(``gauss_tpu/analysis/baseline.json`` — EMPTY in this tree, and ratcheted:
a grandfathered count may only shrink; new findings always fail).

``--json`` writes a ``kind: lint_report`` summary (finding counts per
pass) that ``obs.regress`` ingests; ``--regress-check`` gates the counts
against the committed epochs in ``reports/history.jsonl`` exactly like
the perf gates (0 findings is the committed baseline value, so ANY
finding is out-of-band there too). ``make lint-check`` runs both.

``--check-file`` / ``--check-entry`` extend the audited surface with
extra sources / registry entries — the seeded-violation path the tests
and the acceptance criteria drive (a violation injected through them
must exit nonzero with the correct file:line).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import uuid
from typing import List

from gauss_tpu.analysis import (
    PASSES,
    Finding,
    check_against_baseline,
    default_baseline_path,
    history_records,
    load_baseline,
    repo_root,
    save_baseline,
)


def _load_extra_entries(specs: List[str]):
    out = []
    for spec in specs:
        modname, _, attr = spec.partition(":")
        obj = getattr(importlib.import_module(modname), attr)
        out.extend(obj if isinstance(obj, (list, tuple)) else [obj])
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gauss-lint",
        description="Static verification of the jaxpr, concurrency, and "
                    "drift contracts (docs/ANALYSIS.md).")
    p.add_argument("--passes", default=",".join(PASSES),
                   help=f"comma-separated subset of {'/'.join(PASSES)} "
                        f"(default: all)")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="grandfathered-findings baseline (default: "
                        "gauss_tpu/analysis/baseline.json; committed "
                        "EMPTY — keep it that way)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the CURRENT findings "
                        "(ratchet: only sensible when the count shrank; "
                        "adding findings to the baseline is a review "
                        "decision, not a default)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the kind: lint_report summary JSON here")
    p.add_argument("--regress-check", action="store_true",
                   help="gate the per-pass finding counts against "
                        "reports/history.jsonl (exit 1 out-of-band)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's records to the history "
                        "(default path when no value given); only on a "
                        "green gate")
    p.add_argument("--check-file", action="append", default=[],
                   metavar="PATH",
                   help="extra source file for the lockset pass and the "
                        "drift falsy-default scan (seeded-violation "
                        "surface)")
    p.add_argument("--check-entry", action="append", default=[],
                   metavar="MOD:ATTR",
                   help="extra jaxpr-audit EntryPoint (or list of them) "
                        "imported from MOD")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="findings and verdicts only, no per-pass stats")
    args = p.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    wanted = [s.strip() for s in args.passes.split(",") if s.strip()]
    unknown = [w for w in wanted if w not in PASSES]
    if unknown:
        p.error(f"unknown pass(es) {unknown}; options: {list(PASSES)}")

    findings: List[Finding] = []
    passes = {}
    rc = 0
    if "jaxpr" in wanted:
        from gauss_tpu.analysis import jaxpr_audit

        try:
            extra = _load_extra_entries(args.check_entry)
        except Exception as e:  # noqa: BLE001 — operator input
            print(f"gauss-lint: cannot load --check-entry: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        got, stats = jaxpr_audit.run(extra_entries=extra)
        findings += got
        passes["jaxpr"] = {**stats, "findings": len(got)}
    if "lockset" in wanted:
        from gauss_tpu.analysis import lockset

        files = list(lockset.DEFAULT_FILES) + list(args.check_file)
        got, stats = lockset.run(files=files, root=root)
        findings += got
        passes["lockset"] = {**stats, "findings": len(got)}
    if "drift" in wanted:
        from gauss_tpu.analysis import driftlint

        got, stats = driftlint.run(root=root,
                                   extra_files=tuple(args.check_file))
        findings += got
        passes["drift"] = {**stats, "findings": len(got)}

    baseline_path = args.baseline or default_baseline_path()
    baseline = load_baseline(baseline_path)
    new, ratchet_notes = check_against_baseline(findings, baseline)

    for f in findings:
        marker = "" if f in new else "  (grandfathered)"
        print(f.format() + marker)
    for note in ratchet_notes:
        print(note)
    if not args.quiet:
        for name in PASSES:
            if name in passes:
                print(f"pass {name}: {passes[name]}")

    if args.update_baseline:
        counts = save_baseline(findings, baseline_path)
        print(f"baseline: {baseline_path} rewritten "
              f"({sum(counts.values())} finding(s))")
        new = []

    summary = {
        "kind": "lint_report",
        "run_id": uuid.uuid4().hex[:12],
        "clean": not findings,
        "passes": passes,
        "findings_total": len(findings),
        "new_findings": len(new),
        "baseline_findings": sum(baseline.values()),
        "findings": [f.to_doc() for f in findings],
    }
    if args.json:
        parent = os.path.dirname(args.json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        if not args.quiet:
            print(f"summary: {args.json}")

    if new:
        print(f"gauss-lint: {len(new)} new finding(s) "
              f"({len(findings)} total, "
              f"{sum(baseline.values())} grandfathered)")
        rc = 1
    else:
        print(f"gauss-lint: clean ({len(findings)} grandfathered, "
              f"{sum(p.get('findings', 0) for p in passes.values())} "
              f"finding(s) across {len(passes)} pass(es))")

    if args.regress_check or args.history is not None:
        from gauss_tpu.obs import regress

        records = history_records(summary)
        if args.regress_check and records:
            history_path = os.path.join(root, "reports", "history.jsonl")
            verdicts = regress.check_records(
                records, regress.load_history(history_path))
            print(regress.format_verdicts(verdicts))
            if any(v["status"] == "out-of-band" for v in verdicts):
                rc = rc or 1
        if args.history is not None and rc == 0:
            history_path = (args.history
                            or os.path.join(root, "reports",
                                            "history.jsonl"))
            added = regress.append_history(records, history_path)
            print(f"history: {added} record(s) appended to "
                  f"{history_path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
