"""gauss-lint: static verification of the contracts tests only sample.

Three passes (see docs/ANALYSIS.md for the catalog and annotation
grammar):

- :mod:`gauss_tpu.analysis.jaxpr_audit` — trace the declared registry of
  fast-path entry points (``gauss_tpu.core.entrypoints``) and statically
  assert the callback-free plain path, the bf16->f32 accumulation
  contract, the f64 confinement to declared refinement sites, and that
  declared donations survive to the lowering's input/output aliasing.
- :mod:`gauss_tpu.analysis.lockset` — AST guarded-by analysis over the
  concurrent serving core (``serve/`` + ``resilience/``): shared mutable
  attributes annotated ``# guarded by: self._lock`` must be accessed
  under that lock (or an annotated owning thread), and terminal-status
  events may only be emitted on the winning ``resolve()`` CAS path.
- :mod:`gauss_tpu.analysis.driftlint` — single-source/doc drift: tunable
  constants import from ``tune/space.py``, every ``ServeConfig`` field
  and audited CLI flag has a ``docs/API.md`` row, every emitted obs
  event name appears in ``docs/OBSERVABILITY.md``, every
  ``RATCHET_BASELINES`` metric exists in ``reports/history.jsonl``, and
  the ``x or Ctor()`` falsy-default anti-pattern (the PR-12
  ``cache or ExecutableCache(...)`` bug) never recurs.

Findings are typed (:class:`Finding`), carry ``file:line``, and are
gated against a committed baseline (:func:`load_baseline` /
:func:`check_against_baseline`) that may only ever SHRINK — grandfathered
findings are a ratchet, not a suppression list. The repo ships with the
baseline EMPTY. ``gauss-lint`` (``python -m gauss_tpu.analysis.cli``) is
the CLI; ``make lint-check`` wires it into CI.

This module is import-light (stdlib only) so the regress sentinel can
derive history records from a lint report without loading jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Tuple

#: the three passes, in report order.
PASSES = ("jaxpr", "lockset", "drift")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One typed lint finding.

    ``key`` is the BASELINE identity: rule + path + symbol, deliberately
    excluding the line number so grandfathered findings survive unrelated
    edits shifting lines; the report still prints exact ``file:line``.
    """

    rule: str          # e.g. "jaxpr.callback", "lockset.unguarded"
    path: str          # repo-relative file
    line: int
    message: str
    symbol: str = ""   # entry/class.attr/event the finding is about

    @property
    def passname(self) -> str:
        return self.rule.split(".", 1)[0]

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_doc(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(os.path.abspath(path), root)
    except ValueError:  # pragma: no cover — cross-drive (windows)
        return path


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Dict[str, int]:
    """key -> grandfathered count. A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    counts = doc.get("findings", {}) if isinstance(doc, dict) else {}
    return {str(k): int(v) for k, v in counts.items() if int(v) > 0}


def save_baseline(findings: Iterable[Finding], path: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    doc = {"comment": "gauss-lint grandfathered findings — a RATCHET: "
                      "counts may only shrink (docs/ANALYSIS.md); keep "
                      "this empty unless a finding is consciously "
                      "deferred",
           "findings": dict(sorted(counts.items()))}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return counts


def check_against_baseline(findings: List[Finding],
                           baseline: Dict[str, int],
                           ) -> Tuple[List[Finding], List[str]]:
    """Split findings into (new — fail the gate) and ratchet notes.

    A finding whose key holds baseline budget consumes one unit of it;
    anything past the budget is NEW. Baseline keys whose current count
    shrank (or vanished) produce ratchet notes: the committed baseline
    should be tightened to match (the count may only move down)."""
    budget = dict(baseline)
    new: List[Finding] = []
    seen: Dict[str, int] = {}
    for f in findings:
        seen[f.key] = seen.get(f.key, 0) + 1
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    notes = [f"ratchet: '{k}' fixed {v - seen.get(k, 0)} of {v} "
             f"grandfathered finding(s) — shrink the baseline"
             for k, v in sorted(baseline.items())
             if seen.get(k, 0) < v]
    return new, notes


def history_records(summary: Dict[str, Any],
                    source: str = "") -> List[Dict[str, Any]]:
    """History records a ``kind: lint_report`` summary contributes: the
    per-pass finding counts (0 is a real — and the desired — value, so
    these records are built here rather than through regress._record,
    which drops non-positive values)."""
    out: List[Dict[str, Any]] = []
    passes = summary.get("passes") or {}
    src = source or f"lint:{summary.get('run_id', 'unknown')}"
    for name in PASSES:
        info = passes.get(name)
        if not isinstance(info, dict):
            continue
        count = info.get("findings")
        if isinstance(count, (int, float)) and count >= 0:
            out.append({"metric": f"lint:{name}/findings",
                        "value": float(count), "unit": "count",
                        "source": src, "kind": "lint"})
    total = summary.get("findings_total")
    if isinstance(total, (int, float)) and total >= 0:
        out.append({"metric": "lint:findings_total", "value": float(total),
                    "unit": "count", "source": src, "kind": "lint"})
    return out
