"""Drift lint: single-source and documentation invariants, as a gate.

The repo carries several "X must stay in sync with Y" rules that have
historically drifted silently until a reviewer noticed (the stale
``matmul_pallas`` API row of ADVICE r5 #3, undocumented obs events, the
PR-12 ``cache or ExecutableCache(...)`` falsy-default bug). This pass
turns each into a checked invariant:

- ``drift.tune_source`` — the PR-7 single-source rule: every declared
  tunable constant's original home must derive it from ``tune/space.py``
  (assignment or import-as), never a literal; ``matmul_pallas`` must
  keep consuming ``MM_TILE_SEED``.
- ``drift.config_doc`` — every ``ServeConfig`` dataclass field appears
  in ``docs/API.md``.
- ``drift.cli_doc`` — every long flag of the audited CLIs (``gauss-serve``,
  ``gauss-lint``) appears in ``docs/API.md``.
- ``drift.event_doc`` — every obs event name emitted anywhere in
  ``gauss_tpu/`` (``obs.emit("<name>", ...)``) appears as a backticked
  name in ``docs/OBSERVABILITY.md``.
- ``drift.ratchet_history`` — every ``RATCHET_BASELINES`` metric has at
  least one committed epoch in ``reports/history.jsonl`` (a ratchet
  with no history cannot be re-derived or appealed).
- ``drift.falsy_default`` — the ``x or Ctor()`` anti-pattern: a falsy-
  but-valid operand (empty cache, zero-length container) is silently
  discarded by ``or``; write ``x if x is not None else Ctor()``. A
  deliberate use takes a ``# driftlint: ok — reason`` waiver.
- ``drift.postmortem_owner`` — every inject kill/stall hook site
  (``inject.maybe_kill("<site>")``) names the post-mortem cause its
  death surfaces as (``KILL_SITE_CAUSE``), and every
  ``postmortem.KNOWN_CAUSES`` entry names a live capture owner
  (``POSTMORTEM_OWNERS``, ``file::symbol``) — a kill site with no owner
  is a process that can die with no bundle to explain it.
- ``drift.api_signature`` — the ``matmul_pallas`` API row's documented
  ``bm/bn/bk`` defaults match the live signature (the ADVICE r5 #3
  regression, pinned).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from gauss_tpu.analysis import Finding, rel, repo_root

#: (file, constant, tune.space attribute) — the single-source table.
TUNE_SOURCED = (
    ("gauss_tpu/core/blocked.py", "CHUNK_DEFAULT", "CHUNK_SEED"),
    ("gauss_tpu/core/blocked.py", "PANEL_VMEM_BUDGET",
     "PANEL_VMEM_BUDGET_SEED"),
    ("gauss_tpu/kernels/panel_pallas.py", "DEFAULT_SEG", "PANEL_SEG_SEED"),
    ("gauss_tpu/kernels/rowelim_pallas.py", "DEFAULT_BM",
     "ROWELIM_TILE_SEED"),
    ("gauss_tpu/kernels/rowelim_pallas.py", "DEFAULT_BN",
     "ROWELIM_TILE_SEED"),
    ("gauss_tpu/outofcore/stream.py", "OUTOFCORE_DEVICE_FRAC",
     "OUTOFCORE_DEVICE_FRAC_SEED"),
    ("gauss_tpu/structure/detect.py", "SPARSE_MAX_DENSITY",
     "SPARSE_DENSITY_SEED"),
)

#: files that must REFERENCE a tune.space seed (no module-level constant
#: of their own — the seed is consumed inline).
TUNE_REFERENCED = (
    ("gauss_tpu/kernels/matmul_pallas.py", "MM_TILE_SEED"),
    ("gauss_tpu/sparse/krylov.py", "SPARSE_RESTART_SEED"),
    ("gauss_tpu/sparse/precond.py", "SPARSE_BLOCK_SEED"),
)

#: CLIs whose long flags must have docs/API.md coverage.
AUDITED_CLIS = (
    ("gauss_tpu/serve/cli.py", "gauss-serve"),
    ("gauss_tpu/analysis/cli.py", "gauss-lint"),
    ("gauss_tpu/obs/debug.py", "gauss-debug"),
)

SERVE_CONFIG_FILE = "gauss_tpu/serve/admission.py"
API_DOC = "docs/API.md"
OBS_DOC = "docs/OBSERVABILITY.md"
HISTORY = "reports/history.jsonl"
MATMUL_KERNEL = "gauss_tpu/kernels/matmul_pallas.py"


def _read(root: str, relpath: str) -> Optional[str]:
    path = os.path.join(root, relpath)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return f.read()


def _parse(root: str, relpath: str) -> Optional[ast.Module]:
    text = _read(root, relpath)
    return None if text is None else ast.parse(text, filename=relpath)


#: excluded from the default scans: the seeded-violation fixture module
#: exists to FAIL every pass and is only audited when fed back explicitly
#: via ``--check-file`` / ``--check-entry`` (tests + the red-path
#: acceptance check drive it).
SELFTEST_FILE = os.path.join("gauss_tpu", "analysis", "selftest.py")


def _py_files(root: str) -> List[str]:
    out = []
    base = os.path.join(root, "gauss_tpu")
    skip = os.path.join(root, SELFTEST_FILE)
    for dirpath, dirs, files in os.walk(base):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in sorted(files):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                if path != skip:
                    out.append(path)
    return out


# -- drift.tune_source -------------------------------------------------------

def _derives_from(tree: ast.Module, const: str, attr: str) -> Tuple[bool,
                                                                    int]:
    """Does the module bind ``const`` from tune.space's ``attr``
    (assignment referencing it, or ``import ... as const``)? Returns
    (ok, best line for the finding)."""
    line = 1
    space_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                "tune.space" in node.module:
            for alias in node.names:
                space_names.add(alias.asname or alias.name)
                if alias.name == attr and (alias.asname or alias.name) \
                        == const:
                    return True, node.lineno
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, ast.Tuple):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        if const not in names:
            continue
        line = node.lineno
        for ref in ast.walk(node.value):
            if isinstance(ref, ast.Attribute) and ref.attr == attr:
                return True, line
            if isinstance(ref, ast.Name) and ref.id == attr and \
                    attr in space_names:
                return True, line
    return False, line


def check_tune_source(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for relpath, const, attr in TUNE_SOURCED:
        tree = _parse(root, relpath)
        if tree is None:
            findings.append(Finding(
                rule="drift.tune_source", path=relpath, line=1,
                symbol=const,
                message=f"declared single-source file missing (table in "
                        f"analysis/driftlint.py names {const})"))
            continue
        ok, line = _derives_from(tree, const, attr)
        if not ok:
            findings.append(Finding(
                rule="drift.tune_source", path=relpath, line=line,
                symbol=const,
                message=f"'{const}' must derive from tune.space.{attr} "
                        f"(the PR-7 single-source rule) — a literal here "
                        f"lets the code default and the tuner's seed "
                        f"drift apart"))
    for relpath, attr in TUNE_REFERENCED:
        text = _read(root, relpath)
        if text is None or attr not in text:
            findings.append(Finding(
                rule="drift.tune_source", path=relpath, line=1,
                symbol=attr,
                message=f"file no longer references tune.space.{attr} — "
                        f"its tile defaults must stay tuner-sourced"))
    return findings


# -- drift.config_doc / drift.cli_doc ---------------------------------------

def serve_config_fields(root: str) -> List[Tuple[str, int]]:
    tree = _parse(root, SERVE_CONFIG_FILE)
    if tree is None:
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ServeConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    out.append((stmt.target.id, stmt.lineno))
    return out


def check_config_doc(root: str) -> List[Finding]:
    api = _read(root, API_DOC) or ""
    findings = []
    for field, line in serve_config_fields(root):
        if not re.search(rf"\b{re.escape(field)}\b", api):
            findings.append(Finding(
                rule="drift.config_doc", path=SERVE_CONFIG_FILE,
                line=line, symbol=f"ServeConfig.{field}",
                message=f"ServeConfig field '{field}' has no docs/API.md "
                        f"row — every serving knob must be documented"))
    return findings


def cli_flags(root: str, relpath: str) -> List[Tuple[str, int]]:
    tree = _parse(root, relpath)
    if tree is None:
        return []
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "attr", "") == "add_argument"):
            for arg in node.args:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("--"):
                    out.append((arg.value, node.lineno))
    return out


def check_cli_doc(root: str) -> List[Finding]:
    api = _read(root, API_DOC) or ""
    findings = []
    for relpath, prog in AUDITED_CLIS:
        for flag, line in cli_flags(root, relpath):
            if flag not in api:
                findings.append(Finding(
                    rule="drift.cli_doc", path=relpath, line=line,
                    symbol=f"{prog} {flag}",
                    message=f"{prog} flag '{flag}' has no docs/API.md "
                            f"coverage"))
    return findings


# -- drift.event_doc ---------------------------------------------------------

def emitted_events(root: str, extra_files: Tuple[str, ...] = (),
                   ) -> Dict[str, Tuple[str, int]]:
    """event name -> (file, first line) for every obs.emit("name", ...)."""
    out: Dict[str, Tuple[str, int]] = {}
    files = _py_files(root) + [os.path.join(root, f) for f in extra_files
                               if os.path.exists(os.path.join(root, f))]
    for path in files:
        try:
            tree = ast.parse(open(path).read())
        except SyntaxError:  # pragma: no cover
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "obs"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            out.setdefault(name, (rel(path, root), node.lineno))
    return out


def check_event_doc(root: str,
                    extra_files: Tuple[str, ...] = ()) -> List[Finding]:
    doc = _read(root, OBS_DOC) or ""
    findings = []
    for name, (path, line) in sorted(
            emitted_events(root, extra_files).items()):
        if f"`{name}`" not in doc:
            findings.append(Finding(
                rule="drift.event_doc", path=path, line=line, symbol=name,
                message=f"obs event '{name}' is emitted here but has no "
                        f"docs/OBSERVABILITY.md row — the event schema "
                        f"table is the contract consumers read"))
    return findings


# -- drift.ratchet_history ---------------------------------------------------

def check_ratchet_history(root: str) -> List[Finding]:
    from gauss_tpu.obs import regress

    findings = []
    history = regress.load_history(os.path.join(root, HISTORY))
    have = {r.get("metric") for r in history}
    for metric in sorted(regress.RATCHET_BASELINES):
        if metric not in have:
            findings.append(Finding(
                rule="drift.ratchet_history", path="gauss_tpu/obs/"
                "regress.py", line=1, symbol=metric,
                message=f"RATCHET_BASELINES metric '{metric}' has no "
                        f"committed epoch in {HISTORY} — a ratchet with "
                        f"no history cannot be re-derived or appealed"))
    return findings


# -- drift.falsy_default -----------------------------------------------------

def check_falsy_default(root: str,
                        extra_files: Tuple[str, ...] = ()) -> List[Finding]:
    findings = []
    files = _py_files(root) + [os.path.join(root, f) for f in extra_files
                               if os.path.exists(os.path.join(root, f))]
    for path in files:
        try:
            source = open(path).read()
        except OSError:  # pragma: no cover
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError:  # pragma: no cover
            continue
        lines = source.splitlines()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.BoolOp)
                    and isinstance(node.op, ast.Or)):
                continue
            last = node.values[-1]
            if not isinstance(last, ast.Call):
                continue
            f = last.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", ""))
            if not name[:1].isupper():
                continue
            ln = node.lineno
            if ln - 1 < len(lines) and "driftlint: ok" in lines[ln - 1]:
                continue
            findings.append(Finding(
                rule="drift.falsy_default", path=rel(path, root), line=ln,
                symbol=name,
                message=f"'... or {name}(...)' discards a falsy-but-"
                        f"valid left operand (the PR-12 empty-"
                        f"ExecutableCache bug); write "
                        f"'x if x is not None else {name}(...)' (or "
                        f"waive with '# driftlint: ok — reason')"))
    return findings


# -- drift.postmortem_owner --------------------------------------------------

#: inject kill/stall hook site -> the postmortem.KNOWN_CAUSES entry the
#: death surfaces as when it fires under a supervisor. Adding a
#: ``maybe_kill`` site without a row here fails the gate: the new fault
#: would kill a process nobody owns a post-mortem capture for.
KILL_SITE_CAUSE = {
    "serve.server.batch": "supervisor_death",
    "outofcore.group": "supervisor_death",
    "dist.multihost.worker": "fleet_worker_dead",
    "checkpoint.group": "fleet_worker_dead",
    "fleet.worker.group": "fleet_worker_dead",
}

#: post-mortem cause -> ``file::symbol`` of the code that owns capturing
#: the bundle when that cause fires (the other half of the contract the
#: KNOWN_CAUSES table in obs/postmortem.py promises). The symbol must be
#: a live ``def`` in the named file — a renamed owner fails the gate.
POSTMORTEM_OWNERS = {
    "supervisor_death": "gauss_tpu/serve/durable.py::supervise",
    "supervisor_stall": "gauss_tpu/serve/durable.py::supervise",
    "fleet_worker_dead": "gauss_tpu/resilience/fleet.py::_supervise",
    "fleet_worker_stalled": "gauss_tpu/resilience/fleet.py::_supervise",
    "unclean_resume": "gauss_tpu/serve/server.py::_replay",
    "slo_alert": "gauss_tpu/obs/live.py::observe_slo",
    "sdc_detected": "gauss_tpu/resilience/recover.py::solve_resilient",
    "poison_quarantine": "gauss_tpu/serve/durable.py::supervise",
    "manual": "gauss_tpu/obs/debug.py::main",
}

DRIFTLINT_FILE = "gauss_tpu/analysis/driftlint.py"


def kill_sites(root: str, extra_files: Tuple[str, ...] = (),
               ) -> Dict[str, Tuple[str, int]]:
    """site -> (file, first line) for every ``*.maybe_kill("<site>")``."""
    out: Dict[str, Tuple[str, int]] = {}
    files = _py_files(root) + [os.path.join(root, f) for f in extra_files
                               if os.path.exists(os.path.join(root, f))]
    for path in files:
        if path.endswith(os.path.join("resilience", "inject.py")):
            continue  # the hook's own definition/docstring, not a site
        try:
            tree = ast.parse(open(path).read())
        except (OSError, SyntaxError):  # pragma: no cover
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "maybe_kill"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            out.setdefault(node.args[0].value, (rel(path, root),
                                                node.lineno))
    return out


def check_postmortem_owner(root: str,
                           extra_files: Tuple[str, ...] = (),
                           ) -> List[Finding]:
    from gauss_tpu.obs import postmortem

    findings: List[Finding] = []
    sites = kill_sites(root, extra_files)
    for site, (path, line) in sorted(sites.items()):
        if site not in KILL_SITE_CAUSE:
            findings.append(Finding(
                rule="drift.postmortem_owner", path=path, line=line,
                symbol=site,
                message=f"inject kill/stall site '{site}' has no "
                        f"KILL_SITE_CAUSE row (analysis/driftlint.py) — "
                        f"a process this fault kills would die with no "
                        f"owner on the hook to capture its post-mortem "
                        f"bundle"))
    for site, cause in sorted(KILL_SITE_CAUSE.items()):
        if site not in sites:
            findings.append(Finding(
                rule="drift.postmortem_owner", path=DRIFTLINT_FILE,
                line=1, symbol=site,
                message=f"KILL_SITE_CAUSE names '{site}' but no "
                        f"maybe_kill(\"{site}\") hook exists — stale "
                        f"registry row"))
        if cause not in postmortem.KNOWN_CAUSES:
            findings.append(Finding(
                rule="drift.postmortem_owner", path=DRIFTLINT_FILE,
                line=1, symbol=site,
                message=f"KILL_SITE_CAUSE maps '{site}' to '{cause}', "
                        f"which is not in postmortem.KNOWN_CAUSES"))
    for cause in postmortem.KNOWN_CAUSES:
        if cause not in POSTMORTEM_OWNERS:
            findings.append(Finding(
                rule="drift.postmortem_owner",
                path="gauss_tpu/obs/postmortem.py", line=1, symbol=cause,
                message=f"KNOWN_CAUSES entry '{cause}' has no "
                        f"POSTMORTEM_OWNERS row — every cause must name "
                        f"the code that captures its bundle"))
    for cause, owner in sorted(POSTMORTEM_OWNERS.items()):
        if cause not in postmortem.KNOWN_CAUSES:
            findings.append(Finding(
                rule="drift.postmortem_owner", path=DRIFTLINT_FILE,
                line=1, symbol=cause,
                message=f"POSTMORTEM_OWNERS names unknown cause "
                        f"'{cause}' (not in postmortem.KNOWN_CAUSES)"))
        path, _, symbol = owner.partition("::")
        text = _read(root, path)
        if text is None or not re.search(
                rf"^\s*def {re.escape(symbol)}\b", text, re.M):
            findings.append(Finding(
                rule="drift.postmortem_owner", path=DRIFTLINT_FILE,
                line=1, symbol=cause,
                message=f"POSTMORTEM_OWNERS owner '{owner}' for "
                        f"'{cause}' does not resolve to a def — the "
                        f"capture owner moved or was renamed"))
    return findings


# -- drift.api_signature -----------------------------------------------------

def check_api_signature(root: str) -> List[Finding]:
    """The matmul_pallas API row's bm/bn/bk defaults must match the live
    signature — the ADVICE r5 #3 staleness, pinned as a rule."""
    findings: List[Finding] = []
    tree = _parse(root, MATMUL_KERNEL)
    api = _read(root, API_DOC) or ""
    if tree is None:
        return findings
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "matmul_pallas"), None)
    if fn is None:
        findings.append(Finding(
            rule="drift.api_signature", path=MATMUL_KERNEL, line=1,
            symbol="matmul_pallas",
            message="matmul_pallas not found — update the api_signature "
                    "rule in analysis/driftlint.py"))
        return findings
    defaults = {}
    kwonly = dict(zip([a.arg for a in fn.args.kwonlyargs],
                      fn.args.kw_defaults))
    for name in ("bm", "bn", "bk"):
        node = kwonly.get(name)
        if isinstance(node, ast.Constant):
            defaults[name] = node.value
    row = next((ln for ln in api.splitlines()
                if ln.startswith("| `matmul_pallas`")), "")
    if not row:
        findings.append(Finding(
            rule="drift.api_signature", path=API_DOC, line=1,
            symbol="matmul_pallas",
            message="docs/API.md has no matmul_pallas row"))
        return findings
    for name, default in defaults.items():
        want = f"{name}={default}"
        if want not in row:
            findings.append(Finding(
                rule="drift.api_signature", path=API_DOC, line=1,
                symbol="matmul_pallas",
                message=f"docs/API.md matmul_pallas row documents a "
                        f"different default than the signature's "
                        f"'{want}' (ADVICE r5 #3 — keep the row live)"))
    return findings


def run(root: Optional[str] = None,
        extra_files: Tuple[str, ...] = ()) -> Tuple[List[Finding], dict]:
    root = root or repo_root()
    findings: List[Finding] = []
    findings += check_tune_source(root)
    findings += check_config_doc(root)
    findings += check_cli_doc(root)
    findings += check_event_doc(root, extra_files)
    findings += check_ratchet_history(root)
    findings += check_falsy_default(root, extra_files)
    findings += check_postmortem_owner(root, extra_files)
    findings += check_api_signature(root)
    stats = {
        "tune_constants": len(TUNE_SOURCED) + len(TUNE_REFERENCED),
        "config_fields": len(serve_config_fields(root)),
        "cli_flags": sum(len(cli_flags(root, p)) for p, _ in AUDITED_CLIS),
        "events": len(emitted_events(root)),
        "kill_sites": len(kill_sites(root)),
        "findings": len(findings),
    }
    return findings, stats
