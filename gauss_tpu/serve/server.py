"""SolverServer: the long-lived batched solving service.

Turns the one-shot solvers into a service loop (ROADMAP north star): clients
``submit`` systems and block on per-request results; a single worker thread
drains the bounded queue in SAME-BUCKET batches and dispatches each batch as
one ``vmap``-batched blocked LU solve through the shape-bucketed executable
cache. Three lanes:

- **batched** — requests whose padded size fits the bucket ladder; the hot
  lane (amortized compile via serve.cache, one device step per batch).
- **handoff** — oversized systems (past the ladder top); routed one at a
  time through :func:`core.blocked.solve_handoff`, which itself picks
  single-chip vs distributed and now emits its routing decision as an obs
  ``route`` event, so serve traces show WHY a request took the slow lane.
- **numpy** — the degraded lane: host LAPACK ``solve`` when the device lane
  is persistently unhealthy (admission.LaneHealth circuit breaker), so the
  service returns correct-but-slow answers instead of errors while the
  device recovers.

Everything observable lands on the active obs recorder: per-request
``serve_request`` events (status, lane, latencies), per-batch ``serve_batch``
events (occupancy), cache/retry/fallback events, and the latency histogram —
the summarizer's "serving" section and the loadgen report both read this one
stream.

With ``ServeConfig(live_port=...)`` the same stream ALSO feeds the live
telemetry plane (gauss_tpu.obs.live): a rolling-window aggregator installed
as the obs live sink and an embedded HTTP endpoint serving ``/metrics``
(Prometheus text), ``/slo`` (burn-rate alert states), and ``/trace``
(on-demand Chrome-trace capture of the next N batches) while the server
runs. Every request is minted a ``trace_id`` at ``submit()`` and carries it
through admission, batching, dispatch, retry, recovery, and handoff, so any
terminal status folds back into one per-request span tree
(gauss_tpu.obs.requesttrace). With ``slo_shed`` the admission path consults
the firing SLO alerts and degrades EARLY (reduced queue bound) instead of
riding into the deadline cliff.

With ``ServeConfig(journal_dir=...)`` admission is DURABLE
(gauss_tpu.serve.durable): every admit and every terminal is journaled
(write-ahead, CRC-per-record, torn-tail tolerant), a restarted server
replays unterminated admits through this same dispatch path (in-deadline
requests re-solve, expired ones get a typed terminal, original trace ids
preserved so span trees complete across the crash), and client idempotency
keys (``submit(request_id=...)``) dedupe resubmissions against journaled
terminals — exactly-once terminal statuses across ``kill -9``.
``journal_dir=None`` keeps the whole layer compiled out: one ``is None``
check at admission, none anywhere else.

With ``ServeConfig(lanes=N)`` the single worker is replaced by the MESH
serving plane (gauss_tpu.serve.lanes): N async dispatch lanes placed
across the device mesh — one per device, or per ``lane_width``-device
slice with the batch axis GSPMD-sharded over it — with key-affinity
placement, work stealing between lane queues, continuous batching
(admission into the next in-flight batch slot, bounded by a formation
deadline), and SLO-burn-driven lane autoscaling. Admission bounds,
journaling, verification, and terminal resolution stay HERE, unchanged;
``lanes=0`` (default) is the pre-mesh single-lane path, byte-identical.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import threading
import time
from typing import Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.serve import buckets
from gauss_tpu.serve.admission import (
    STATUS_EXPIRED,
    STATUS_FAILED,
    STATUS_OK,
    STATUS_POISON,
    STATUS_REJECTED,
    LaneHealth,
    ServeConfig,
    ServeRequest,
    ServeResult,
    is_transient_device_error,
    poison_scan,
    retry_backoff,
)
from gauss_tpu.serve.cache import CacheKey, ExecutableCache


class SolverServer:
    """In-process batched solver service (start() ... submit() ... stop()).

    The service boundary is a thread-safe Python API rather than a network
    socket: the interesting serving problems at this layer — batching,
    executable caching, admission, degradation — are transport-independent,
    and an RPC front end would wrap ``submit`` without changing any of them.
    """

    def __init__(self, config: Optional[ServeConfig] = None, *,
                 cache: Optional[ExecutableCache] = None):
        # ``is None``, not ``or``: the falsy-default anti-pattern silently
        # discards a falsy-but-valid operand (the PR-12 empty-cache bug);
        # gauss-lint's drift pass now bans the shape outright.
        self.config = config if config is not None else ServeConfig()
        self.ladder = buckets.validate_ladder(
            self.config.ladder or buckets.DEFAULT_LADDER)
        # ``cache``: share one executable cache across server incarnations
        # (the durable chaos campaign restarts dozens of servers; paying a
        # fresh compile set per incarnation would benchmark XLA, not the
        # recovery protocol). Default: the PROCESS-SHARED instance
        # (cache.shared_cache) — respawned/supervised servers and
        # multi-lane warmup stop paying duplicate compiles; pass an
        # explicit ExecutableCache for isolation.
        # ``is None``, not ``or``: an EMPTY shared cache is falsy
        # (len() == 0) and ``or`` would silently discard it.
        from gauss_tpu.serve import cache as _cache_mod

        self.cache = (cache if cache is not None
                      else _cache_mod.shared_cache(self.config.cache_capacity))
        self.health = LaneHealth(self.config.unhealthy_after,
                                 self.config.device_probe_cooldown_s)
        self._queue: "_queue.Queue[ServeRequest]" = _queue.Queue()
        self._depth = 0                   # guarded by: self._depth_lock
        self._depth_lock = threading.Lock()
        self._closed = False              # guarded by: self._depth_lock
        self._drain_rate = 0.0            # owned by: worker — EWMA req/s
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: the mesh serving plane (None = single-lane; config.lanes > 0
        #: builds a serve.lanes.LaneSet at start())
        self._lanes = None
        self._stats_lock = threading.Lock()  # batches/served under lanes
        self.batches = 0                  # guarded by: self._stats_lock
        self.requests_served = 0          # guarded by: self._stats_lock
        self.retries = 0                  # guarded by: self._stats_lock
        #: the live telemetry plane (None until start() with a live_port)
        self.live = None                  # obs.live.LiveAggregator
        self._live_server = None          # obs.export.LiveServer
        self._live_prev = None            # sink displaced by install()
        #: the crash-surviving flight recorder (None until start() with a
        #: flight_dir) — obs.flight.FlightSink
        self._flight = None
        #: the device-time attribution plane (None until start() with
        #: config.attr) — obs.attr.AttributionMatrix; /snapshot, the
        #: loadgen cost report, and per-request ServeResult.device_s /
        #: .compile_s all read it
        self.attr = None
        self._attr_prev = None            # matrix displaced by install()
        #: durable admission (None = journal off; the serve path is then
        #: byte-identical to the pre-journal behavior)
        self.journal = None               # serve.durable.RequestJournal
        self._rid_terminals: dict = {}    # idempotency key -> terminal doc
        self._rid_pending: dict = {}      # idempotency key -> in-flight req
        self._resumed = False             # replay runs once per journal open
        #: what the last start() recovery did (the campaign/test assert
        #: surface): {"replayed", "expired", "clean", ...}; None before
        #: any journaled start.
        self.last_resume = None
        self._hb_last = 0.0               # owned by: worker — hb throttle
        if self.config.journal_dir:
            from gauss_tpu.serve import durable as _durable

            self._durable = _durable
            self.journal = _durable.RequestJournal(
                self.config.journal_dir,
                fsync_batch=self.config.journal_fsync_batch,
                rotate_records=self.config.journal_rotate_records)
            self._rid_terminals = dict(self.journal.recovered.by_rid)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "SolverServer":
        if self._worker is not None and self._worker.is_alive():
            return self
        if self._lanes is not None:
            return self
        if self.config.live_port is not None and self._live_server is None:
            self._start_live()
        if self.config.flight_dir and self._flight is None:
            self._start_flight()
        if self.config.attr and self.attr is None:
            self._start_attr()
        self._stop.clear()
        with self._depth_lock:
            self._closed = False
        if self.config.lanes:
            # The mesh serving plane (gauss_tpu.serve.lanes): one async
            # dispatch lane per device / mesh slice instead of the single
            # worker — placement, stealing, continuous batching, and
            # autoscaling live there; admission/journal/verify stay here.
            from gauss_tpu.serve import lanes as _lanes

            self._lanes = _lanes.LaneSet(self).start()
            # Requests submitted before start() queued on the single-lane
            # queue (nobody was draining either way); hand them to the
            # lane set so they are owned by a lane, not orphaned.
            while True:
                try:
                    early = self._queue.get_nowait()
                except _queue.Empty:
                    break
                if early is not None and not self._lanes.place(early):
                    self._queue.put(early)  # pragma: no cover — closing
                    break
        else:
            self._worker = threading.Thread(target=self._run,
                                            name="gauss-serve", daemon=True)
            self._worker.start()
        if self.journal is not None and not self._resumed:
            self._resumed = True
            self._replay()
        return self

    def _start_live(self) -> None:
        """Bring up the live telemetry plane: aggregator installed as the
        process obs live sink + the embedded HTTP endpoint. Lazy imports:
        a server without a live_port never loads (or pays for) any of
        this."""
        from gauss_tpu.obs import export as _export
        from gauss_tpu.obs import live as _live
        from gauss_tpu.obs import slo as _slo

        cfg = self.config
        slos = cfg.slos or (_slo.default_serving_slo(),)
        self.live = _live.LiveAggregator(window=cfg.live_window, slos=slos)
        self._live_prev = _live.install(self.live)
        self._live_server = _export.LiveServer(
            self.live, port=cfg.live_port, host=cfg.live_host).start()
        obs.emit("live", event="listening", url=self._live_server.url,
                 slos=[m.slo.name for m in self.live.slos])

    def _stop_live(self) -> None:
        if self._live_server is not None:
            self._live_server.stop()
            self._live_server = None
        if self.live is not None:
            from gauss_tpu.obs import live as _live

            _live.uninstall(self._live_prev)
            self.live = None
            self._live_prev = None

    def _start_flight(self) -> None:
        """Bring up the crash-surviving flight recorder: the obs flight
        sink writing every event into ``flight_dir``'s mmap ring, plus the
        in-process post-mortem trigger (SLO firing / SDC escalation). Lazy
        imports — a ``flight_dir=None`` server never loads (or pays for)
        any of this, and its obs hot path is byte-identical pre-flight."""
        from gauss_tpu.obs import flight as _flight_mod
        from gauss_tpu.obs import postmortem as _postmortem

        cfg = self.config
        self._flight = _flight_mod.install(
            cfg.flight_dir, ring_bytes=cfg.flight_ring_bytes)
        _postmortem.install_trigger(
            _postmortem.default_bundles_dir(cfg.flight_dir),
            flight_dir=cfg.flight_dir, journal_dir=cfg.journal_dir,
            heartbeat_path=cfg.heartbeat_path,
            metrics_url=(self._live_server.url + "/metrics"
                         if self._live_server else None))
        obs.emit("flight", event="recording", dir=cfg.flight_dir,
                 ring_bytes=cfg.flight_ring_bytes)

    def _stop_flight(self) -> None:
        if self._flight is not None:
            from gauss_tpu.obs import flight as _flight_mod
            from gauss_tpu.obs import postmortem as _postmortem

            _postmortem.uninstall_trigger()
            _flight_mod.uninstall()
            self._flight = None

    def _start_attr(self) -> None:
        """Bring up the device-time attribution plane: a process
        AttributionMatrix (obs.attr) the dispatch paths below fold every
        blocked executable wall into, joined with compile-time FLOP/byte
        budgets into roofline ``util.*`` gauges and the per-compat-sig
        capacity model ``/snapshot`` exposes. Lazy imports — an
        ``attr=None`` server never loads (or pays for) any of this, and
        its dispatch path and traces are byte-identical pre-attribution
        behavior (one ``is None`` read per dispatch)."""
        from gauss_tpu.obs import attr as _attr

        self.attr = _attr.AttributionMatrix()
        self._attr_prev = _attr.install(self.attr)
        obs.emit("attr_plane", event="start", **self.attr.peaks.to_dict())

    def _stop_attr(self) -> None:
        if self.attr is not None:
            from gauss_tpu.obs import attr as _attr

            _attr.uninstall(self._attr_prev)
            self.attr = None
            self._attr_prev = None

    @property
    def live_url(self) -> Optional[str]:
        """The live endpoint base URL (None when the plane is off)."""
        return self._live_server.url if self._live_server else None

    @property
    def flight_sink(self):
        """The installed flight recorder sink (None when the plane is
        off) — the /snapshot exposition reads its ring position here."""
        return self._flight

    def lane_stats(self) -> Optional[dict]:
        """The mesh lane-set report (lanes/active/steals/cb_admits +
        per-lane served/stolen/occupancy) — None single-lane. The loadgen
        report and the mesh-serve-check gate both read this."""
        lanes = self._lanes
        return lanes.stats() if lanes is not None else None

    # -- durability (gauss_tpu.serve.durable) ------------------------------

    def _journal_terminal(self, req: ServeRequest, result) -> None:
        """The resolve() terminal hook (installed only on journaled
        requests): append the terminal record from the winning CAS, so the
        journal carries exactly one terminal per admit. Never raises into
        the resolver — a journaling failure is counted and surfaced, not
        allowed to turn a served result into a client-visible error."""
        try:
            doc = self.journal.append_terminal(
                id=req.journal_id, request_id=req.request_id,
                trace=req.trace_id, status=result.status, x=result.x,
                lane=result.lane, rel_residual=result.rel_residual,
                error=result.error)
            if req.request_id:
                self._rid_terminals[req.request_id] = doc
                self._rid_pending.pop(req.request_id, None)
        except Exception as e:  # noqa: BLE001 — durability must not break serving
            obs.counter("journal.errors")
            obs.emit("journal", event="append_error",
                     error=f"{type(e).__name__}: {e}"[:200])

    def _replay(self) -> None:
        """Crash -> restart recovery: push the journal's unterminated
        admits back through the normal dispatch path. In-deadline requests
        re-solve (and re-verify at the configured gate); past-deadline ones
        resolve as typed STATUS_EXPIRED terminals. Replayed requests keep
        their ORIGINAL journal ids and trace ids, so terminals pair with
        their admits and obs span trees complete across the crash. The
        admission bound is bypassed — these requests were already admitted
        once; re-rejecting them would forfeit their terminal."""
        st = self.journal.recovered
        if st.clean_shutdown or not self.config.resume:
            self.last_resume = {"replayed": 0, "expired": 0,
                                "clean": st.clean_shutdown,
                                "resume": self.config.resume,
                                "torn_dropped": st.torn_dropped}
            obs.emit("serve_resume", **self.last_resume)
            return
        if self.config.flight_dir and st.live_admits():
            # Crash detection at resume time: an unclean journal with
            # unterminated admits means the previous incarnation died
            # mid-flight — harvest its flight ring into a post-mortem
            # bundle BEFORE replay traffic overwrites the scene.
            try:
                from gauss_tpu.obs import postmortem as _postmortem

                _postmortem.capture_bundle(
                    _postmortem.default_bundles_dir(self.config.flight_dir),
                    "unclean_resume", flight_dir=self.config.flight_dir,
                    journal_dir=self.config.journal_dir,
                    heartbeat_path=self.config.heartbeat_path,
                    extra={"live_admits": len(st.live_admits()),
                           "torn_dropped": st.torn_dropped})
            except Exception:  # noqa: BLE001 — capture never blocks recovery
                obs.counter("postmortem.capture_errors")
        dec = self._durable.decode_array
        replayed = expired = poisoned = quarantined = 0
        # Blame-journal accounting: for every still-live admit, how many
        # DISTINCT prior process deaths (journal boots) dispatched it and
        # never reached its terminal. An id at/over the threshold is
        # quarantined — replay must not re-trigger the crash that killed
        # its predecessors.
        k_deaths = (self.config.quarantine_deaths
                    if self.config.journal_dir else 0)
        deaths = st.death_counts() if k_deaths else {}
        now = time.time()
        for doc in st.live_admits():
            try:
                a = dec(doc["a"])
                b = dec(doc["b"])
            except Exception:  # pragma: no cover — admit body damaged
                obs.counter("journal.replay_undecodable")
                continue
            if doc.get("was_vector"):
                b = b.reshape(-1)
            remaining = None
            if doc.get("deadline_unix") is not None:
                remaining = float(doc["deadline_unix"]) - now
            structure = (doc.get("structure")
                         if self.config.structure_aware else None)
            req = ServeRequest(
                a, b, deadline_s=(remaining if remaining is None
                                  or remaining > 0 else None),
                structure=structure,
                dtype=doc.get("dtype") or self.config.dtype,
                request_id=doc.get("rid"))
            req.journal_id = int(doc["id"])
            if doc.get("trace"):
                req.trace_id = str(doc["trace"])
            req._on_terminal = self._journal_terminal
            if req.request_id:
                # Replayed requests join the pending map too: a client
                # resubmitting its key DURING recovery attaches to the
                # replay instead of double-solving.
                self._rid_pending[req.request_id] = req
            if remaining is not None and remaining <= 0:
                expired += 1
                if req.resolve(ServeResult(
                        status=STATUS_EXPIRED,
                        error="deadline expired before recovery "
                              "(crash -> restart replay)")):
                    obs.counter("serve.resume_expired")
                    obs.emit("serve_request", id=req.journal_id, n=req.n,
                             trace=req.trace_id, status=STATUS_EXPIRED,
                             replayed=True)
                continue
            # Poison isolation at replay: the scan runs on every journaled
            # operand too (an admit journaled by an older/scan-off server,
            # or adopted from a peer, is exactly the payload a restart
            # would otherwise faithfully re-crash on).
            reason = (poison_scan(a, b) if self.config.poison_scan
                      else None)
            implicated = deaths.get(int(doc["id"]), 0)
            if reason is not None or (k_deaths
                                      and implicated > k_deaths):
                # Typed terminal instead of a replay: poisoned operands no
                # rung can repair, or a payload that kept killing workers
                # even after solo quarantine — either way re-dispatching it
                # is the crash loop. The terminal is journaled through the
                # normal hook, so the NEXT restart replays nothing.
                poisoned += 1
                err = (f"poisoned operands: {reason}" if reason is not None
                       else f"quarantined: implicated in {implicated} "
                            f"worker deaths (threshold {k_deaths})")
                if req.resolve(ServeResult(status=STATUS_POISON,
                                           error=err)):
                    obs.counter("serve.poisoned")
                    obs.emit("serve_request", id=req.journal_id, n=req.n,
                             trace=req.trace_id, status=STATUS_POISON,
                             replayed=True, deaths=implicated,
                             error=err[:200])
                continue
            if k_deaths and implicated >= k_deaths:
                # Quarantine: replay it, but SOLO on the host recovery
                # ladder — never co-batched (innocent batch-mates stay
                # safe), never on the device lane (the thing its deaths
                # implicate), and with no further blame append. One more
                # death pushes it over k_deaths into the typed reject
                # above — the ladder is finite by construction.
                req.quarantine = True
                quarantined += 1
                obs.counter("serve.quarantined")
                obs.emit("quarantine", id=req.journal_id,
                         rid=req.request_id, trace=req.trace_id,
                         deaths=implicated, action="solo")
            replayed += 1
            self._depth_add(1)
            if self._lanes is not None:
                self._lanes.place(req)
            else:
                self._queue.put(req)
            obs.counter("serve.replayed")
            obs.emit("serve_admit", id=req.journal_id, trace=req.trace_id,
                     n=req.n, k=req.k, replayed=True,
                     deadline_s=remaining)
        self.last_resume = {"replayed": replayed, "expired": expired,
                            "poisoned": poisoned,
                            "quarantined": quarantined,
                            "clean": False, "resume": True,
                            "torn_dropped": st.torn_dropped}
        obs.emit("serve_resume", **self.last_resume)

    def _crash(self) -> None:
        """CHAOS HOOK (not part of the serving API): die the way a kill at
        a batch boundary does. The worker finishes its in-flight batch
        (those terminals are journaled — a kill cannot unresolve them),
        then everything still queued is ABANDONED unresolved, the journal
        file handle is dropped with no fsync and no shutdown marker, and
        no terminal/flush bookkeeping runs. The in-process durable chaos
        campaign uses this where a subprocess would use os._exit."""
        self._stop.set()
        if self._lanes is not None:
            self._lanes.kill()      # abandon lane queues unresolved
            self._lanes = None
        self._queue.put(None)  # type: ignore[arg-type]
        if self._worker is not None:
            self._worker.join(timeout=60.0)
            self._worker = None
        if self.journal is not None:
            self.journal.abandon()
        self._stop_live()
        if self._flight is not None:
            from gauss_tpu.obs import postmortem as _postmortem
            from gauss_tpu.obs import spans as _spans

            # Dropped, not closed: a real kill writes no final sidecar —
            # the ring is left exactly as the crash left it.
            _spans.set_flight_sink(None)
            _postmortem.uninstall_trigger()
            self._flight = None

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the worker; with ``drain`` (default) requests accepted
        before the stop began are served first, otherwise they resolve as
        rejected.

        Every accepted request resolves with exactly one terminal status:
        admission closes FIRST (under the same lock submits enqueue under,
        so a submit is either fully before the close — and will be drained
        or flushed below — or fully after it and rejected synchronously in
        :meth:`submit`). Without the closed gate, a request enqueued during
        or after this method's final flush was simply dropped: never served,
        never resolved, a client blocked forever (the shutdown race
        tests/test_serve.py::test_stop_shutdown_race pins)."""
        with self._depth_lock:
            self._closed = True
        joined = True
        if self._lanes is not None:
            if drain:
                deadline = time.monotonic() + timeout
                while self._depth_snapshot() and time.monotonic() < deadline:
                    time.sleep(0.005)
            self._stop.set()
            leftovers, joined = self._lanes.stop(timeout=timeout)
            self._lanes = None
            # Leftovers (non-drain stop / drain timeout) are refused under
            # the same exactly-one-terminal contract as the queue flush
            # below — a lane-queued request can never be silently dropped.
            for req in leftovers:
                self._depth_add(-1)
                if req.resolve(ServeResult(status=STATUS_REJECTED,
                                           error="server stopped")):
                    obs.counter("serve.rejected")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=STATUS_REJECTED,
                             reason="server_stopped")
        elif self._worker is not None:
            if drain:
                deadline = time.monotonic() + timeout
                while self._depth_snapshot() and time.monotonic() < deadline:
                    time.sleep(0.005)
            self._stop.set()
            self._queue.put(None)  # type: ignore[arg-type] # wake the worker
            self._worker.join(timeout=timeout)
            joined = not self._worker.is_alive()
            self._worker = None
        else:
            self._stop.set()
        # Anything still queued (non-drain stop, drain timeout, or requests
        # that raced the drain window) is refused, not lost — no further
        # submit can enqueue once _closed is set, so this flush is final.
        while True:
            try:
                req = self._queue.get_nowait()
            except _queue.Empty:
                break
            if req is None:
                continue
            self._depth_add(-1)
            if req.resolve(ServeResult(status=STATUS_REJECTED,
                                       error="server stopped")):
                obs.counter("serve.rejected")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_REJECTED,
                         reason="server_stopped")
        if self.journal is not None and not self.journal.closed:  # lockset: ok — stop() is the only closer; close() re-checks under its lock
            # Graceful drain's final act: the clean-shutdown marker — but
            # only when the stop actually completed (worker joined). A
            # wedged worker might still be computing a journaled admit;
            # claiming "clean" would make the next start skip its replay.
            if joined:
                self.journal.append_shutdown()
            self.journal.close()
        self._stop_live()
        self._stop_flight()
        self._stop_attr()

    def __enter__(self) -> "SolverServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission --------------------------------------------------------

    def _depth_add(self, d: int) -> int:
        with self._depth_lock:
            self._depth += d
            depth = self._depth
        obs.gauge("serve.queue_depth", depth)
        return depth

    def _depth_snapshot(self) -> int:
        with self._depth_lock:
            return self._depth

    def retry_after_hint(self) -> float:
        """Seconds until a full queue has likely drained one batch's worth
        (from the EWMA drain rate; a floor keeps the hint meaningful before
        any batch has completed).

        With the mesh plane on, the rate is the LANE SET's aggregate
        (sum of the active lanes' EWMAs): the single global-queue rate
        over-estimates the wait once several lanes drain in parallel, and
        a client told to back off for the single-lane hint would sit out
        N-1 lanes' worth of capacity."""
        if self._lanes is not None:
            rate = max(self._lanes.drain_rate(), 1e-3)
        else:
            rate = max(self._drain_rate, 1e-3)  # lockset: ok — racy EWMA read; a hint, not state
        return round(min(60.0, max(0.01, self.config.max_batch / rate)), 4)

    def submit(self, a, b, deadline_s: Optional[float] = None,
               structure: Optional[str] = None,
               dtype: Optional[str] = None,
               request_id: Optional[str] = None) -> ServeRequest:
        """Enqueue one system. Returns the request handle immediately; a
        queue-full rejection resolves the handle synchronously with
        ``retry_after_s`` set (the client never blocks to learn it was
        refused).

        ``structure``: an optional routing tag (``gauss_tpu.structure``
        kinds). With ``config.structure_aware`` an untagged request is
        classified here (one O(n^2) scan against an O(n^3) solve); the tag
        keys batching and the executable cache, and certified-SPD batches
        take the Cholesky lane. Without ``structure_aware`` the tag is
        ignored — the pre-existing single-lane behavior.

        ``dtype``: the batched lane's storage dtype for this request
        ("float32" / "bfloat16" / "bf16x3" — core.lowered's ladder names);
        None takes ``config.dtype``. Requests batch only with same-dtype
        company and compile against their own ``CacheKey.dtype`` entry —
        mixed-precision traffic can never alias an f32 executable.

        ``request_id``: a client idempotency key (durable serving only —
        ignored without ``config.journal_dir``). Journaled with the admit;
        a resubmission whose key already holds a journaled terminal
        resolves from the journal — same status, same solution — WITHOUT
        re-solving, which is what makes crash recovery exactly-once from
        the client's view."""
        jr = self.journal
        lanes = self._lanes  # snapshot: a concurrent stop() nulls the attr
        if jr is not None and request_id:
            pending = self._rid_pending.get(request_id)
            if pending is not None:
                # The key is already IN FLIGHT (admitted, or replayed by
                # recovery, not yet terminal): attach the resubmission to
                # the live request instead of admitting a duplicate —
                # without this, a client retrying while recovery replays
                # its backlog would double-solve (and double-terminal) the
                # same logical request. Same handle, same single terminal.
                obs.counter("serve.deduped_pending")
                obs.emit("serve_dedup", request_id=request_id,
                         trace=pending.trace_id, pending=True)
                return pending
            term = self._rid_terminals.get(request_id)
            if term is not None:
                # Idempotent resubmission: the journaled terminal answers.
                # A fresh trace is minted (this is a NEW client
                # interaction) and carries exactly one terminal event —
                # the dedupe, not a second solve.
                req = ServeRequest(a, b, deadline_s=deadline_s,
                                   request_id=request_id)
                if req.resolve(self._durable.terminal_to_result(term)):
                    obs.counter("serve.deduped")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id,
                             status=term.get("status"), deduped=True,
                             request_id=request_id)
                return req
        if deadline_s is None:
            deadline_s = self.config.deadline_default_s
        if self.config.poison_scan:
            # Admission hardening: the operand scan runs BEFORE the journal
            # admit, so a poisoned submit resolves a typed STATUS_POISON
            # terminal synchronously and leaves NO journal record — a
            # restart can never replay it, so a poison submit cannot
            # crash-loop a replica by construction. Shape errors below stay
            # plain ValueError (programming errors, not poison).
            reason = poison_scan(a, b)
            if reason is not None:
                req = ServeRequest(a, b, deadline_s=deadline_s,
                                   request_id=request_id)
                if req.resolve(ServeResult(
                        status=STATUS_POISON,
                        error=f"poisoned operands: {reason}")):
                    obs.counter("serve.poisoned")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=STATUS_POISON,
                             reason="admission_scan", error=reason,
                             request_id=request_id)
                return req
        if self.config.structure_aware and structure is None:
            from gauss_tpu.structure import structure_tag

            structure = structure_tag(a)
        if not self.config.structure_aware:
            structure = None
        req = ServeRequest(a, b, deadline_s=deadline_s, structure=structure,
                           dtype=dtype or self.config.dtype,
                           request_id=request_id)
        # SLO-degraded admission (slo_shed): while a burn-rate alert FIRES,
        # the effective queue bound shrinks, so load is turned away while
        # the error budget is bleeding — shedding starts BEFORE the
        # deadline cliff instead of at it. One boolean read when the live
        # plane is off.
        bound = self.config.max_queue
        degraded = (self.config.slo_shed and self.live is not None
                    and self.live.slo_firing())
        if degraded:
            bound = int(bound * self.config.degraded_queue_factor)
        # Admission is ONE critical section: the closed/full check and the
        # enqueue happen under the lock stop() closes admission under, so a
        # request is either enqueued strictly before the close (stop's
        # drain/flush owns it) or rejected here — there is no window where
        # an accepted request can miss both and hang its client.
        dup = None
        with self._depth_lock:
            closed = self._closed
            if not closed and jr is not None and request_id:
                # Re-check the pending map INSIDE the critical section: the
                # lock-free check above and this insert are not atomic, and
                # both a concurrent double-submit and a failover adoption
                # (net.adopt_journal inserts pending entries under this
                # same lock) can land the key between them. Losing the race
                # here would journal a second admit for one logical request
                # — two solves, two terminals.
                dup = self._rid_pending.get(request_id)
            full = (not closed and dup is None and self._depth >= bound)
            if not closed and dup is None and not full:
                if jr is not None:
                    # Write-ahead: the admit is journaled (and the
                    # terminal hook installed) INSIDE the admission
                    # critical section, strictly before the request
                    # becomes visible to the worker — so a terminal can
                    # never precede its admit in the journal, and journal
                    # admit order is queue order. Without a journal this
                    # branch costs one is-None check.
                    jr.append_admit(
                        id=req.id, request_id=request_id,
                        trace=req.trace_id, a=req.a, b=req.b,
                        was_vector=req.was_vector,
                        deadline_unix=req.deadline_unix,
                        dtype=req.dtype, structure=req.structure)
                    req._on_terminal = self._journal_terminal
                    if request_id:
                        self._rid_pending[request_id] = req
                self._depth += 1
                if lanes is None:
                    self._queue.put(req)
        if dup is not None:
            obs.counter("serve.deduped_pending")
            obs.emit("serve_dedup", request_id=request_id,
                     trace=dup.trace_id, pending=True, raced=True)
            return dup
        if not closed and not full and lanes is not None:
            # Lane placement happens OUTSIDE the depth lock (it takes
            # per-lane locks; the worker threads take those and then the
            # depth lock — nesting them here would order locks both
            # ways). The accounting hole is closed on the other side: a
            # place() refused by a closing lane set is rejected right
            # here, and one that lands is owned by stop()'s leftover
            # collection — either way exactly one terminal.
            if not lanes.place(req):
                self._depth_add(-1)
                closed = True
        if closed:
            if req.resolve(ServeResult(status=STATUS_REJECTED,
                                       error="server stopped")):
                obs.counter("serve.rejected")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_REJECTED,
                         reason="server_stopped")
            return req
        if full:
            hint = self.retry_after_hint()
            reason = "slo_degraded" if degraded else "queue_full"
            if req.resolve(ServeResult(status=STATUS_REJECTED,
                                       retry_after_s=hint,
                                       error="queue full"
                                             + (" (slo degraded)"
                                                if degraded else ""))):
                obs.counter("serve.rejected")
                if degraded:
                    obs.counter("serve.slo_shed")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_REJECTED,
                         reason=reason, retry_after_s=hint,
                         queue_depth=self._depth_snapshot())
            return req
        obs.counter("serve.submitted")
        obs.emit("serve_admit", id=req.id, trace=req.trace_id, n=req.n,
                 k=req.k, queue_depth=self._depth_snapshot(),
                 deadline_s=deadline_s,
                 **({"structure": structure} if structure else {}))
        return req

    def solve(self, a, b, deadline_s: Optional[float] = None,
              timeout: Optional[float] = 300.0,
              dtype: Optional[str] = None,
              request_id: Optional[str] = None) -> ServeResult:
        """Synchronous convenience: submit + wait."""
        return self.submit(a, b, deadline_s=deadline_s, dtype=dtype,
                           request_id=request_id).result(timeout)

    # -- worker loop ------------------------------------------------------

    def _run(self) -> None:
        # lockset: thread worker — the single-lane dispatch loop
        hb_path = self.config.heartbeat_path
        while not self._stop.is_set():
            if hb_path is not None:
                self._heartbeat(hb_path)
            try:
                req = self._queue.get(timeout=0.1)
            except _queue.Empty:
                continue
            if req is None:
                continue
            batch = [req]
            if req.n <= self.ladder[-1]:
                batch.extend(self._drain_same_bucket(req))
            self._depth_add(-len(batch))
            if _inject.enabled():
                # Hook point "serve.worker.dispatch": injected worker stall
                # (deadline pressure — expired requests must shed, not hang).
                _inject.maybe_delay("serve.worker.dispatch")
            t0 = time.perf_counter()
            served = self._dispatch(batch)
            dt = time.perf_counter() - t0
            if dt > 0 and served:
                inst = served / dt
                self._drain_rate = (0.7 * self._drain_rate + 0.3 * inst
                                    if self._drain_rate else inst)
            if _inject.enabled():
                # Hook point "serve.server.batch": the batch BOUNDARY —
                # the in-flight batch's terminals are journaled, the rest
                # of the queue is not yet served. Kind "server_kill"
                # os._exits here (the durable campaign's crash site).
                _inject.maybe_kill("serve.server.batch")

    def _heartbeat(self, path: str) -> None:
        # lockset: thread worker — called only from the dispatch loop
        # (single-lane _run, or lane 0 of the mesh plane; never both)
        """Supervisor liveness (durable.supervise): touch the heartbeat
        file from the worker loop, throttled — a wedged worker stops
        touching it and the supervisor calls the stall."""
        now = time.monotonic()
        if now - self._hb_last < 0.5:
            return
        self._hb_last = now
        try:
            with open(path, "w") as f:
                f.write(json.dumps({"pid": os.getpid(),
                                    "time_unix": time.time(),
                                    "batches": self.batches}))  # lockset: ok — stats snapshot for liveness
        except OSError:  # pragma: no cover — liveness must not kill serving
            pass

    def _drain_same_bucket(self, first: ServeRequest):
        """Collect queued requests that share ``first``'s size bucket — and,
        in structure-aware mode, its structure tag (an SPD batch must stay
        all-SPD to take the Cholesky executable) — up to max_batch,
        optionally lingering for late same-bucket arrivals.
        Different-bucket requests go straight back on the queue (order among
        survivors is preserved by the FIFO)."""
        want = buckets.bucket_for(first.n, self.ladder)
        got, requeue = [], []
        deadline = time.monotonic() + self.config.batch_linger_s
        while len(got) + 1 < self.config.max_batch:
            try:
                nxt = self._queue.get_nowait()
            except _queue.Empty:
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.001)
                continue
            if nxt is None:
                continue
            if (nxt.n <= self.ladder[-1]
                    and buckets.bucket_for(nxt.n, self.ladder) == want
                    and nxt.structure == first.structure
                    and nxt.dtype == first.dtype):
                got.append(nxt)
            else:
                requeue.append(nxt)
        for r in requeue:
            self._queue.put(r)
        return got

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, batch, lane=None) -> int:
        """Serve one same-bucket batch (or one oversized request); returns
        the number of requests resolved. ``lane``: the dispatching mesh
        lane (serve.lanes) — carries the device placement and takes the
        per-lane stats; None is the single-lane worker."""
        now = time.perf_counter()
        live = []
        solo = []
        for req in batch:
            if req.done:
                # Cancelled while queued (result-timeout propagation): the
                # client already holds the terminal status; skip the work.
                obs.counter("serve.cancelled_skipped")
                continue
            if req.expired(now):
                if req.resolve(ServeResult(status=STATUS_EXPIRED,
                                           error="deadline expired before "
                                                 "compute")):
                    obs.counter("serve.expired")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=STATUS_EXPIRED)
            elif req.quarantine:
                solo.append(req)
            else:
                live.append(req)
        if (live or solo) and self.journal is not None:
            # Blame record BEFORE the dispatch: if this process dies while
            # the batch is in flight, the restart's replay knows exactly
            # which ids were being executed when the lights went out — the
            # evidence the quarantine policy counts deaths from (one death
            # per DISTINCT journal boot). Quarantined solos are blamed too:
            # a death during solo execution pushes them past the threshold
            # into the typed reject, so the quarantine ladder is finite.
            # One compact append per dispatch; a torn blame simply drops at
            # scan (CRC), costing evidence, never correctness.
            try:
                self.journal.append_blame(
                    ids=[r.journal_id for r in live + solo],
                    rids=[r.request_id for r in live + solo
                          if r.request_id])
            except Exception as e:  # noqa: BLE001 — durability must not break serving
                obs.counter("journal.errors")
                obs.emit("journal", event="append_error",
                         error=f"{type(e).__name__}: {e}"[:200])
        for req in solo:
            # Quarantined: solo host-ladder execution — never co-batched
            # (batch-mates stay innocent), never the device lane (the lane
            # its deaths implicate).
            self._serve_numpy(req)
        if not live:
            return len(batch)
        if live[0].n > self.ladder[-1]:
            for req in live:
                self._serve_handoff(req)
            return len(batch)
        self._serve_batched(live, lane=lane)
        return len(batch)

    def _serve_batched(self, reqs, lane=None, hunt=False) -> None:
        cfg = self.config
        if reqs[0].structure == "sparse":
            # The sparse compat sig keeps these batches homogeneous (drain
            # compatibility); the iterative lane has no padded dense
            # executable to share, so members run the per-request Krylov
            # ladder instead of the bucketed dispatch.
            self._serve_sparse(reqs, lane=lane)
            return
        bucket_n = buckets.bucket_for(reqs[0].n, self.ladder)
        nrhs = buckets.pow2_bucket(max(r.k for r in reqs))
        # Mesh lanes serve a FIXED batch slot (always max_batch, identity-
        # padded): jax compiles one executable per (key, placement), so a
        # pow2 ladder of batch shapes would multiply the per-LANE backend
        # compiles by its length — the fixed slot caps them at one per
        # ladder rung per lane, all paid in lane warmup. Filling the slot
        # is then exactly what continuous batching is for. The single-
        # lane path keeps the pre-existing pow2 batch bucketing.
        bb = (cfg.max_batch if lane is not None
              else buckets.pow2_bucket(len(reqs), cap=cfg.max_batch))
        # Batch-level records carry the identity of EVERY member request
        # (the trace_id list + the request count), so per-request serving
        # percentiles and span trees are computable from per-batch spans —
        # before this, serve_batch_* spans had no request identity at all.
        traces = [r.trace_id for r in reqs]
        # dtype was already a CacheKey field (PR 3); the precision choice
        # now actually varies it — batches are dtype-homogeneous (drain
        # compatibility above), so f32 and lowered executables can never
        # alias one cache entry.
        key = CacheKey(bucket_n=bucket_n, nrhs=nrhs, batch=bb,
                       dtype=reqs[0].dtype or "float32", engine=cfg.engine,
                       refine_steps=cfg.refine_steps, mesh=None,
                       structure=reqs[0].structure)

        allowed = self.health.device_allowed()
        obs.gauge("serve.breaker_open", 0.0 if allowed else 1.0)
        if not allowed:
            obs.counter("serve.fallback_batches")
            for req in reqs:
                self._serve_numpy(req)
            return

        with obs.span("serve_batch_pad", bucket_n=bucket_n, batch=len(reqs),
                      requests=len(reqs), traces=traces):
            a_pad = np.empty((bb, bucket_n, bucket_n), dtype=np.float64)
            b_pad = np.zeros((bb, bucket_n, nrhs), dtype=np.float64)
            for i, req in enumerate(reqs):
                a_pad[i], b_pad[i] = buckets.pad_system(
                    req.a.astype(np.float64), req.b.astype(np.float64),
                    bucket_n, nrhs)
            for i in range(len(reqs), bb):  # batch padding: identity systems
                a_pad[i] = np.eye(bucket_n)

        # Mesh lane dispatch: the executable comes through the lane's
        # view of the ONE shared cache (build/warmup paid once across
        # lanes — racing warmups coalesce) and the operand stacks are
        # placed on the lane's device / sharded over its mesh slice.
        placement = lane.placement_for(bb) if lane is not None else None
        t0 = time.perf_counter()
        x = None
        exe = None
        get_s = solve_s = 0.0
        err: Optional[BaseException] = None
        for attempt in range(cfg.max_retries + 1):
            try:
                t_get = time.perf_counter()
                exe = (lane.cache_view.get(key, panel=cfg.panel)
                       if lane is not None
                       else self.cache.get(key, panel=cfg.panel))
                t_solve = time.perf_counter()
                with obs.span("serve_batch_solve", bucket_n=bucket_n,
                              batch=len(reqs), requests=len(reqs),
                              traces=traces):
                    x = exe.solve(a_pad, b_pad, placement=placement)
                solve_s = time.perf_counter() - t_solve
                get_s = t_solve - t_get
                err = None
                break
            except Exception as e:  # noqa: BLE001 — lane boundary
                err = e
                if not is_transient_device_error(e):
                    break
                with self._stats_lock:
                    self.retries += 1
                obs.counter("serve.retries")
                obs.emit("serve_retry", attempt=attempt, bucket_n=bucket_n,
                         requests=len(reqs), traces=traces,
                         error=f"{type(e).__name__}: {e}"[:200])
                if attempt < cfg.max_retries:
                    time.sleep(retry_backoff(cfg.retry_backoff_s, attempt))
        batch_s = time.perf_counter() - t0

        if x is None:
            transient = err is not None and is_transient_device_error(err)
            if transient and self.health.record_failure():
                obs.emit("serve_fallback", lane="numpy",
                         reason="device lane unhealthy",
                         cooldown_s=cfg.device_probe_cooldown_s)
            if transient:
                # Degrade THIS batch to the host lane rather than failing
                # user requests over a device-side hiccup.
                for req in reqs:
                    self._serve_numpy(req)
                return
            if cfg.bisect_batches and len(reqs) > 1:
                # Batch bisection: a NON-transient failure of a multi-
                # member batch names no culprit — never fail the whole
                # batch for one member. Split and re-dispatch each half
                # (O(log B) re-dispatches isolate the culprit set):
                # innocents re-serve through this same path under their
                # ORIGINAL journal/trace ids and deadlines (exactly one
                # terminal, resolve's CAS unchanged); a member that still
                # fails alone is the culprit and is terminal-rejected
                # typed below.
                obs.counter("serve.bisections")
                obs.emit("serve_bisect", bucket_n=bucket_n,
                         requests=len(reqs), traces=traces,
                         error=f"{type(err).__name__}: {err}"[:200])
                mid = len(reqs) // 2
                self._serve_batched(reqs[:mid], lane=lane, hunt=True)
                self._serve_batched(reqs[mid:], lane=lane, hunt=True)
                return
            # A batch of one failing non-transiently: with bisection on,
            # the member itself is the fault — a typed poison terminal,
            # never a worker death and never a batch-mate casualty. The
            # pre-bisection whole-batch STATUS_FAILED shape is kept for
            # bisect_batches=False and for top-level singletons (a lone
            # deterministic error is indistinguishable from a server bug;
            # only the hunt proves the batch-relative blame).
            culprit = hunt and cfg.bisect_batches
            status = STATUS_POISON if culprit else STATUS_FAILED
            for req in reqs:
                if req.resolve(ServeResult(
                        status=status, lane="batched",
                        bucket_n=bucket_n,
                        error=(("poison batch member: " if culprit else "")
                               + f"{type(err).__name__}: {err}"))):
                    obs.counter("serve.poisoned" if culprit
                                else "serve.failed")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=status,
                             lane="batched", bisected=hunt,
                             error=f"{type(err).__name__}: {err}"[:200])
            return

        self.health.record_success()
        obs.gauge("serve.breaker_open", 0.0)
        with self._stats_lock:
            self.batches += 1
        occupancy = len(reqs) / bb
        if lane is not None:
            lane.note_batch(len(reqs), occupancy,
                            device_s=(solve_s if self.attr is not None
                                      else 0.0))
        if self.attr is not None:
            self._attr_batch(reqs, key, bb, lane, solve_s, get_s, exe)
        obs.counter("serve.batches")
        obs.histogram("serve.batch_occupancy", occupancy)
        obs.emit("serve_batch", bucket_n=bucket_n, nrhs=nrhs,
                 batch=len(reqs), batch_bucket=bb, occupancy=occupancy,
                 seconds=round(batch_s, 6), requests=len(reqs),
                 traces=traces,
                 **({"lane": lane.idx} if lane is not None else {}),
                 **({"structure": reqs[0].structure}
                    if reqs[0].structure else {}))
        for i, req in enumerate(reqs):
            xi = buckets.unpad_solution(x[i], req.n, req.k, req.was_vector)
            self._finish(req, xi, lane="batched", bucket_n=bucket_n)

    # -- device-time attribution (gauss_tpu.obs.attr) ----------------------

    def _attr_batch(self, reqs, key, bb, lane, solve_s: float, get_s: float,
                    exe) -> None:
        """Fold one served batch into the attribution matrix and spread its
        cost over the member requests: each rider owes an equal share of
        the blocked solve wall (device-seconds) and of the cache-get wall
        (amortized compile-seconds — ~0 on a hit, the executable build on
        the miss that created the entry). Called only with the plane on;
        never raises — attribution must not take down serving."""
        try:
            share = solve_s / len(reqs)
            cshare = get_s / len(reqs)
            for req in reqs:
                req.cost_device_s += share
                req.cost_compile_s += cshare
            cost = exe.cost_budget() if exe is not None else {}
            engine = "cholesky" if key.structure == "spd" else key.engine
            exe_label = (f"{engine}/b{key.bucket_n}x{bb}/r{key.nrhs}"
                         f"/{key.dtype}")
            sig = f"b{key.bucket_n}/{key.dtype}" + (
                f"/{key.structure}" if key.structure else "")
            self.attr.observe(
                "serve_batch_solve", exe_label, solve_s, engine=engine,
                lane=lane.idx if lane is not None else 0,
                requests=len(reqs), flops=cost.get("flops"),
                bytes_accessed=cost.get("bytes_accessed"),
                compile_s=get_s, sig=sig)
        except Exception:  # noqa: BLE001 — attribution must not break serving
            obs.counter("attr.errors")

    def _attr_single(self, req: ServeRequest, phase: str, engine: str,
                     seconds: float) -> None:
        """Attribute one single-request lane dispatch (handoff / fleet /
        outofcore / abft / numpy): the request owes the whole blocked
        wall; the matrix gets a roofline row for the engine with the
        analytic LU budget (the single-request lanes have no cached
        executable to ask XLA about). Never raises."""
        try:
            from gauss_tpu.obs import attr as _attr

            req.cost_device_s += seconds
            self.attr.observe(
                phase, f"{engine}/n{req.n}", seconds, engine=engine,
                requests=1,
                flops=_attr.lu_flop_budget(req.n, req.k),
                bytes_accessed=_attr.lu_byte_budget(req.n, req.k,
                                                    itemsize=8),
                sig=f"{phase}/{engine}")
        except Exception:  # noqa: BLE001 — attribution must not break serving
            obs.counter("attr.errors")

    def _serve_handoff(self, req: ServeRequest) -> None:
        """Oversized lane: one solve_handoff call per request (the routing
        decision itself is emitted by solve_handoff as a ``route`` event).
        With ``supervised_handoff`` the single-RHS case routes through the
        fleet supervisor instead — the long solve survives worker loss
        (restart/resume from the sharded checkpoint, elastic degrade) where
        a plain handoff would die with its process."""
        from gauss_tpu.core import blocked

        cfg = self.config
        lane = "handoff"
        sdc_detected = False
        t0 = time.perf_counter()
        try:
            # The trace context stamps every event emitted below us —
            # solve_handoff's route decision, fleet supervision events —
            # with this request's trace id, no parameter threading needed.
            with obs.trace_context(req.trace_id), \
                    obs.span("serve_handoff", n=req.n):
                if cfg.supervised_handoff and req.was_vector:
                    from gauss_tpu.resilience import fleet

                    lane = "fleet"
                    obs.emit("route", tool="serve_handoff", lane="fleet",
                             n=req.n, workers=cfg.fleet_workers)
                    x = fleet.solve_supervised(
                        req.a.astype(np.float64), req.b.astype(np.float64),
                        workers=cfg.fleet_workers, panel=cfg.panel,
                        refine_iters=max(2, cfg.refine_steps)).x
                elif (cfg.outofcore_handoff
                      and not blocked.fits_single_chip(
                          req.n, budget=cfg.device_budget)):
                    # Giant-request lane (ISSUE 13): the working set
                    # exceeds the device budget, so the request streams
                    # from host memory through the out-of-core rung —
                    # under the recovery ladder, so a streamed failure
                    # (SDC detection, admission) escalates to the host
                    # LAPACK tail instead of failing the request.
                    from gauss_tpu.resilience import recover

                    lane = "outofcore"
                    obs.emit("route", tool="serve_handoff",
                             lane="outofcore", n=req.n,
                             budget=cfg.device_budget)
                    rr = recover.solve_resilient(
                        req.a.astype(np.float64),
                        req.b.astype(np.float64),
                        rungs=("outofcore", "numpy_f64"), panel=cfg.panel,
                        refine_iters=max(2, cfg.refine_steps))
                    x = rr.x
                elif cfg.abft and blocked.fits_single_chip(req.n):
                    # ABFT-protected single-chip lane: the checksum-
                    # carrying ladder detects mid-solve corruption within
                    # one panel group and repairs it by localized replay;
                    # the request is tagged when that happened.
                    from gauss_tpu.resilience import recover

                    obs.emit("route", tool="serve_handoff", lane="abft",
                             n=req.n)
                    rr = recover.solve_resilient(
                        req.a.astype(np.float64), req.b.astype(np.float64),
                        abft=True, panel=cfg.panel,
                        refine_iters=max(2, cfg.refine_steps))
                    x = rr.x
                    sdc_detected = rr.sdc_detected
                else:
                    x = blocked.solve_handoff(
                        req.a.astype(np.float64), req.b.astype(np.float64),
                        budget=cfg.device_budget,
                        panel=cfg.panel, iters=max(2, cfg.refine_steps))
        except Exception as e:  # noqa: BLE001 — lane boundary
            if req.resolve(ServeResult(status=STATUS_FAILED, lane=lane,
                                       error=f"{type(e).__name__}: {e}")):
                obs.counter("serve.failed")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_FAILED, lane=lane,
                         error=f"{type(e).__name__}: {e}"[:200])
            return
        if self.attr is not None:
            self._attr_single(req, "serve_handoff", lane,
                              time.perf_counter() - t0)
        self._finish(req, np.asarray(x), lane=lane, bucket_n=None,
                     sdc_detected=sdc_detected)

    def _serve_sparse(self, reqs, lane=None) -> None:
        """The sparse serving lane (``structure="sparse"`` compat sig):
        every member runs the Krylov recovery ladder — CG for certified
        operands, GMRES/BiCGStab for general, the dense chain only past
        all three — under its own trace context, then the SAME
        ``_finish`` verify-gate/terminal path as the batched lanes.
        Iteration telemetry rides the ``sparse_solve`` events the rungs
        emit inside each request's span tree."""
        from gauss_tpu.resilience import recover

        gate = self.config.verify_gate or recover.DEFAULT_GATE
        obs.emit("route", tool="serve", lane="sparse", requests=len(reqs))
        for req in reqs:
            t0 = time.perf_counter()
            try:
                with obs.trace_context(req.trace_id), \
                        obs.span("serve_sparse", n=req.n):
                    rr = recover.solve_resilient(
                        req.a.astype(np.float64), req.b.astype(np.float64),
                        gate=gate, rungs=recover.structured_rungs("sparse"))
                x = rr.x
            except Exception as e:  # noqa: BLE001 — lane boundary
                if req.resolve(ServeResult(
                        status=STATUS_FAILED, lane="sparse",
                        error=f"{type(e).__name__}: {e}")):
                    obs.counter("serve.failed")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=STATUS_FAILED,
                             lane="sparse",
                             error=f"{type(e).__name__}: {e}"[:200])
                continue
            if self.attr is not None:
                self._attr_single(req, "serve_sparse", "sparse",
                                  time.perf_counter() - t0)
            self._finish(req, x, lane="sparse", bucket_n=None)

    def _serve_numpy(self, req: ServeRequest) -> None:
        """Degraded host lane, through the SAME recovery ladder the solver
        stack uses (gauss_tpu.resilience.recover) rather than the ad-hoc
        one-shot ``np.linalg.solve`` it used to be: the host LAPACK rung
        first (the device lane is the thing that is sick), escalating to the
        rank-1 device engine if even LAPACK cannot pass the gate — and a
        TYPED UnrecoverableSolveError, with recovery events in the stream,
        when nothing can."""
        from gauss_tpu.resilience import recover

        gate = self.config.verify_gate or recover.DEFAULT_GATE
        t0 = time.perf_counter()
        try:
            # recover.solve_resilient emits per-rung ``recovery`` events;
            # the trace context stamps them with this request's identity so
            # the recovery ladder shows up inside the request's span tree.
            with obs.trace_context(req.trace_id), \
                    obs.span("serve_numpy", n=req.n):
                rr = recover.solve_resilient(
                    req.a.astype(np.float64), req.b.astype(np.float64),
                    gate=gate, rungs=("numpy_f64", "rank1"))
            x = rr.x
        except Exception as e:  # noqa: BLE001 — lane boundary
            # Typed poison verdicts from the ladder: an exactly-singular
            # system (the f64 rung's LAPACK zero pivot — a property of the
            # REQUEST) or non-finite input no rung can repair. Everything
            # else stays the generic failed terminal.
            poison = (isinstance(e, recover.SingularSystemError)
                      or getattr(e, "trigger", None) == "nonfinite_input")
            status = STATUS_POISON if poison else STATUS_FAILED
            if req.resolve(ServeResult(status=status, lane="numpy",
                                       error=f"{type(e).__name__}: {e}")):
                obs.counter("serve.poisoned" if poison else "serve.failed")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=status,
                         lane="numpy",
                         error=f"{type(e).__name__}: {e}"[:200])
            return
        if self.attr is not None:
            self._attr_single(req, "serve_numpy", "numpy",
                              time.perf_counter() - t0)
        self._finish(req, x, lane="numpy", bucket_n=None)

    def _finish(self, req: ServeRequest, x: np.ndarray, lane: str,
                bucket_n: Optional[int], sdc_detected: bool = False) -> None:
        rel = None
        if (lane == "batched" and self.config.poison_scan
                and not bool(np.isfinite(x).all())):
            # A NaN/Inf solution out of the batched lane is the member's
            # own numerics (a singular system survives the finite-operand
            # admission scan and poisons only its own vmap row) — re-run
            # it SOLO on the host recovery ladder, which either serves it
            # verified or returns the typed singular verdict
            # (STATUS_POISON). Unconditional on `verify_gate`: a
            # non-finite solution is detectable for free and must never
            # resolve `ok`, gate or no gate.
            obs.counter("serve.nonfinite_rescues")
            self._serve_numpy(req)
            return
        if self.config.verify_gate is not None:
            from gauss_tpu.verify import checks

            rel = checks.residual_norm(req.a, x, req.b, relative=True)
            if not rel <= self.config.verify_gate:
                if req.resolve(ServeResult(
                        status=STATUS_FAILED, lane=lane, bucket_n=bucket_n,
                        rel_residual=rel,
                        error=f"relative residual {rel:.3e} exceeds the "
                              f"{self.config.verify_gate:.0e} verify gate")):
                    obs.counter("serve.failed")
                    obs.emit("serve_request", id=req.id, n=req.n,
                             trace=req.trace_id, status=STATUS_FAILED,
                             lane=lane, rel_residual=rel,
                             error="verify gate")
                return
        queue_s = time.perf_counter() - req.t_submit
        # Per-request cost accounting (ServeConfig.attr): attach the
        # accumulated device/compile seconds to the terminal result and
        # event. With the plane off, cost is {} — the result and trace
        # are byte-identical to the pre-attribution shape.
        cost = ({"device_s": round(req.cost_device_s, 6),
                 "compile_s": round(req.cost_compile_s, 6)}
                if self.attr is not None else {})
        if not req.resolve(ServeResult(status=STATUS_OK, x=x, lane=lane,
                                       bucket_n=bucket_n, queue_s=queue_s,
                                       rel_residual=rel,
                                       sdc_detected=sdc_detected, **cost)):
            return  # cancelled mid-compute: the client owns the terminal
        with self._stats_lock:
            self.requests_served += 1
        obs.counter("serve.served")
        if sdc_detected:
            obs.counter("serve.sdc_detected")
        obs.histogram("serve.latency_s", queue_s)
        obs.emit("serve_request", id=req.id, n=req.n, k=req.k,
                 trace=req.trace_id, status=STATUS_OK, lane=lane,
                 bucket_n=bucket_n, latency_s=round(queue_s, 6),
                 rel_residual=rel,
                 **({"sdc_detected": True} if sdc_detected else {}),
                 **cost)
