"""Open/closed-loop load generator + report for the serving layer.

The reference's evaluation story is one matrix per process launch; a serving
system is evaluated under TRAFFIC. This module replays a workload mix against
an in-process :class:`SolverServer` and reports what the serving literature
reports: throughput, p50/p95/p99 latency, batch occupancy, and cache
hit-rate — all recomputed from numbers the server already emitted as obs
events, and exportable as a regress-sentinel record so serving performance
is gated the same way solve performance is (``reports/history.jsonl``).

Workload mixes are comma-separated weighted tokens::

    random:100*3,internal:256,dat:/path/to/jpwh_991.dat,dataset:orsirr_1

- ``random:<n>`` — diagonally-dominant dense random system (well-
  conditioned; the serving analog of the bench sweeps' rng systems).
- ``internal:<n>`` — the reference's internal benchmark matrix
  (io.synthetic.internal_matrix, known closed-form solution).
- ``dat:<path>`` — a reference-format ``.dat`` file, RHS manufactured
  as the external programs do (io.synthetic.manufactured_rhs).
- ``dataset:<name>`` — an io.datasets stand-in by name (the committed
  deterministic doubles of the reference Harwell-Boeing set).
- ``spd:<n>`` / ``banded:<n>/<b>`` / ``blockdiag:<n>/<k>`` — the
  structured generators (io.synthetic.spd_matrix / banded_matrix /
  blockdiag_matrix), so a mix can drive the structure-aware serving
  lanes (``ServeConfig(structure_aware=True)``) and the chaos campaign
  end to end; ``<b>``/``<k>`` default to 1 / n // 8.
- ``sparse:<n>/<nnz_per_row>`` — the sparse-plane generator
  (io.synthetic.sparse_matrix, ``<nnz_per_row>`` default 8): a
  Gershgorin-certified low-density system that a structure-aware server
  routes to the Krylov lane (``gauss_tpu.sparse``). Loadgen operands are
  in-memory ndarrays, so ``<n>`` is capped at the generator's 4096
  densify limit — the scalable no-densify path is exercised by
  ``gauss_tpu.sparse.check``, not by serving traffic.
- ``dtype:<dt>/<n>`` — a diagonally-dominant random system (like
  ``random:<n>``) submitted with a per-request storage dtype
  (``bfloat16`` / ``bf16x3`` / ``float32`` — core.lowered's ladder
  names), so a mix can drive the LOWERED batched lanes
  (``submit(dtype=...)`` -> ``CacheKey.dtype``) alongside f32 traffic
  and prove the executables never alias; every solution still passes
  the same 1e-4 verification below.

Two driving modes: **closed** loop (``concurrency`` clients, each submits,
waits, repeats — throughput self-clocks to service capacity) and **open**
loop (Poisson arrivals at ``rate`` rps regardless of completions — the mode
that actually exercises admission control, because arrivals do not slow down
when the server does).

Every request's solution is verified against ``verify.checks`` at the 1e-4
relative-residual gate; the summary counts ``incorrect`` separately from
transport-level failures, because a fast wrong answer is the one failure
mode a solver service must never ship.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.serve.admission import STATUS_OK, ServeConfig
from gauss_tpu.serve.server import SolverServer
from gauss_tpu.verify import checks

VERIFY_GATE = 1e-4  # relative-residual bar, the reference EPSILON


def _compilecache_dir() -> Optional[str]:
    """The persistent compile-cache dir this run used, for the report
    (None when the cache is off — the report's cold/warm decode key)."""
    from gauss_tpu.tune import compilecache

    return compilecache.cache_dir()


@dataclass(frozen=True)
class WorkloadSpec:
    """One sampled request template."""

    kind: str          # random | internal | dat | dataset | structured...
    arg: str           # n as string, path, or dataset name
    nrhs: int = 1
    #: per-request storage dtype for the batched lane (the ``dtype:``
    #: token); None = the server's default.
    dtype: Optional[str] = None


@dataclass
class LoadgenConfig:
    mix: str = "random:100*2,random:200,internal:160"
    requests: int = 50
    warmup: int = 8               # per-run warmup requests (excluded)
    mode: str = "closed"          # closed | open
    concurrency: int = 4          # closed loop: client count
    rate: float = 50.0            # open loop: arrivals per second
    nrhs: int = 1
    seed: int = 258458
    deadline_s: Optional[float] = None
    timeout_s: float = 600.0
    verify_gate: float = VERIFY_GATE
    #: mint a deterministic idempotency key per request
    #: (``submit(request_id="lg<seed>-<i>")``) — with a journaled server a
    #: rerun of the same plan dedupes already-terminal requests instead of
    #: re-solving them (the crash-restart client behavior).
    request_ids: bool = False
    serve: ServeConfig = field(default_factory=ServeConfig)


def parse_mix(mix: str) -> List[Tuple[WorkloadSpec, float]]:
    """Parse ``kind:arg*weight`` comma-separated tokens into specs."""
    out: List[Tuple[WorkloadSpec, float]] = []
    for token in mix.split(","):
        token = token.strip()
        if not token:
            continue
        weight = 1.0
        if "*" in token:
            token, w = token.rsplit("*", 1)
            weight = float(w)
        if ":" not in token:
            raise ValueError(f"workload token {token!r} needs kind:arg")
        kind, arg = token.split(":", 1)
        if kind not in ("random", "internal", "dat", "dataset",
                        "spd", "banded", "blockdiag", "sparse", "dtype",
                        "poison"):
            raise ValueError(f"unknown workload kind {kind!r} in {token!r}")
        if kind == "poison":
            # poison:<kind>/<n> — a deliberately bad operand at a
            # controlled rate: nan/inf (non-finite entries the admission
            # scan rejects) or singular (finite but exactly rank-deficient
            # — the recovery ladder's typed singular verdict). Typed
            # rejects are counted separately from failures in the report.
            p_part, _, n_part = arg.partition("/")
            if p_part not in ("nan", "inf", "singular"):
                raise ValueError(
                    f"bad poison kind in workload token {token!r}; "
                    f"options: ('nan', 'inf', 'singular')")
            if not n_part or int(n_part) < 2:
                raise ValueError(f"bad size in workload token {token!r} "
                                 f"(poison needs n >= 2)")
        dtype = None
        if kind == "dtype":
            # dtype:<dt>/<n> — a random dominant system served at the
            # lowered storage dtype (the mixed-precision batched lane).
            from gauss_tpu.core.lowered import LOWERED_DTYPES

            dt_part, _, n_part = arg.partition("/")
            if dt_part not in LOWERED_DTYPES:
                raise ValueError(
                    f"bad dtype in workload token {token!r}; options: "
                    f"{LOWERED_DTYPES}")
            if not n_part or int(n_part) < 1:
                raise ValueError(f"bad size in workload token {token!r}")
            kind, arg, dtype = "random", n_part, dt_part
        if kind in ("random", "internal", "spd") and int(arg) < 1:
            raise ValueError(f"bad size in workload token {token!r}")
        if kind in ("banded", "blockdiag", "sparse"):
            n_part, _, x_part = arg.partition("/")
            if int(n_part) < 1:
                raise ValueError(f"bad size in workload token {token!r}")
            if kind == "sparse":
                if int(n_part) > 4096:
                    raise ValueError(
                        f"sparse workload n={n_part} exceeds the loadgen "
                        f"densify cap 4096 (token {token!r})")
                if x_part and int(x_part) < 1:
                    raise ValueError(
                        f"bad nnz_per_row in workload token {token!r}")
        out.append((WorkloadSpec(kind=kind, arg=arg, dtype=dtype), weight))
    if not out:
        raise ValueError(f"empty workload mix {mix!r}")
    return out


_dat_cache: Dict[str, np.ndarray] = {}
_dat_lock = threading.Lock()


def materialize(spec: WorkloadSpec, rng: np.random.Generator, nrhs: int = 1,
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Build the (a, b) operands for one request from its spec.

    ``.dat``/dataset matrices are parsed once and cached host-side (the
    serving layer's own cache is about EXECUTABLES; re-parsing a file per
    request would just benchmark the parser). RHS vectors are freshly
    sampled per request — same matrix, different b is exactly the
    one-factorization-many-solves traffic serving is built for.
    """
    if spec.kind == "random":
        n = int(spec.arg)
        a = rng.standard_normal((n, n))
        a[np.arange(n), np.arange(n)] += float(n)  # diagonal dominance
    elif spec.kind == "internal":
        from gauss_tpu.io import synthetic

        a = synthetic.internal_matrix(int(spec.arg))
    elif spec.kind == "dat":
        with _dat_lock:
            a = _dat_cache.get(spec.arg)
        if a is None:
            from gauss_tpu.io.datfile import read_dat_dense

            a = np.asarray(read_dat_dense(spec.arg), dtype=np.float64)
            with _dat_lock:
                _dat_cache[spec.arg] = a
    elif spec.kind in ("spd", "banded", "blockdiag", "sparse"):
        from gauss_tpu.io import synthetic

        if spec.kind == "spd":
            a = synthetic.spd_matrix(int(spec.arg))
        elif spec.kind == "banded":
            n_s, _, b_s = spec.arg.partition("/")
            a = synthetic.banded_matrix(int(n_s),
                                        int(b_s) if b_s else 1)
        elif spec.kind == "sparse":
            n_s, _, z_s = spec.arg.partition("/")
            a = synthetic.sparse_matrix(int(n_s),
                                        int(z_s) if z_s else 8)
        else:
            n_s, _, k_s = spec.arg.partition("/")
            n_i = int(n_s)
            a = synthetic.blockdiag_matrix(
                n_i, int(k_s) if k_s else max(1, n_i // 8))
    elif spec.kind == "poison":
        p_kind, _, n_s = spec.arg.partition("/")
        n = int(n_s)
        a = rng.standard_normal((n, n))
        a[np.arange(n), np.arange(n)] += float(n)
        if p_kind == "nan":
            a[0, 0] = np.nan
        elif p_kind == "inf":
            a[0, 0] = np.inf
        else:  # singular: zero a full row — exactly rank-deficient, but
            # finite, so it sails past the admission scan and must be
            # caught by the ladder's typed singular verdict instead. (A
            # zero row, not a duplicated one: elimination of a duplicate
            # leaves a rounding-level pivot and a finite garbage answer,
            # which is a generic gate failure, not the typed verdict.)
            a[n // 2, :] = 0.0
    elif spec.kind == "dataset":
        with _dat_lock:
            a = _dat_cache.get("dataset:" + spec.arg)
        if a is None:
            from gauss_tpu.io import datasets

            a = np.asarray(datasets.dataset_dense(spec.arg),
                           dtype=np.float64)
            with _dat_lock:
                _dat_cache["dataset:" + spec.arg] = a
    else:  # pragma: no cover — parse_mix already rejects
        raise ValueError(f"unknown workload kind {spec.kind!r}")
    n = a.shape[0]
    k = max(1, nrhs)
    b = rng.standard_normal((n, k)) if k > 1 else rng.standard_normal(n)
    return a, b


def sample_plan(cfg: LoadgenConfig, count: int, rng: np.random.Generator,
                ) -> List[WorkloadSpec]:
    """Deterministically sample ``count`` request specs from the mix."""
    specs_weights = parse_mix(cfg.mix)
    specs = [s for s, _ in specs_weights]
    w = np.asarray([wt for _, wt in specs_weights], dtype=np.float64)
    idx = rng.choice(len(specs), size=count, p=w / w.sum())
    return [specs[i] for i in idx]


def _percentile(sorted_vals: Sequence[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def run_load(server: SolverServer, cfg: LoadgenConfig) -> Dict:
    """Drive the workload and return the serving report (a plain dict).

    Warmup requests run first through the same path (closed-loop, low
    concurrency) and are excluded from every reported number; cache
    hit-rate is measured from the post-warmup delta of the server's cache
    counters — the steady-state number, which is what the >80% acceptance
    bar is about (the first occupant of each bucket shape is always a miss).
    """
    rng = np.random.default_rng(cfg.seed)
    warm_plan = sample_plan(cfg, cfg.warmup, rng)
    plan = sample_plan(cfg, cfg.requests, rng)

    t_warm = time.perf_counter()
    with obs.span("loadgen_warmup", requests=len(warm_plan)):
        # Submitted as a burst, not serially: warmup must compile the
        # BATCHED executable shapes too (a serial warmup only ever forms
        # batch-1 dispatches, leaving every batch-bucket shape to compile
        # inside the measured window).
        warm_handles = [server.submit(*materialize(spec, rng, cfg.nrhs),
                                      dtype=spec.dtype)
                        for spec in warm_plan]
        warm_results = [h.result(cfg.timeout_s) for h in warm_handles]
    # Warmup wall-clock is the COLD-START number the persistent compile
    # cache (gauss_tpu.tune.compilecache) exists to kill: a second process
    # sharing the cache dir reruns this same warmup mostly from cached
    # executables — the before/after pair in the report.
    warmup_s = time.perf_counter() - t_warm
    hits0, misses0 = server.cache.hits, server.cache.misses
    batches0 = server.batches
    retries0 = server.retries
    rec = obs.active()
    occ_skip = (len(rec.histograms.get("serve.batch_occupancy", []))
                if rec is not None else 0)

    results = [None] * len(plan)
    operands = [None] * len(plan)
    next_i = iter(range(len(plan)))
    next_lock = threading.Lock()

    def _take() -> Optional[int]:
        with next_lock:
            return next(next_i, None)

    def _rid(i: int) -> Optional[str]:
        return f"lg{cfg.seed}-{i}" if cfg.request_ids else None

    def closed_worker(wid: int):
        wrng = np.random.default_rng(cfg.seed + 1000 + wid)
        while True:
            i = _take()
            if i is None:
                return
            a, b = materialize(plan[i], wrng, cfg.nrhs)
            operands[i] = (a, b)
            results[i] = server.solve(a, b, deadline_s=cfg.deadline_s,
                                      timeout=cfg.timeout_s,
                                      dtype=plan[i].dtype,
                                      request_id=_rid(i))

    t_start = time.perf_counter()
    if cfg.mode == "closed":
        threads = [threading.Thread(target=closed_worker, args=(w,))
                   for w in range(max(1, cfg.concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    elif cfg.mode == "open":
        wrng = np.random.default_rng(cfg.seed + 999)
        handles = []
        t_next = time.perf_counter()
        for i, spec in enumerate(plan):
            a, b = materialize(spec, wrng, cfg.nrhs)
            operands[i] = (a, b)
            # Poisson arrivals: exponential inter-arrival gaps at `rate`.
            t_next += wrng.exponential(1.0 / max(cfg.rate, 1e-9))
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            handles.append(server.submit(a, b, deadline_s=cfg.deadline_s,
                                         dtype=spec.dtype,
                                         request_id=_rid(i)))
        for i, h in enumerate(handles):
            results[i] = h.result(cfg.timeout_s)
    else:
        raise ValueError(f"unknown mode {cfg.mode!r}; options: "
                         "('closed', 'open')")
    wall_s = time.perf_counter() - t_start

    # -- fold the per-request outcomes ------------------------------------
    counts = {"ok": 0, "rejected": 0, "expired": 0, "failed": 0,
              "poison": 0}
    incorrect = 0
    lanes: Dict[str, int] = {}
    lat = []
    for i, res in enumerate(results):
        counts[res.status] = counts.get(res.status, 0) + 1
        if res.status == STATUS_OK:
            lat.append(res.latency_s)
            lanes[res.lane] = lanes.get(res.lane, 0) + 1
            a, b = operands[i]
            if not (checks.residual_norm(a, res.x, b, relative=True)
                    <= cfg.verify_gate):
                incorrect += 1
    lat.sort()
    served = counts["ok"]

    hits = server.cache.hits - hits0
    misses = server.cache.misses - misses0
    lookups = hits + misses
    occ = None
    if server.batches > batches0 and rec is not None:
        vals = rec.histograms.get("serve.batch_occupancy", [])[occ_skip:]
        if vals:
            occ = float(np.mean(vals))

    summary = {
        "kind": "serve_loadgen",
        "mix": cfg.mix,
        "mode": cfg.mode,
        "requests": len(plan),
        "warmup": len(warm_plan),
        "warmup_s": round(warmup_s, 6),
        "compile_cache": _compilecache_dir(),
        "counts": counts,
        "incorrect": incorrect,
        "lanes": lanes,
        "wall_s": round(wall_s, 6),
        "throughput_rps": round(served / wall_s, 4) if wall_s > 0 else None,
        "latency_s": {
            "mean": round(float(np.mean(lat)), 6) if lat else None,
            "p50": _percentile(lat, 0.50),
            "p95": _percentile(lat, 0.95),
            "p99": _percentile(lat, 0.99),
            "max": lat[-1] if lat else None,
        },
        "batch_occupancy_mean": round(occ, 4) if occ is not None else None,
        "batches": server.batches - batches0,
        "retries": server.retries - retries0,
        "cache": {"hits": hits, "misses": misses,
                  "hit_rate": round(hits / lookups, 4) if lookups else None,
                  **{k: v for k, v in server.cache.stats().items()
                     if k in ("entries", "capacity", "evictions")}},
        "verify_gate": cfg.verify_gate,
    }
    if getattr(server, "url", None) is not None:
        # The "server" is a network client (gauss_tpu.serve.net.SolveClient
        # — the --net mode): record the endpoint, and history_records tags
        # the metrics ``serve:net:...`` so wire-path epochs band separately
        # from the in-process serve bands while keeping the same metric
        # family and verification gate.
        summary["net"] = server.url
    mesh = server.lane_stats() if hasattr(server, "lane_stats") else None
    if mesh is not None:
        # The mesh serving plane was on: the lane-set report (lane count /
        # active / steals / continuous-batching admits + per-lane
        # served/stolen/occupancy) rides in the summary — the numbers the
        # mesh-serve-check gate and the gauss-top lane panel read.
        summary["mesh"] = mesh
    if getattr(server, "journal", None) is not None:
        # Durable admission was on: the journal's own accounting rides in
        # the report (and the overhead is visible as the delta between a
        # journal-on and journal-off run of the same plan — what
        # durablecheck's overhead phase measures and history-gates).
        summary["journal"] = {**server.journal.stats(),
                              "resume": server.last_resume}
    if getattr(server, "live", None) is not None:
        # The live plane was on: fold its SLO monitors into the report.
        # The nested dict is ALSO exportable standalone (gauss-serve
        # --slo-json) as the regress-ingestable ``kind: slo_report``.
        from gauss_tpu.obs import slo as _slo

        summary["slo"] = _slo.slo_report(server.live.slos, mix=cfg.mix,
                                         mode=cfg.mode)
    if getattr(server, "attr", None) is not None:
        # The attribution plane was on: per-request cost accounting rides
        # in the report. ``request_device_s`` re-sums the ServeResult cost
        # fields the clients saw; ``capacity`` is the matrix's per-sig /
        # per-lane view — prof-check reconciles the two. Absent (not null)
        # when attr is off, so attr=None summaries stay byte-identical.
        cap = server.attr.capacity()
        req_device = sum(r.device_s or 0.0 for r in results
                         if r is not None and r.status == STATUS_OK)
        req_compile = sum(r.compile_s or 0.0 for r in results
                          if r is not None and r.status == STATUS_OK)
        # Warmup device-seconds ride separately: the matrix saw the warmup
        # traffic too, so the reconcile identity prof-check asserts is
        # request_device_s + warmup_device_s ≈ serve_device_s.
        warm_device = sum(r.device_s or 0.0 for r in warm_results
                          if r.status == STATUS_OK)
        summary["cost"] = {
            "request_device_s": round(req_device, 6),
            "request_compile_s": round(req_compile, 6),
            "warmup_device_s": round(warm_device, 6),
            "device_s_per_request": (round(req_device / served, 6)
                                     if served else None),
            **cap,
        }
    obs.emit("serve_loadgen", **{k: v for k, v in summary.items()
                                 if k != "kind"})
    for name, value in history_records(summary):
        obs.gauge(f"loadgen.{name}", value)
    return summary


def history_records(summary: Dict) -> List[Tuple[str, float]]:
    """The (metric, value) pairs a loadgen summary contributes to the
    regression history (obs.regress ingests these via the serve_loadgen
    ingest path; metric names are mode-qualified so open- and closed-loop
    epochs never pollute each other's baselines — and LANE-qualified, so a
    mesh run's throughput never drags the single-lane serve-check band)."""
    tag = f"serve:{summary.get('mode', 'closed')}"
    if summary.get("net"):
        # Wire-path runs pay HTTP/codec overhead on top of serving — they
        # get their own band instead of dragging the in-process one.
        tag = f"serve:net:{summary.get('mode', 'closed')}"
    mesh = summary.get("mesh")
    if mesh:
        tag += f":l{mesh.get('lanes')}"
    out = []
    tput = summary.get("throughput_rps")
    if isinstance(tput, (int, float)) and tput > 0:
        # Regress gates SLOWDOWNS (value above median * band fails), so
        # throughput enters history inverted — seconds per request.
        out.append((f"{tag}/s_per_request", round(1.0 / tput, 6)))
    lat = summary.get("latency_s") or {}
    for q in ("p50", "p95"):
        v = lat.get(q)
        if isinstance(v, (int, float)) and v > 0:
            out.append((f"{tag}/{q}_s", round(v, 6)))
    return out


def write_summary(summary: Dict, path) -> None:
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
        f.write("\n")


def format_summary(summary: Dict) -> str:
    c = summary["counts"]
    lat = summary["latency_s"]
    cache = summary["cache"]

    def _s(v):
        return "-" if v is None else (f"{v:.6f}" if isinstance(v, float)
                                      else str(v))

    lines = [
        f"serve loadgen [{summary['mode']}] mix={summary['mix']}",
        f"  warmup: {_s(summary.get('warmup_s'))} s"
        + (f" (compile cache: {summary['compile_cache']})"
           if summary.get("compile_cache") else " (no compile cache)"),
        f"  requests {summary['requests']} (+{summary['warmup']} warmup): "
        f"{c.get('ok', 0)} ok, {c.get('rejected', 0)} rejected, "
        f"{c.get('expired', 0)} expired, {c.get('failed', 0)} failed, "
        f"{c.get('poison', 0)} poison-rejected, "
        f"{summary['incorrect']} INCORRECT",
        f"  lanes: " + (", ".join(f"{k}={v}" for k, v in
                                  sorted(summary['lanes'].items())) or "-"),
        f"  throughput {_s(summary['throughput_rps'])} req/s over "
        f"{_s(summary['wall_s'])} s",
        f"  latency s: mean {_s(lat['mean'])}  p50 {_s(lat['p50'])}  "
        f"p95 {_s(lat['p95'])}  p99 {_s(lat['p99'])}  max {_s(lat['max'])}",
        f"  batches {summary['batches']}, mean occupancy "
        f"{_s(summary['batch_occupancy_mean'])}",
        f"  cache: {cache['hits']} hits / {cache['misses']} misses "
        f"(hit-rate {_s(cache['hit_rate'])}), {cache['entries']} entries, "
        f"{cache['evictions']} evictions"
        + (f"; {summary['retries']} retried batch attempt(s)"
           if summary.get("retries") else ""),
    ]
    mesh = summary.get("mesh")
    if mesh:
        per = ", ".join(
            f"L{p['lane']}: {p['served']} served/"
            f"{p['stolen_in']} stolen/"
            f"occ {_s(p['occupancy_mean'])}" for p in mesh["per_lane"])
        lines.append(
            f"  mesh: {mesh['lanes']} lane(s) x{mesh['width']} "
            f"device(s), {mesh['active']} active, {mesh['steals']} "
            f"steal(s), {mesh['cb_admits']} continuous-batching admit(s)")
        lines.append(f"  per-lane: {per}")
    jr = summary.get("journal")
    if jr:
        lines.append(
            f"  journal: {jr['appends']} append(s) / {jr['fsyncs']} "
            f"fsync(s) / {jr['rotations']} rotation(s), "
            f"{jr['segments']} segment(s), {jr['bytes']} bytes"
            + (f"; resumed {jr['resume']['replayed']} replayed + "
               f"{jr['resume']['expired']} expired"
               if jr.get("resume") else ""))
    slo = summary.get("slo")
    if slo:
        lines.append(
            f"  slo: {slo['violations']}/{slo['requests_counted']} "
            f"violation(s) (rate {slo['violation_rate']:.4f}), worst burn "
            f"{slo['worst_burn_rate']:.2f}x, {slo['alerts']} alert(s) "
            f"fired / {slo['clears']} cleared")
    cost = summary.get("cost")
    if cost:
        lines.append(
            f"  cost: {_s(cost['request_device_s'])} device-s across "
            f"requests ({_s(cost['device_s_per_request'])} s/req), "
            f"{_s(cost['request_compile_s'])} s amortized compile; "
            f"matrix serve total {_s(cost.get('serve_device_s'))} s")
        sigs = cost.get("sigs") or {}
        if sigs:
            per = ", ".join(
                f"{sig}: {v['requests']} req @ "
                f"{_s(v['device_s_per_request'])} s"
                for sig, v in sorted(sigs.items()))
            lines.append(f"  per-sig: {per}")
    return "\n".join(lines)
