"""The replica front tier: consistent-hash routing + journal failover.

One :class:`Router` owns N ``SolverServer`` replica PROCESSES (each is
``python -m gauss_tpu.serve.net --replica`` — a journaled server behind
the request API), watches them the way ``fleet.py`` watches solver
workers (liveness polling, heartbeat staleness, bounded restarts), and
fronts them with one HTTP endpoint speaking the same wire schema, so a
client cannot tell one replica from many:

- **Routing.** A request's ``matrix_id`` (falling back to its idempotency
  key) is consistent-hashed over the replica ring (:class:`HashRing` —
  md5 positions, ``vnodes`` virtual nodes per replica, lookups walk
  clockwise skipping dead replicas), so repeat-A traffic keeps hitting
  the replica whose executable cache is warm for it. The FIRST sight of
  an idempotency key pins it in the :class:`AssignLog`; every later
  resubmit of that key follows the pin, because exactly-once depends on
  the resubmit reaching the journal that knows the key.
- **Failover.** When a replica dies (exit, injected kill, stall-kill),
  the router retires its journal directory, asks a surviving peer to
  ADOPT it (``POST /v1/adopt`` → :func:`gauss_tpu.serve.net
  .adopt_journal`: terminals imported for dedupe, live admits replayed,
  expired admits typed), appends a fsync-forced failover record
  remapping the dead replica's pinned keys to the adopter, and respawns
  the replica against a fresh journal. A resubmit that raced the window
  either hits the pinned-but-dead replica (503 → the client's jittered
  retry lands after the remap) or the adopter (the imported journal
  dedupes) — never a second solve.
- **Restart accounting.** Deaths are classified through
  ``fleet.exit_cause``: a graceful drain (``fleet.DRAIN_EXIT``) respawns
  WITHOUT charging ``max_restarts`` (the ISSUE-19 satellite — a rolling
  drain must not look like a crash loop), while crashes/kills/stalls
  consume the bounded budget and each capture a post-mortem bundle from
  the dead replica's flight ring (cause ``supervisor_death`` /
  ``supervisor_stall`` — the same vocabulary ``durable.supervise``
  uses) before the respawn overwrites the scene.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from bisect import bisect_right
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set, Tuple
from urllib.parse import urlparse

from gauss_tpu import obs
from gauss_tpu.resilience import fleet as _fleet
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.serve import durable

#: virtual nodes per replica on the hash ring: enough that removing one
#: replica of three moves ~1/3 of the keyspace, not a contiguous half.
RING_VNODES = 64
#: assign-log group-commit batch (failover records always force fsync —
#: a lost plain assign is recoverable by deterministic rehash; a lost
#: failover record is not).
ASSIGN_FSYNC_BATCH = 8


def _ring_hash(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hashing over replica names. Immutable after build —
    liveness is a per-lookup filter, not a ring mutation, so the mapping
    of keys to their PREFERRED replica never churns when a replica
    bounces."""

    def __init__(self, nodes: List[str], vnodes: int = RING_VNODES):
        self.nodes = tuple(nodes)
        points: List[Tuple[int, str]] = []
        for node in nodes:
            for v in range(vnodes):
                points.append((_ring_hash(f"{node}#{v}"), node))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    def lookup(self, key: str, live: Optional[Set[str]] = None,
               ) -> Optional[str]:
        """The first clockwise replica from ``key``'s ring position that
        is in ``live`` (all nodes when None). Also how failover picks the
        adopter: ``lookup(dead_name, survivors)`` is the dead replica's
        ring successor."""
        if not self._points:
            return None
        start = bisect_right(self._hashes, _ring_hash(key))
        for i in range(len(self._points)):
            node = self._points[(start + i) % len(self._points)][1]
            if live is None or node in live:
                return node
        return None


class AssignLog:
    """Durable ``rid -> replica`` pin map (CRC'd records via the journal
    line codec, so a torn tail drops records instead of poisoning the
    scan). ``assign`` records are group-committed; ``failover`` records
    fsync immediately. A router restart reloads the surviving prefix —
    an assign lost from the torn tail re-derives by rehash, which is
    only wrong if the live set changed in the same crash window, in
    which case the journal dedupe still holds the exactly-once line."""

    def __init__(self, path: str):
        self.path = path
        self._alock = threading.Lock()
        self._pins: Dict[str, str] = {}   # guarded by: self._alock
        self._unsynced = 0                # guarded by: self._alock
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        for doc in self._scan():
            self._apply(doc)
        self._fh = open(path, "ab")       # guarded by: self._alock

    def _scan(self) -> List[Dict[str, Any]]:
        docs = []
        try:
            with open(self.path, "rb") as f:
                for line in f.read().split(b"\n"):
                    if not line:
                        continue
                    doc = durable.decode_line(line + b"\n")
                    if doc is not None:
                        docs.append(doc)
        except OSError:
            pass
        return docs

    def _apply(self, doc: Dict[str, Any]) -> None:
        # Construction-time replay only: runs before the instance is
        # published to any other thread, so _pins needs no lock yet.
        if doc.get("rec") == "assign":
            self._pins[str(doc["rid"])] = str(doc["node"])  # lockset: ok — pre-publication replay in __init__
        elif doc.get("rec") == "failover":
            src, dst = str(doc["from"]), str(doc["to"])
            for rid, node in list(self._pins.items()):  # lockset: ok — pre-publication replay in __init__
                if node == src:
                    self._pins[rid] = dst  # lockset: ok — pre-publication replay in __init__

    def _append(self, doc: Dict[str, Any], force_fsync: bool) -> None:
        # Private write path: every caller (assign/failover) already holds
        # _alock; taking it again here would deadlock a non-reentrant lock.
        self._fh.write(durable.encode_record(doc))  # lockset: ok — caller holds _alock
        self._fh.flush()  # lockset: ok — caller holds _alock
        self._unsynced += 1  # lockset: ok — caller holds _alock
        if force_fsync or self._unsynced >= ASSIGN_FSYNC_BATCH:  # lockset: ok — caller holds _alock
            os.fsync(self._fh.fileno())  # lockset: ok — caller holds _alock
            self._unsynced = 0  # lockset: ok — caller holds _alock

    def resolve(self, rid: str) -> Optional[str]:
        with self._alock:
            return self._pins.get(rid)

    def assign(self, rid: str, node: str) -> None:
        with self._alock:
            if self._pins.get(rid) == node:
                return
            self._pins[rid] = node
            self._append({"rec": "assign", "rid": rid, "node": node},
                         force_fsync=False)

    def failover(self, src: str, dst: str) -> int:
        """Remap every pin on ``src`` to ``dst``; fsync-forced. Returns
        how many pins moved."""
        with self._alock:
            moved = 0
            for rid, node in list(self._pins.items()):
                if node == src:
                    self._pins[rid] = dst
                    moved += 1
            self._append({"rec": "failover", "from": src, "to": dst},
                         force_fsync=True)
            return moved

    def pins(self) -> Dict[str, str]:
        with self._alock:
            return dict(self._pins)

    def close(self) -> None:
        with self._alock:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass
            self._fh.close()


@dataclasses.dataclass
class RouterConfig:
    """Knobs for the replica front tier."""

    replicas: int = 3               # replica process count
    dir: str = "gauss_router"      # state root: r<i>/ per replica + assign log
    port: int = 0                   # front endpoint port (0 = ephemeral)
    host: str = "127.0.0.1"
    # -- per-replica ServeConfig passthrough -------------------------------
    ladder: tuple = ()
    max_batch: int = 8
    max_queue: int = 256
    linger_s: float = 0.0
    verify_gate: Optional[float] = None
    dtype: str = "float32"
    fsync_batch: int = 4
    # -- supervision -------------------------------------------------------
    max_restarts: int = 3           # crash-restart budget (drains are free)
    #: blame-journal quarantine threshold for the free-respawn guard; must
    #: match the replicas' ``ServeConfig.quarantine_deaths`` (both default
    #: 2). A death is uncharged only when it pushed a suspect's death
    #: count TO this threshold or past it — the point where the adoption-
    #: side replay solos or typed-rejects the suspect. 0 disables.
    quarantine_deaths: int = 2
    stall_after_s: float = 30.0     # heartbeat staleness that calls a stall
    poll_s: float = 0.25            # watch-loop cadence
    spawn_timeout_s: float = 180.0  # endpoint.json publish deadline
    forward_timeout_s: float = 120.0  # per proxied request


class ReplicaProc:
    """One spawned replica process + its on-disk state dir."""

    def __init__(self, name: str, dirpath: str, proc: subprocess.Popen,
                 log_fh):
        self.name = name
        self.dirpath = dirpath
        self.proc = proc
        self.url: Optional[str] = None
        self.t_spawn = time.time()
        self._log_fh = log_fh

    def wait_ready(self, timeout_s: float) -> str:
        """Block until this incarnation published ``endpoint.json`` (pid
        must match — a stale file from the previous incarnation does not
        count)."""
        deadline = time.monotonic() + timeout_s
        path = os.path.join(self.dirpath, "endpoint.json")
        while time.monotonic() < deadline:
            rc = self.proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"replica {self.name} died during startup (rc={rc}, "
                    f"cause={_fleet.exit_cause(rc)}); see "
                    f"{self.dirpath}/child.log")
            try:
                with open(path) as f:
                    doc = json.load(f)
                if doc.get("pid") == self.proc.pid:
                    self.url = str(doc["url"])
                    return self.url
            except (OSError, ValueError, KeyError):
                pass
            time.sleep(0.05)
        raise TimeoutError(f"replica {self.name} did not publish its "
                           f"endpoint within {timeout_s} s")

    def heartbeat_age(self) -> Optional[float]:
        try:
            return time.time() - os.path.getmtime(
                os.path.join(self.dirpath, "heartbeat.json"))
        except OSError:
            return None

    def retire_journal(self, seq: int) -> Optional[str]:
        """Move this incarnation's journal aside for adoption; the
        respawn starts a FRESH journal (the retired one now belongs to
        the adopter, and two writers against one journal dir would tear
        it)."""
        src = os.path.join(self.dirpath, "journal")
        if not os.path.isdir(src):
            return None
        # The seq counter is per-Router; a retired dir from a PREVIOUS
        # incarnation against the same state dir would collide the rename
        # (and an OSError here would take the watch thread down with it) —
        # probe forward to a free name instead.
        dst = os.path.join(self.dirpath, f"journal-failed-{seq}")
        k = seq
        while os.path.exists(dst):
            k += 1
            dst = os.path.join(self.dirpath, f"journal-failed-{k}")
        os.rename(src, dst)
        return dst

    def close_log(self) -> None:
        try:
            self._log_fh.close()
        except OSError:  # pragma: no cover
            pass


class Router:
    """Spawn/watch N replicas, route requests, fail over journals.

    ``start()`` brings up the replicas and the front endpoint;
    ``kill_replica``/``terminate_replica`` are the chaos surface the
    replica campaign drives; ``stop(drain=True)`` SIGTERMs every replica
    and expects ``fleet.DRAIN_EXIT`` back (the graceful path)."""

    def __init__(self, config: Optional[RouterConfig] = None):
        self.config = config if config is not None else RouterConfig()
        names = [f"r{i}" for i in range(self.config.replicas)]
        self.ring = HashRing(names)
        self.alog: Optional[AssignLog] = None
        self._rlock = threading.Lock()
        self._live: Dict[str, ReplicaProc] = {}   # guarded by: self._rlock
        self.restarts_used = 0                    # guarded by: self._rlock
        self.degraded = False                     # guarded by: self._rlock
        self.failovers = 0                        # guarded by: self._rlock
        self._retired_dirs: List[str] = []        # guarded by: self._rlock
        self._failover_seq = 0                    # guarded by: self._rlock
        #: stable request key (rid / trace) -> max deaths seen across
        #: failovers — the quarantine growth guard. guarded by: self._rlock
        self._blame_seen: Dict[str, int] = {}
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._api: Optional["RouterFront"] = None
        self._stopping = False                    # guarded by: self._rlock

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, name: str, strip_faults: bool = False) -> ReplicaProc:
        cfg = self.config
        rdir = os.path.join(cfg.dir, name)
        os.makedirs(rdir, exist_ok=True)
        cmd = [sys.executable, "-m", "gauss_tpu.serve.net", "--replica",
               "--dir", rdir, "--port", "0",
               "--max-batch", str(cfg.max_batch),
               "--max-queue", str(cfg.max_queue),
               "--linger", str(cfg.linger_s),
               "--dtype", cfg.dtype,
               "--fsync-batch", str(cfg.fsync_batch)]
        if cfg.ladder:
            cmd += ["--ladder", ",".join(str(r) for r in cfg.ladder)]
        if cfg.verify_gate is not None:
            cmd += ["--verify-gate", str(cfg.verify_gate)]
        env = dict(os.environ)
        if strip_faults:
            # One-off-crash contract (same as durable.supervise): an
            # injected kill dies with the incarnation it killed.
            env.pop(_inject.ENV_VAR, None)
        log_fh = open(os.path.join(rdir, "child.log"), "ab")
        proc = subprocess.Popen(cmd, env=env, stdout=log_fh,
                                stderr=subprocess.STDOUT)
        rp = ReplicaProc(name, rdir, proc, log_fh)
        obs.emit("router", event="replica_spawn", replica=name,
                 pid=proc.pid, dir=rdir)
        return rp

    def start(self) -> "Router":
        cfg = self.config
        os.makedirs(cfg.dir, exist_ok=True)
        self.alog = AssignLog(os.path.join(cfg.dir, "assign.log"))
        spawned = [self._spawn(f"r{i}") for i in range(cfg.replicas)]
        for rp in spawned:
            rp.wait_ready(cfg.spawn_timeout_s)
        with self._rlock:
            for rp in spawned:
                self._live[rp.name] = rp
        self._watch_thread = threading.Thread(
            target=self._watch, name="gauss-router-watch", daemon=True)
        self._watch_thread.start()
        self._api = RouterFront(self, port=cfg.port, host=cfg.host).start()
        obs.emit("router", event="listening", url=self._api.url,
                 replicas=cfg.replicas, dir=cfg.dir)
        return self

    @property
    def url(self) -> Optional[str]:
        return self._api.url if self._api is not None else None

    def live_replicas(self) -> Dict[str, ReplicaProc]:
        with self._rlock:
            return dict(self._live)

    def stats(self) -> Dict[str, Any]:
        with self._rlock:
            live = {name: {"pid": rp.proc.pid, "url": rp.url,
                           "heartbeat_age_s": rp.heartbeat_age()}
                    for name, rp in self._live.items()}
            return {"live": live, "restarts_used": self.restarts_used,
                    "failovers": self.failovers,
                    "degraded": self.degraded,
                    "pins": len(self.alog.pins()) if self.alog else 0}

    # -- the watch loop (liveness + heartbeat staleness) -------------------

    def _watch(self) -> None:
        cfg = self.config
        while not self._watch_stop.wait(cfg.poll_s):
            with self._rlock:
                if self._stopping:
                    return
                procs = dict(self._live)
            for name, rp in procs.items():
                # A death-handling failure (retire/adopt/respawn raising)
                # must not take the watch thread with it — a dead watcher
                # means no replica death is ever noticed again, which is
                # strictly worse than one degraded failover.
                try:
                    rc = rp.proc.poll()
                    if rc is not None:
                        self._on_death(name, rp, _fleet.exit_cause(rc),
                                       rc=rc)
                        continue
                    age = rp.heartbeat_age()
                    if (age is not None and age > cfg.stall_after_s
                            and time.time() - rp.t_spawn > cfg.stall_after_s):
                        # Alive but wedged: the heartbeat (written every
                        # worker-loop iteration) went stale — kill it and
                        # treat the death as a stall.
                        obs.emit("router", event="stall", replica=name,
                                 heartbeat_age_s=round(age, 3))
                        rp.proc.kill()
                        try:
                            rp.proc.wait(timeout=15)
                        except subprocess.TimeoutExpired:  # pragma: no cover
                            continue
                        self._on_death(name, rp, "stalled",
                                       rc=rp.proc.returncode,
                                       heartbeat_age_s=round(age, 3))
                except Exception as exc:  # pragma: no cover - defensive
                    obs.emit("router", event="death_handling_failed",
                             replica=name, error=repr(exc))
                    with self._rlock:
                        self._live.pop(name, None)
                        self.degraded = True
            with self._rlock:
                n_live = len(self._live)
            obs.gauge("router.replicas_live", n_live)

    def _capture(self, rp: ReplicaProc, cause: str, retired: Optional[str],
                 **detail) -> None:
        """Post-mortem bundle from the dead replica's flight ring —
        BEFORE the respawn overwrites the scene. Best-effort: a capture
        failure must not cost the failover."""
        try:
            from gauss_tpu.obs import postmortem

            flight_dir = os.path.join(rp.dirpath, "flight")
            postmortem.capture_bundle(
                postmortem.default_bundles_dir(flight_dir), cause,
                flight_dir=flight_dir, journal_dir=retired,
                heartbeat_path=os.path.join(rp.dirpath, "heartbeat.json"),
                extra={"replica": rp.name, **detail},
                log=lambda *a: None)
        except Exception as e:  # pragma: no cover — capture is best-effort
            obs.emit("router", event="capture_failed", replica=rp.name,
                     error=f"{type(e).__name__}: {e}"[:200])

    def _blame_grew(self, retired: Optional[str]) -> bool:
        """Did the retired journal push a suspect's death count TO the
        quarantine threshold (or past it) — higher than anything seen for
        its stable key (rid, else trace) across prior failovers AND at
        least ``config.quarantine_deaths``? That is the point where the
        adoption-side replay changes behavior (solo at K deaths, typed
        reject past K), so the death is the ladder CONVERGING, not a crash
        loop. Growth below the threshold does NOT qualify: every mid-
        dispatch kill blames its in-flight batch once, and charging
        nothing for first deaths would let an environmental crasher under
        load respawn for free forever. Counts are bounded per request
        (past K deaths the replay rejects it terminally), so free
        respawns are finite."""
        k = self.config.quarantine_deaths
        if not retired or k <= 0:
            return False
        try:
            st = durable.scan(retired)
            counts = st.death_counts()
        except (durable.JournalError, OSError):
            return False
        grew = False
        with self._rlock:
            for jid, c in counts.items():
                adm = st.admits.get(jid) or {}
                key = str(adm.get("rid") or adm.get("trace") or jid)
                if c > self._blame_seen.get(key, 0):
                    self._blame_seen[key] = c
                    if c >= k:
                        grew = True
        return grew

    def _on_death(self, name: str, rp: ReplicaProc, cause: str,
                  rc: Optional[int] = None, **detail) -> None:
        t0 = time.perf_counter()
        with self._rlock:
            if self._live.get(name) is not rp or self._stopping:
                return
            del self._live[name]
            self._failover_seq += 1
            seq = self._failover_seq
        charged = _fleet.counts_against_restart_budget(cause)
        retired = rp.retire_journal(seq)
        if charged and self._blame_grew(retired):
            # Poison-implicated death: reclassify through the shared
            # fleet cause vocabulary so the respawn stops charging the
            # restart budget — the journal adoption below quarantines or
            # rejects the suspects, which is what actually ends the loop.
            obs.counter("router.quarantined_deaths")
            detail = {**detail, "underlying_cause": cause}
            cause = "quarantined"
            charged = _fleet.counts_against_restart_budget(cause)
            self._capture(rp, "poison_quarantine", retired, rc=rc, **detail)
        elif charged:
            self._capture(rp, "supervisor_stall" if cause == "stalled"
                          else "supervisor_death", retired, rc=rc, **detail)
        rp.close_log()
        adopter_name = None
        adopt_out: Dict[str, Any] = {}
        moved = 0
        with self._rlock:
            live = dict(self._live)
            if retired:
                self._retired_dirs.append(retired)
        if retired and live:
            # The dead replica's ring successor adopts its journal —
            # terminals imported for dedupe, live admits replayed,
            # expired ones typed. Walk the survivors until one answers.
            order = [self.ring.lookup(name, set(live))]
            order += [n for n in sorted(live) if n not in order]
            for cand in order:
                try:
                    adopt_out = self._post_adopt(live[cand], retired)
                    adopter_name = cand
                    break
                except (urllib.error.URLError, OSError, ValueError,
                        TimeoutError):
                    continue
            if adopter_name is not None:
                moved = self.alog.failover(name, adopter_name)
        recovery_s = time.perf_counter() - t0
        with self._rlock:
            self.failovers += 1
        obs.counter("router.failovers")
        obs.emit("replica_failover", replica=name, cause=cause, rc=rc,
                 adopter=adopter_name, pins_moved=moved,
                 replayed=adopt_out.get("replayed"),
                 imported=adopt_out.get("imported"),
                 expired=adopt_out.get("expired"),
                 skipped=adopt_out.get("skipped"),
                 poisoned=adopt_out.get("poisoned"),
                 quarantined=adopt_out.get("quarantined"),
                 recovery_s=round(recovery_s, 4), **detail)
        # -- respawn accounting (fleet.exit_cause vocabulary): drains and
        # -- peer-lost respawn free; crashes/kills/stalls spend the budget.
        respawn = False
        with self._rlock:
            if not charged:
                respawn = True
            elif self.restarts_used < self.config.max_restarts:
                self.restarts_used += 1
                respawn = True
            else:
                self.degraded = True
        if not respawn:
            obs.emit("router", event="degraded", replica=name, cause=cause,
                     max_restarts=self.config.max_restarts)
            return
        new_rp = self._spawn(name, strip_faults=True)
        try:
            new_rp.wait_ready(self.config.spawn_timeout_s)
        except (RuntimeError, TimeoutError) as e:  # pragma: no cover
            obs.emit("router", event="respawn_failed", replica=name,
                     error=str(e)[:200])
            return
        with self._rlock:
            if not self._stopping:
                self._live[name] = new_rp
        obs.emit("router", event="restart", replica=name, cause=cause,
                 charged=charged, pid=new_rp.proc.pid)

    def _post_adopt(self, rp: ReplicaProc, retired: str) -> Dict[str, Any]:
        req = urllib.request.Request(
            rp.url + "/v1/adopt",
            data=json.dumps({"dir": retired}).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120.0) as resp:
            return json.loads(resp.read())

    # -- routing -----------------------------------------------------------

    def route(self, rid: Optional[str], affinity: Optional[str],
              ) -> Optional[ReplicaProc]:
        """Pick the replica for a request. A PINNED rid follows its pin
        even while that replica is down (returning None → the front
        answers 503 and the client's jittered retry lands after the
        failover record moves the pin) — remapping early would race the
        adoption and could double-solve. Unpinned keys hash over the
        LIVE ring; first sight of a rid pins it."""
        with self._rlock:
            live = dict(self._live)
        if rid:
            pinned = self.alog.resolve(rid)
            if pinned is not None:
                return live.get(pinned)
        if not live:
            return None
        node = self.ring.lookup(affinity or rid or "?", set(live))
        if node is None:  # pragma: no cover — live is non-empty
            return None
        if rid:
            self.alog.assign(rid, node)
        return live.get(node)

    # -- chaos surface -----------------------------------------------------

    def kill_replica(self, name: str) -> int:
        """SIGKILL a live replica (the campaign's mid-load kill). Returns
        the killed pid. The watch loop notices the death and fails over."""
        with self._rlock:
            rp = self._live[name]
        rp.proc.kill()
        return rp.proc.pid

    def terminate_replica(self, name: str) -> int:
        """SIGTERM a live replica: graceful drain → ``fleet.DRAIN_EXIT``
        → a budget-free respawn."""
        with self._rlock:
            rp = self._live[name]
        rp.proc.send_signal(signal.SIGTERM)
        return rp.proc.pid

    # -- shutdown ----------------------------------------------------------

    def stop(self, drain: bool = True, timeout: float = 60.0,
             ) -> Dict[str, Any]:
        with self._rlock:
            self._stopping = True
            procs = dict(self._live)
            self._live.clear()
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=10)
            self._watch_thread = None
        rcs = {}
        for name, rp in procs.items():
            if rp.proc.poll() is None:
                rp.proc.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL)
        for name, rp in procs.items():
            try:
                rcs[name] = rp.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:  # pragma: no cover
                rp.proc.kill()
                rcs[name] = rp.proc.wait(timeout=10)
            rp.close_log()
        if self._api is not None:
            self._api.stop()
            self._api = None
        if self.alog is not None:
            self.alog.close()
        out = {"rcs": rcs,
               "causes": {n: _fleet.exit_cause(rc)
                          for n, rc in rcs.items()}}
        obs.emit("router", event="drained" if drain else "killed", **out)
        return out

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def retired_dirs(self) -> List[str]:
        with self._rlock:
            return list(self._retired_dirs)

    def replica_dirs(self) -> List[str]:
        return [os.path.join(self.config.dir, f"r{i}")
                for i in range(self.config.replicas)]


class _FrontHandler(BaseHTTPRequestHandler):
    """Front-tier connection handler: parse just enough of the body to
    route, then proxy the raw bytes to the chosen replica."""

    server_version = "gauss-router/1"
    router: Router = None  # type: ignore[assignment] # set per server

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        data = (json.dumps(payload, sort_keys=True) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _unavailable(self, why: str) -> None:
        self._json(503, {"error": why, "retry_after_s": 0.5},
                   headers={"Retry-After": "1"})

    def _proxy(self, rp: ReplicaProc, method: str, path: str,
               raw: Optional[bytes]) -> None:
        req = urllib.request.Request(
            rp.url + path, data=raw, method=method,
            headers={"Content-Type": "application/json"})
        timeout = self.router.config.forward_timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                body = resp.read()
                self.send_response(resp.status)
                for key in ("Content-Type", "Retry-After"):
                    if resp.headers.get(key):
                        self.send_header(key, resp.headers[key])
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
        except urllib.error.HTTPError as e:
            body = e.read()
            self.send_response(e.code)
            for key in ("Content-Type", "Retry-After"):
                if e.headers and e.headers.get(key):
                    self.send_header(key, e.headers[key])
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (urllib.error.URLError, OSError):
            # The replica died under us (mid-failover window): tell the
            # client to retry — its key stays pinned until the failover
            # record moves it to the adopter.
            self._unavailable("replica unavailable (failover in progress)")
        except (BrokenPipeError, ConnectionResetError):  # pragma: no cover
            pass

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = urlparse(self.path).path
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
            doc = json.loads(raw)
        except (ValueError, OSError):
            self._json(400, {"error": "unparseable JSON body"})
            return
        if path not in ("/v1/solve", "/v1/upload"):
            self._json(404, {"error": f"unknown endpoint {path!r}"})
            return
        rid = doc.get("request_id")
        affinity = doc.get("matrix_id")
        if path == "/v1/upload" and rid is None:
            # Uploads carry request_id/matrix_id too, so the slabs land
            # on the replica the solve will route to.
            rid = doc.get("upload")
        rp = self.router.route(rid, affinity)
        if rp is None:
            self._unavailable("no live replica for this key yet")
            return
        self._proxy(rp, "POST", path, raw)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        if url.path == "/healthz":
            self._json(200, {"status": "ok", **self.router.stats()})
            return
        if url.path.startswith("/v1/requests/"):
            rid = url.path[len("/v1/requests/"):]
            rp = self.router.route(rid, None)
            if rp is None:
                self._unavailable("no live replica holds this request yet")
                return
            self._stream_proxy(rp, self.path)
            return
        self._json(404, {"error": f"unknown endpoint {url.path!r}"})

    def _stream_proxy(self, rp: ReplicaProc, path: str) -> None:
        timeout = self.router.config.forward_timeout_s
        try:
            with urllib.request.urlopen(rp.url + path,
                                        timeout=timeout) as resp:
                self.send_response(resp.status)
                self.send_header("Content-Type",
                                 resp.headers.get("Content-Type",
                                                  "application/x-ndjson"))
                self.send_header("Connection", "close")
                self.end_headers()
                for line in resp:
                    self.wfile.write(line)
                    self.wfile.flush()
        except (urllib.error.URLError, OSError):
            self._unavailable("replica unavailable (failover in progress)")
        except (BrokenPipeError, ConnectionResetError):  # pragma: no cover
            pass


class RouterFront:
    """The router's single client-facing endpoint (same bound-handler
    idiom as the replica API and the PR-8 live endpoint)."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1"):
        self.router = router
        handler = type("BoundFrontHandler", (_FrontHandler,),
                       {"router": router})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "RouterFront":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="gauss-router",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
