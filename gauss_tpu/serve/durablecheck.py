"""The kill-the-server chaos campaign: ``python -m gauss_tpu.serve.durablecheck``.

Asserts the durability invariant the write-ahead request journal
(gauss_tpu.serve.durable) exists to provide:

    **every admitted request reaches EXACTLY ONE terminal status —
    served-and-verified at the 1e-4 gate, a typed failure, or a typed
    expiry — across server crashes, torn journal writes, and restarts;
    and an idempotent resubmission never causes a duplicate solve.**

"Admitted" is client-truth, not server-truth: the campaign keeps its own
LEDGER of every ``submit()`` that returned an admitted handle, then crashes
the server and audits the journal against the ledger — the invariant is
judged by the side that could have lost data, from records the crash could
not revise.

Phases:

- **recovery cases** (``--cases``, in-process): seeded crash scenarios
  against a live journaled :class:`SolverServer` — ``crash`` (die at a
  seeded batch boundary, queued work abandoned), ``torn`` (crash DURING a
  terminal append: a half-written record at the tail recovery must drop),
  ``clean`` (SIGTERM-shaped graceful drain: the clean-shutdown marker must
  make the next start replay nothing), ``underload`` (restart replays the
  dead server's backlog WHILE new traffic is admitted). Every case ends
  with a full journal-vs-ledger audit plus an idempotent-resubmission pass
  that must return every journaled terminal without one new solve.
  In-process crashes use the server's ``_crash()`` chaos hook (abandon the
  queue, drop the journal handle cold) — the journal-level state is the
  one a kill leaves; the places only a REAL dead process can prove are
  covered by:
- **subprocess legs** (skipped by ``--no-subprocess``): a self-driving
  server child (``--drive``) killed by the seeded ``server_kill`` fault at
  a batch boundary (genuine ``os._exit`` mid-load), a ``journal_torn_write``
  child that dies mid-append tearing the live segment, and a SUPERVISED
  child (gauss_tpu.serve.durable.supervise — the PR-5 watchdog pattern)
  whose auto-restart must finish the original plan exactly-once.
- **overhead** (``--no-overhead`` to skip): the same loadgen plan run
  journal-off and journal-on; the journal-on cost lands in history next to
  the PR-11 serving/throughput records (``durable:journal_s_per_request``,
  ``durable:overhead_ratio``) and is regress-gated like any perf metric.
  The journal-OFF run's timing stays covered by the pre-existing
  ``serve-check`` band — journal off must stay zero-cost.

The summary is regress-ingestable (``kind: durable_campaign``). Exit 2
when the invariant is violated (lost request, duplicate terminal,
duplicate solve, unverified serve), 1 when ``--regress-check`` finds an
out-of-band metric, 0 otherwise. ``make durable-check`` runs the CI
configuration; like the other timing-gated gates it must not run
concurrently with them (Makefile serial-ordering note).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

CASE_KINDS = ("crash", "torn", "clean", "underload")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _system(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _case_config(journal_dir: str, gate: float, **over):
    from gauss_tpu.serve.admission import ServeConfig

    kw = dict(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
              verify_gate=gate, journal_dir=journal_dir,
              journal_fsync_batch=4, max_queue=256)
    kw.update(over)
    return ServeConfig(**kw)


def _wait_batches(srv, k: int, timeout_s: float = 20.0) -> None:
    t0 = time.monotonic()
    while srv.batches < k and time.monotonic() - t0 < timeout_s:
        time.sleep(0.002)


def _tear_terminal_append(journal_dir: str, admit_id: int,
                          rng: np.random.Generator) -> None:
    """Simulate a crash DURING a terminal append: a seeded prefix of a
    would-be terminal record for ``admit_id`` lands at the live segment's
    tail, newline never written. Recovery must drop it (CRC fails) and
    re-solve the request — at-least-once execution, exactly-once
    terminal."""
    from gauss_tpu.serve import durable

    segs = durable.segment_paths(journal_dir)
    payload = durable.encode_record({
        "rec": "terminal", "schema": durable.JOURNAL_SCHEMA,
        "id": int(admit_id), "rid": None, "trace": "torn", "status": "ok",
        "t_unix": time.time()})
    cut = int(rng.integers(1, len(payload) - 1))
    with open(segs[-1], "ab") as f:
        f.write(payload[:cut])


def audit(journal_dir: str, ledger: List[Tuple[str, int]],
          gate: float) -> Dict:
    """Journal-vs-ledger audit: every admitted request_id must hold exactly
    one journaled terminal; every ``ok`` terminal must verify at ``gate``
    against the JOURNALED operands (the runner's own check — the invariant
    must not trust the server's gate to judge the server)."""
    from gauss_tpu.serve import durable
    from gauss_tpu.verify import checks

    st = durable.scan(journal_dir)
    per_rid: Dict[str, int] = {}
    for term in st.terminals.values():
        rid = term.get("rid")
        if rid:
            per_rid[rid] = per_rid.get(rid, 0) + 1
    admits_by_rid = {doc.get("rid"): doc for doc in st.admits.values()
                     if doc.get("rid")}
    missing: List[str] = []
    duplicates: List[str] = []
    incorrect: List[str] = []
    statuses: Dict[str, int] = {}
    for rid, _n in ledger:
        cnt = per_rid.get(rid, 0)
        if cnt == 0:
            missing.append(rid)
            continue
        if cnt > 1:
            duplicates.append(rid)
        term = st.by_rid[rid]
        statuses[term["status"]] = statuses.get(term["status"], 0) + 1
        if term["status"] == "ok":
            adm = admits_by_rid.get(rid)
            if adm is None or term.get("x") is None:
                incorrect.append(rid)
                continue
            a = durable.decode_array(adm["a"])
            b = durable.decode_array(adm["b"])
            if adm.get("was_vector"):
                b = b.reshape(-1)
            x = durable.decode_array(term["x"])
            rel = checks.residual_norm(a, x, b, relative=True)
            if not (np.isfinite(rel) and rel <= gate):
                incorrect.append(rid)
    return {"admitted": len(ledger), "terminals": len(st.terminals),
            "statuses": statuses, "missing": missing,
            "duplicates": duplicates, "incorrect": incorrect,
            "torn_dropped": st.torn_dropped,
            "clean_shutdown": st.clean_shutdown}


def run_recovery_case(i: int, seed: int, gate: float, tmpdir: str,
                      kind: str, cache=None) -> Dict:
    """One in-process kill/resume case; returns its outcome record."""
    from gauss_tpu.serve.server import SolverServer

    rng = np.random.default_rng(np.random.SeedSequence((seed, i, 0xD0B1)))
    jd = os.path.join(tmpdir, f"case-{kind}-{i:03d}")
    out: Dict = {"case": i, "kind": kind}
    ledger: List[Tuple[str, int]] = []
    operands: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    n_req = 8 + int(rng.integers(0, 5))

    # -- phase 1: load, then die (or drain) --------------------------------
    srv = SolverServer(_case_config(jd, gate), cache=cache)
    srv.start()
    for j in range(n_req):
        n = 16 + int(rng.integers(0, 13))
        a, b = _system(rng, n)
        rid = f"d{seed}-{i}-{j}"
        # One request per crash case carries a deadline that will be dead
        # by recovery time: its replay must end as a typed expiry (or an
        # honest pre-crash serve), never a silent loss — the audit's
        # missing-list judges either way.
        deadline = 0.001 if (kind in ("crash", "underload")
                             and j == n_req - 1) else None
        h = srv.submit(a, b, request_id=rid, deadline_s=deadline)
        if not (h.done and h.result(0).status == "rejected"):
            ledger.append((rid, n))
            operands[rid] = (a, b)
    if kind == "clean":
        srv.stop(drain=True, timeout=120.0)
    else:
        _wait_batches(srv, int(rng.integers(0, 3)))
        srv._crash()
        if kind == "torn":
            from gauss_tpu.serve import durable

            st = durable.scan(jd)
            live = st.live_admits()
            victim = live[0]["id"] if live else next(iter(st.admits), 0)
            _tear_terminal_append(jd, victim, rng)

    # -- phase 2: restart, recover, drain ----------------------------------
    srv2 = SolverServer(_case_config(jd, gate), cache=cache)
    srv2.start()
    out["resume"] = dict(srv2.last_resume or {})
    if kind == "clean" and out["resume"].get("replayed", 0) != 0:
        out["outcome"] = "violation"
        out["error"] = "clean shutdown marker did not suppress replay"
        srv2.stop()
        return out
    if kind == "underload":
        for j in range(4):
            n = 16 + int(rng.integers(0, 13))
            a, b = _system(rng, n)
            rid = f"d{seed}-{i}-new{j}"
            h = srv2.submit(a, b, request_id=rid)
            if not (h.done and h.result(0).status == "rejected"):
                ledger.append((rid, n))
                operands[rid] = (a, b)
    srv2.stop(drain=True, timeout=120.0)

    # -- phase 3: idempotent resubmission must not re-solve ----------------
    from gauss_tpu.serve import durable as _d

    st_before = _d.scan(jd)
    srv3 = SolverServer(_case_config(jd, gate), cache=cache)
    srv3.start()
    deduped = mismatched = 0
    for rid, _n in ledger:
        a, b = operands[rid]
        res = srv3.solve(a, b, request_id=rid, timeout=60.0)
        want = st_before.by_rid.get(rid, {}).get("status")
        if want is not None and res.status == want:
            deduped += 1
        else:
            mismatched += 1
    resolves = srv3.requests_served
    srv3.stop(drain=True, timeout=120.0)

    # -- audit -------------------------------------------------------------
    out["audit"] = audit(jd, ledger, gate)
    out["deduped"] = deduped
    out["dedupe_mismatched"] = mismatched
    out["dedupe_resolves"] = resolves
    a_ = out["audit"]
    violated = bool(a_["missing"] or a_["duplicates"] or a_["incorrect"]
                    or mismatched or resolves > 0)
    out["outcome"] = "violation" if violated else "ok"
    if violated:
        out["error"] = (f"missing={a_['missing'][:3]} "
                        f"duplicates={a_['duplicates'][:3]} "
                        f"incorrect={a_['incorrect'][:3]} "
                        f"dedupe_mismatched={mismatched} "
                        f"dedupe_resolves={resolves}")
    return out


# -- subprocess legs -------------------------------------------------------

def _drive_argv(journal: str, ledger: str, requests: int, seed: int,
                metrics_out: Optional[str] = None) -> List[str]:
    argv = [sys.executable, "-m", "gauss_tpu.serve.durablecheck", "--drive",
            "--journal", journal, "--ledger", ledger,
            "--requests", str(requests), "--seed", str(seed)]
    if metrics_out:
        argv += ["--metrics-out", metrics_out]
    return argv


def _read_ledger(path: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    seen = set()
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # torn ledger line: the submit never returned
                rid = doc.get("rid")
                if rid and rid not in seen:  # reruns re-log the same plan
                    seen.add(rid)
                    out.append((rid, int(doc.get("n", 0))))
    except FileNotFoundError:
        pass
    return out


def run_subprocess_legs(seed: int, gate: float, tmpdir: str,
                        log=print) -> Dict:
    """The legs only a real dead process can prove: genuine os._exit kills
    (``server_kill`` at a batch boundary), a torn live segment
    (``journal_torn_write`` mid-append), and supervised auto-restart."""
    from gauss_tpu import obs
    from gauss_tpu.resilience.inject import KILL_EXIT_CODE
    from gauss_tpu.serve import durable

    env_base = {k: v for k, v in os.environ.items() if k != "GAUSS_FAULTS"}
    env_base.setdefault("JAX_PLATFORMS", "cpu")
    legs: List[Dict] = []

    def _leg(name: str, faults: Optional[str], requests: int,
             supervised: bool) -> Dict:
        jd = os.path.join(tmpdir, f"leg-{name}")
        ledger = os.path.join(tmpdir, f"leg-{name}.ledger")
        # Every kill/stall leg records into a flight ring; the death (or
        # the unclean resume after it) must leave a post-mortem bundle the
        # leg asserts on — the flight recorder's own chaos coverage.
        fdir = os.path.join(tmpdir, f"leg-{name}.flight")
        leg: Dict = {"leg": name}
        t0 = time.perf_counter()
        with obs.span(f"durable_leg_{name}"):
            if supervised:
                env = dict(env_base)
                if faults:
                    env["GAUSS_FAULTS"] = faults
                rec = obs.active()
                before = (rec.counters.get("serve.supervisor_restarts", 0)
                          if rec else 0)
                rc = durable.supervise(
                    _drive_argv(jd, ledger, requests, seed),
                    heartbeat_path=os.path.join(jd, "heartbeat.json"),
                    max_restarts=2, stall_after_s=60.0, env=env, log=log,
                    flight_dir=fdir, journal_dir=jd)
                leg["supervise_rc"] = rc
                leg["restarts"] = ((rec.counters.get(
                    "serve.supervisor_restarts", 0) if rec else 0) - before)
                # The leg only proves something if the child really died
                # AND supervision brought the plan home.
                killed = rc == 0 and leg["restarts"] >= 1
            else:
                env = dict(env_base)
                env["GAUSS_FLIGHT_DIR"] = fdir
                if faults:
                    env["GAUSS_FAULTS"] = faults
                env_resume = dict(env_base)
                env_resume["GAUSS_FLIGHT_DIR"] = fdir
                p1 = subprocess.run(_drive_argv(jd, ledger, requests, seed),
                                    env=env, cwd=_REPO, timeout=300,
                                    capture_output=True, text=True)
                killed = p1.returncode == KILL_EXIT_CODE
                leg["first_rc"] = p1.returncode
                if p1.returncode not in (0, KILL_EXIT_CODE):
                    leg["stderr"] = p1.stderr[-1500:]
                # recovery run: no faults, no new requests — replay + drain.
                # Its start() finds the dead child's unterminated admits and
                # captures the 'unclean_resume' bundle this leg asserts on.
                p2 = subprocess.run(_drive_argv(jd, ledger, 0, seed),
                                    env=env_resume, cwd=_REPO, timeout=300,
                                    capture_output=True, text=True)
                leg["resume_rc"] = p2.returncode
                if p2.returncode != 0:
                    leg["stderr2"] = p2.stderr[-1500:]
                # idempotent rerun of the SAME plan: everything already
                # terminal must dedupe, not re-solve
                p3 = subprocess.run(_drive_argv(jd, ledger, requests, seed),
                                    env=env_base, cwd=_REPO, timeout=300,
                                    capture_output=True, text=True)
                leg["rerun_rc"] = p3.returncode
                for line in p3.stdout.splitlines():
                    if line.startswith("DRIVE:"):
                        leg["rerun"] = json.loads(line[6:])
        leg["killed"] = killed
        leg["audit"] = audit(jd, _read_ledger(ledger), gate)
        # Post-mortem assertion: a bundle was captured for this leg's death
        # and gauss-debug --check passes on it (integrity + exactly-one-
        # cause). Judged by the CLI itself — the artifact an operator gets.
        from gauss_tpu.obs import debug as _gdebug
        from gauss_tpu.obs import postmortem as _postmortem

        bundle = _postmortem.latest_bundle(
            _postmortem.default_bundles_dir(fdir))
        leg["bundle"] = bundle
        leg["bundle_check_rc"] = (_gdebug.main([bundle, "--check"])
                                  if bundle else None)
        leg["postmortem_ok"] = bundle is not None \
            and leg["bundle_check_rc"] == 0
        leg["wall_s"] = round(time.perf_counter() - t0, 3)
        a_ = leg["audit"]
        rerun = leg.get("rerun") or {}
        leg["outcome"] = (
            "violation" if (a_["missing"] or a_["duplicates"]
                            or a_["incorrect"] or not killed
                            or not leg["postmortem_ok"]
                            or rerun.get("solved_fresh", 0) > 0)
            else "ok")
        return leg

    legs.append(_leg("kill", "serve.server.batch=server_kill:skip=1", 10,
                     supervised=False))
    legs.append(_leg("torn",
                     "serve.journal.append=journal_torn_write:skip=9:param=0.6",
                     8, supervised=False))
    legs.append(_leg("supervised", "serve.server.batch=server_kill:skip=1",
                     10, supervised=True))
    return {"ran": True, "legs": legs,
            "violations": sum(1 for leg in legs
                              if leg["outcome"] == "violation")}


def run_overhead_phase(seed: int, gate: float, tmpdir: str,
                       cache=None) -> Dict:
    """The journal's cost, measured: one loadgen plan run journal-off then
    journal-on (same seed, same mix, shared executable cache so neither
    run pays compiles). The journal-on seconds-per-request and the
    on/off ratio enter history next to the PR-11 serving records."""
    from gauss_tpu import obs
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    results = {}
    # Warm pass (unmeasured, journal off): both measured runs must see the
    # same fully-compiled executable cache, or run ORDER — not the journal
    # — dominates the ratio (observed 30x in the first draft of this
    # campaign: the off run paid every batch-shape compile).
    warm_cfg = LoadgenConfig(mix="random:24*2,random:30", requests=24,
                             warmup=4, mode="closed", concurrency=4,
                             seed=seed, verify_gate=gate,
                             serve=_case_config(None, gate))
    with obs.span("durable_overhead_warm"):
        with SolverServer(warm_cfg.serve, cache=cache) as srv:
            run_load(srv, warm_cfg)
    for label, jd in (("off", None),
                      ("on", os.path.join(tmpdir, "overhead-journal"))):
        cfg = LoadgenConfig(mix="random:24*2,random:30", requests=24,
                            warmup=4, mode="closed", concurrency=4,
                            seed=seed, verify_gate=gate,
                            request_ids=jd is not None,
                            serve=_case_config(jd, gate))
        with obs.span(f"durable_overhead_{label}"):
            with SolverServer(cfg.serve, cache=cache) as srv:
                summary = run_load(srv, cfg)
        results[label] = {
            "throughput_rps": summary["throughput_rps"],
            "s_per_request": (round(1.0 / summary["throughput_rps"], 6)
                              if summary["throughput_rps"] else None),
            "p50_s": summary["latency_s"]["p50"],
            "incorrect": summary["incorrect"],
        }
        if label == "on":
            results["journal"] = summary.get("journal")
    off, on = results["off"]["s_per_request"], results["on"]["s_per_request"]
    results["overhead_ratio"] = (round(on / off, 4)
                                 if off and on else None)
    return results


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a campaign contributes to history.
    Slow-side gated: recovery getting slower shows as s_per_case, the
    journal getting more expensive as journal_s_per_request /
    overhead_ratio."""
    out: List[Tuple[str, float, str]] = []
    wall, cases = summary.get("wall_s"), summary.get("cases")
    if isinstance(wall, (int, float)) and wall > 0 and cases:
        out.append(("durable:s_per_case", round(wall / cases, 6), "s"))
    ov = summary.get("overhead") or {}
    on = (ov.get("on") or {}).get("s_per_request")
    if isinstance(on, (int, float)) and on > 0:
        # The journal-on absolute cost gates; the on/off RATIO rides in
        # the summary only — its denominator (sub-ms journal-off requests
        # at smoke sizes) jitters 1.8-3x between epochs on this box, which
        # would flake the band, while the numerator is stable.
        out.append(("durable:journal_s_per_request", on, "s"))
    return out


# -- the self-driving server child (--drive) -------------------------------

def drive_main(args) -> int:
    """Subprocess worker mode: run a journaled server against a seeded
    request plan, appending to the client LEDGER as each submit returns —
    the client-side truth the campaign audits the journal against. With
    GAUSS_FAULTS armed, this process dies mid-load; rerun with the same
    seed it resubmits the same request_ids and reports how many deduped
    vs solved fresh."""
    from gauss_tpu import obs
    from gauss_tpu.serve.server import SolverServer

    honor_jax_platforms()
    rng = np.random.default_rng(np.random.SeedSequence(
        (args.seed, 0xD21FE)))
    cfg = _case_config(args.journal, args.gate,
                       heartbeat_path=os.environ.get(
                           "GAUSS_SERVE_HEARTBEAT") or None,
                       flight_dir=os.environ.get("GAUSS_FLIGHT_DIR") or None)
    with obs.run(metrics_out=args.metrics_out, tool="durable_drive",
                 requests=args.requests, seed=args.seed):
        srv = SolverServer(cfg)
        srv.start()
        served_before = srv.requests_served
        handles = []
        with open(args.ledger, "a", buffering=1) as ledger:
            for j in range(args.requests):
                n = 16 + int(rng.integers(0, 13))
                a, b = _system(rng, n)
                rid = f"p{args.seed}-{j}"
                h = srv.submit(a, b, request_id=rid)
                # Ledger = freshly-ADMITTED requests only: a handle that is
                # already done at submit-return was rejected or answered
                # from the journal/pending dedupe (reruns), not admitted.
                if not h.done:
                    ledger.write(json.dumps({"rid": rid, "n": n}) + "\n")
                    ledger.flush()
                handles.append(h)
        deduped = 0
        for h in handles:
            res = h.result(timeout=180.0)
            if res.status is None:  # pragma: no cover
                return 3
        st = srv.journal.recovered
        for h in handles:
            if h.request_id in st.by_rid:
                deduped += 1
        srv.stop(drain=True, timeout=180.0)
        print("DRIVE:" + json.dumps({
            "requests": args.requests,
            "resume": srv.last_resume,
            "deduped": deduped,
            # fresh solves THIS incarnation (includes replays of a dead
            # predecessor's backlog; must be 0 on an idempotent rerun of a
            # fully-terminal plan)
            "solved_fresh": srv.requests_served - served_before,
        }))
    return 0


# -- campaign main ---------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.serve.durablecheck",
        description="Kill-the-server chaos campaign: crash/torn-write/"
                    "resume cases against the write-ahead request journal; "
                    "every admitted request must reach exactly one "
                    "terminal status (served results verified) with zero "
                    "duplicate solves under idempotent resubmission.")
    p.add_argument("--cases", type=int, default=28,
                   help="in-process recovery cases, cycled over kinds "
                        f"{CASE_KINDS} (default 28)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--tmpdir", default="/tmp/gauss_durable",
                   help="journal/ledger scratch directory")
    p.add_argument("--no-subprocess", action="store_true",
                   help="skip the real-kill subprocess legs (in-process "
                        "cases only — what the chaos campaign's durable "
                        "phase runs)")
    p.add_argument("--no-overhead", action="store_true",
                   help="skip the journal-off vs journal-on overhead "
                        "measurement")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append campaign records to the regression history "
                        "(default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    # -- the subprocess worker mode ---------------------------------------
    p.add_argument("--drive", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--journal", default=None, help=argparse.SUPPRESS)
    p.add_argument("--ledger", default=None, help=argparse.SUPPRESS)
    p.add_argument("--requests", type=int, default=10,
                   help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.drive:
        if not args.journal or not args.ledger:
            print("durablecheck --drive needs --journal and --ledger",
                  file=sys.stderr)
            return 2
        return drive_main(args)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve.cache import ExecutableCache

    os.makedirs(args.tmpdir, exist_ok=True)
    cache = ExecutableCache(64)  # shared across incarnations: the campaign
    #                              measures recovery, not XLA compiles
    t0 = time.perf_counter()
    outcomes: List[Dict] = []
    with obs.run(metrics_out=args.metrics_out, tool="durable_campaign",
                 cases=args.cases, seed=args.seed):
        with obs.span("durable_recovery_phase", cases=args.cases):
            for i in range(args.cases):
                kind = CASE_KINDS[i % len(CASE_KINDS)]
                outcomes.append(run_recovery_case(
                    i, args.seed, args.gate, args.tmpdir, kind,
                    cache=cache))
                if (i + 1) % 8 == 0:
                    print(f"  recovery cases: {i + 1}/{args.cases}")
        sub = ({} if args.no_subprocess
               else run_subprocess_legs(args.seed, args.gate, args.tmpdir))
        overhead = ({} if args.no_overhead
                    else run_overhead_phase(args.seed, args.gate,
                                            args.tmpdir, cache=cache))
        wall = round(time.perf_counter() - t0, 3)

        admitted = sum(o["audit"]["admitted"] for o in outcomes)
        terminals = sum(o["audit"]["admitted"] - len(o["audit"]["missing"])
                        for o in outcomes)
        case_violations = [o for o in outcomes if o["outcome"] != "ok"]
        statuses: Dict[str, int] = {}
        for o in outcomes:
            for k, v in o["audit"]["statuses"].items():
                statuses[k] = statuses.get(k, 0) + v
        replayed = sum(o.get("resume", {}).get("replayed", 0)
                       for o in outcomes)
        expired = sum(o.get("resume", {}).get("expired", 0)
                      for o in outcomes)
        deduped = sum(o.get("deduped", 0) for o in outcomes)
        torn = sum(o["audit"]["torn_dropped"] for o in outcomes)
        violations = (len(case_violations)
                      + (sub.get("violations", 0) if sub else 0))
        total_cases = args.cases + len(sub.get("legs", ()))
        summary = {
            "kind": "durable_campaign", "seed": args.seed,
            "gate": args.gate, "cases": total_cases,
            "in_process_cases": args.cases,
            "admitted": admitted, "terminal_covered": terminals,
            "statuses": statuses, "replayed": replayed,
            "expired_in_recovery": expired, "deduped": deduped,
            "torn_dropped": torn,
            "case_violations": [
                {k: o.get(k) for k in ("case", "kind", "error")}
                for o in case_violations],
            "subprocess": sub, "overhead": overhead, "wall_s": wall,
            "invariant_ok": violations == 0,
        }
        obs.emit("durable_campaign",
                 **{k: v for k, v in summary.items() if k != "kind"})

    print(f"durable campaign: {total_cases} case(s) "
          f"({args.cases} in-process + {len(sub.get('legs', ()))} "
          f"subprocess), {admitted} admitted request(s)")
    print(f"  terminals: {statuses} — {replayed} replayed, "
          f"{expired} expired-in-recovery, {deduped} deduped "
          f"resubmission(s), {torn} torn record(s) dropped")
    for leg in sub.get("legs", ()):
        a_ = leg["audit"]
        print(f"  leg[{leg['leg']}]: {leg['outcome']} "
              f"killed={leg['killed']} admitted={a_['admitted']} "
              f"missing={len(a_['missing'])} "
              f"duplicates={len(a_['duplicates'])} "
              f"bundle={'ok' if leg.get('postmortem_ok') else 'MISSING'} "
              f"rerun={leg.get('rerun')}")
    if overhead:
        print(f"  overhead: journal-off {overhead['off']['s_per_request']}"
              f" s/req -> journal-on {overhead['on']['s_per_request']} "
              f"s/req (ratio {overhead['overhead_ratio']})")
    print(f"  invariant {'HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "durablecheck",
                "kind": "durable"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        print(f"durablecheck: INVARIANT VIOLATED ({violations} case(s))",
              file=sys.stderr)
        for o in case_violations[:5]:
            print(f"  case {o['case']} [{o['kind']}]: {o.get('error')}",
                  file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
