"""``make mesh-serve-check`` — the mesh serving plane's end-to-end CI gate.

``python -m gauss_tpu.serve.meshcheck [--summary-json PATH]``

Three legs against the 8-virtual-device CPU proxy (the flag is forced
before jax loads), exit 2 on any assertion failure:

1. **Lane smoke.** A ``lanes=4 x lane_width=2`` server (every lane a
   2-device mesh slice; batch axis NamedSharding-sharded) under a
   SKEWED open-loop token mix: every request must serve and verify at
   1e-4, EVERY lane must dispatch >= 1 batch, and work stealing must
   occur (the skew piles the hot bucket onto its affinity lane; its
   siblings must take from it).
2. **Scrape = ledger.** The same run embeds the live telemetry plane;
   the Prometheus counter totals must agree EXACTLY with the loadgen's
   client-side ledger (served/rejected/expired/failed/retries) — two
   independent folds of one stream, now with four dispatch lanes racing.
3. **Continuous batching beats fixed drain cycles.** The A/B the ISSUE
   names, same open-loop mix and deadline, same 4 lanes, same formation
   window: continuous batching (in-flight admission + DEADLINE-AWARE
   slot closing) vs the fixed drain-cycle discipline (the pre-mesh
   ``batch_linger_s`` batching, which lingers blind to member
   deadlines). Asserted: CB's served solves/sec strictly higher AND its
   p99 equal-or-better — the drain cycle over-lingers deadline traffic
   into expiry; CB closes the slot a margin before the earliest member
   deadline and serves the same occupancy goal without shedding.

HONEST NOTE (asserted into the summary): the 1-core CPU proxy measures
DISPATCH/BATCHING efficiency — admission, formation, placement, steal
and shed behavior — not MXU scaling. The 8 virtual devices share one
core, so lane parallelism adds no FLOPs here; what the gate protects is
the serving plane's discipline, which is what transfers to a real mesh.

The summary (``kind: mesh_serve``) is regress-ingestable; 3 seeded
epochs are committed to reports/history.jsonl so smoke throughput, tail
latency, and the CB-over-fixed win ratio are history-gated in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

# MUST run before the first jax import anywhere in this process: the
# mesh plane needs the 8 virtual host devices CI tests standardize on.
from gauss_tpu.utils.env import force_host_device_count

force_host_device_count(8)

from typing import Dict, List, Tuple  # noqa: E402

SEED = 258458
#: A/B leg shape (see the module docstring and the ISSUE-14 analysis):
#: the formation window W is the occupancy linger both disciplines get;
#: the request deadline D sits BELOW it, so a discipline that lingers
#: blind must shed. Rates are far under the dispatch ceiling — the gap
#: measured is the discipline, not saturation.
AB_WINDOW_S = 0.4
AB_DEADLINE_S = 0.15
AB_MARGIN_S = 0.02
AB_RATE = 40.0
AB_REQUESTS = 80
#: CB must beat fixed drain by at least this served-throughput factor
#: (measured ~1.9x on the reference box; 1.25 leaves epoch-noise room).
AB_MIN_SPEEDUP = 1.25


def _fail(msg: str) -> None:
    print(f"mesh-serve-check: FAIL: {msg}", file=sys.stderr)
    raise SystemExit(2)


def _ok(msg: str) -> None:
    print(f"mesh-serve-check: ok: {msg}")


def _smoke_leg(args) -> Dict:
    """Legs 1+2: the skewed-mix lane smoke with the live plane embedded."""
    from gauss_tpu.obs import top as _top
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    cfg = ServeConfig(ladder=(16, 32, 64), max_batch=8, panel=16,
                      refine_steps=1, verify_gate=1e-4, max_queue=8192,
                      lanes=4, lane_width=2, continuous_batching=True,
                      cb_window_s=0.02, live_port=0)
    # Skew: the bucket-16 token dominates 8:2:1, so its affinity lane
    # floods and the steal path must engage. Closed-loop with a high
    # client count keeps a standing queue on the hot lane (open-loop at
    # smoke rates drains too fast for sibling lanes to ever find a
    # steal-deep queue) — and only THREE signatures exist for FOUR
    # lanes, so the fourth lane can serve at all only by stealing.
    # warmup=0: the lanes pre-warm their own executables (lane_warmup),
    # and the scrape-vs-ledger comparison below needs the obs counters to
    # count exactly the measured requests.
    lg = LoadgenConfig(mix="random:12*8,random:24*2,random:56",
                       requests=args.requests, warmup=0, mode="closed",
                       concurrency=16, seed=args.seed, serve=cfg)
    with SolverServer(cfg) as server:
        server._lanes.wait_warm()
        report = run_load(server, lg)
        mesh = report["mesh"]

        counts = report["counts"]
        if counts.get("ok", 0) != args.requests or report["incorrect"]:
            _fail(f"smoke: expected {args.requests} verified ok, got "
                  f"{counts} with {report['incorrect']} incorrect")
        _ok(f"smoke: {counts['ok']} requests served + verified over "
            f"{mesh['lanes']} lanes x{mesh['width']} devices")

        lanes_without = [p["lane"] for p in mesh["per_lane"]
                         if p["batches"] < 1]
        if lanes_without:
            _fail(f"smoke: lane(s) {lanes_without} served no batch — the "
                  f"mesh plane is not spreading work")
        _ok("smoke: every lane dispatched >= 1 batch "
            + str([(p['lane'], p['batches']) for p in mesh['per_lane']]))
        if mesh["steals"] < 1:
            _fail("smoke: no work stealing under the skewed mix")
        _ok(f"smoke: {mesh['steals']} steal(s) rebalanced the skew")
        if mesh["cb_admits"] < 1:
            _fail("smoke: no continuous-batching admissions — requests "
                  "never joined an in-flight forming slot")
        _ok(f"smoke: {mesh['cb_admits']} in-flight forming-slot admit(s)")

        # Leg 2: Prometheus scrape totals == the loadgen ledger, exactly.
        pairs: List[Tuple[str, int, str]] = [
            ("gauss_serve_served_total", counts.get("ok", 0), "served"),
            ("gauss_serve_rejected_total", counts.get("rejected", 0),
             "rejected"),
            ("gauss_serve_expired_total", counts.get("expired", 0),
             "expired"),
            ("gauss_serve_failed_total", counts.get("failed", 0),
             "failed"),
            ("gauss_serve_retries_total", report.get("retries", 0),
             "retries"),
        ]
        mismatch = None
        for _ in range(25):  # settle the worker-side counter increments
            samples = _top.parse_metrics(urllib.request.urlopen(
                f"{server.live_url}/metrics", timeout=10).read().decode())
            flat = {name: v for name, labels, v in samples if not labels}
            mismatch = next(((m, flat.get(m, 0), want, label)
                             for m, want, label in pairs
                             if flat.get(m, 0) != want), None)
            if mismatch is None:
                break
            import time as _time

            _time.sleep(0.1)
        if mismatch is not None:
            m, got, want, label = mismatch
            _fail(f"scrape: {m} ({label}) = {got}, loadgen ledger says "
                  f"{want}")
        _ok("scrape: /metrics totals equal the loadgen ledger exactly")
    return report


def _ab_leg(args, continuous: bool) -> Dict:
    """One arm of the CB-vs-fixed A/B (same mix/rate/deadline/window)."""
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    cfg = ServeConfig(ladder=(32, 64), max_batch=8, panel=16,
                      refine_steps=1, verify_gate=1e-4, max_queue=8192,
                      lanes=4, lane_width=1,
                      continuous_batching=continuous,
                      cb_window_s=AB_WINDOW_S,
                      cb_deadline_margin_s=AB_MARGIN_S,
                      batch_linger_s=AB_WINDOW_S)
    lg = LoadgenConfig(mix="random:24,random:48", requests=AB_REQUESTS,
                       warmup=8, mode="open", rate=AB_RATE,
                       seed=args.seed, deadline_s=AB_DEADLINE_S,
                       serve=cfg)
    with SolverServer(cfg) as server:
        server._lanes.wait_warm()
        return run_load(server, lg)


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a mesh_serve summary contributes to
    the regression history — all slow-side-gated: seconds-per-request and
    p95 rising = the lane plane got slower; fixed_over_cb rising = the
    continuous-batching win shrinking."""
    out: List[Tuple[str, float, str]] = []
    smoke = summary.get("smoke") or {}
    tput = smoke.get("throughput_rps")
    if isinstance(tput, (int, float)) and tput > 0:
        out.append(("mesh:smoke/s_per_request", round(1.0 / tput, 6), "s"))
    p95 = (smoke.get("latency_s") or {}).get("p95")
    if isinstance(p95, (int, float)) and p95 > 0:
        out.append(("mesh:smoke/p95_s", round(p95, 6), "s"))
    ab = summary.get("ab") or {}
    cb_tput = ab.get("cb_throughput_rps")
    if isinstance(cb_tput, (int, float)) and cb_tput > 0:
        out.append(("mesh:ab/cb_s_per_request",
                    round(1.0 / cb_tput, 6), "s"))
    ratio = ab.get("fixed_over_cb")
    if isinstance(ratio, (int, float)) and ratio > 0:
        out.append(("mesh:ab/fixed_over_cb", round(ratio, 6), "ratio"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.serve.meshcheck",
        description="End-to-end gate for the mesh serving plane: lane "
                    "smoke + steals, scrape-vs-ledger exactness, and the "
                    "continuous-batching-vs-fixed-drain A/B.")
    p.add_argument("--requests", type=int, default=120,
                   help="smoke-leg measured requests (default 120)")
    p.add_argument("--rate", type=float, default=120.0,
                   help="smoke-leg open-loop arrival rate (default 120)")
    p.add_argument("--seed", type=int, default=SEED)
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the summary (regress-ingestable: "
                        "kind=mesh_serve)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate this run against the history baselines "
                        "(exit 1 when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    import jax

    if len(jax.devices()) < 8:
        _fail(f"need 8 virtual devices, got {len(jax.devices())} — was "
              f"jax initialized before meshcheck set XLA_FLAGS?")

    from gauss_tpu import obs

    with obs.run(metrics_out=args.metrics_out, tool="mesh_serve_check",
                 seed=args.seed) as rec:
        smoke = _smoke_leg(args)

        cb = _ab_leg(args, continuous=True)
        fx = _ab_leg(args, continuous=False)
        cb_tput = cb["throughput_rps"] or 0.0
        fx_tput = fx["throughput_rps"] or 0.0
        cb_p99 = (cb["latency_s"]["p99"] or float("inf"))
        fx_p99 = (fx["latency_s"]["p99"] or float("inf"))
        if cb["incorrect"] or fx["incorrect"]:
            _fail("ab: incorrect solutions")
        if cb["counts"].get("ok", 0) < int(0.95 * AB_REQUESTS):
            _fail(f"ab: continuous batching served only "
                  f"{cb['counts']} of {AB_REQUESTS}")
        if not cb_tput > fx_tput * AB_MIN_SPEEDUP:
            _fail(f"ab: continuous batching {cb_tput:.2f} solves/s does "
                  f"not beat fixed drain {fx_tput:.2f} by "
                  f">= {AB_MIN_SPEEDUP}x on the same open-loop mix")
        if not cb_p99 <= fx_p99 * 1.05:
            _fail(f"ab: continuous batching p99 {cb_p99:.4f}s worse than "
                  f"fixed drain's {fx_p99:.4f}s")
        _ok(f"ab: continuous batching {cb_tput:.2f} solves/s vs fixed "
            f"drain {fx_tput:.2f} ({cb_tput / max(fx_tput, 1e-9):.2f}x) "
            f"at p99 {cb_p99:.4f}s vs {fx_p99:.4f}s "
            f"(fixed shed {fx['counts'].get('expired', 0)} of "
            f"{AB_REQUESTS} to the {AB_DEADLINE_S}s deadline)")

        summary = {
            "kind": "mesh_serve",
            "seed": int(args.seed),
            "run_id": rec.run_id,
            "note": ("1-core CPU proxy: measures dispatch/batching "
                     "efficiency (admission, formation, placement, "
                     "stealing, shedding), not MXU scaling — the 8 "
                     "virtual devices share one core"),
            "smoke": {k: smoke[k] for k in
                      ("counts", "throughput_rps", "latency_s", "wall_s",
                       "batch_occupancy_mean", "batches", "mesh")},
            "ab": {
                "window_s": AB_WINDOW_S, "deadline_s": AB_DEADLINE_S,
                "margin_s": AB_MARGIN_S, "rate": AB_RATE,
                "requests": AB_REQUESTS,
                "cb_throughput_rps": cb_tput,
                "cb_p99_s": cb["latency_s"]["p99"],
                "cb_counts": cb["counts"],
                "cb_occupancy": cb["batch_occupancy_mean"],
                "fixed_throughput_rps": fx_tput,
                "fixed_p99_s": fx["latency_s"]["p99"],
                "fixed_counts": fx["counts"],
                "fixed_occupancy": fx["batch_occupancy_mean"],
                "fixed_over_cb": round(fx_tput / max(cb_tput, 1e-9), 6),
            },
        }
        obs.emit("mesh_serve_check", **{k: v for k, v in summary.items()
                                        if k != "kind"})

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    from gauss_tpu.obs import regress

    records = [{"metric": m, "value": v, "unit": u,
                "source": f"meshcheck:{summary['run_id']}",
                "kind": "mesh_serve"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
