"""Shape-bucketed LRU cache of jitted batched-solve executables.

The whole point of bucketing (serve.buckets) is that the set of shapes the
service ever compiles is bounded; this module is the bound. A cache entry is
one :class:`BatchedExecutable` — a ``vmap``-batched blocked LU factor+solve
pair, jitted and warmed at its exact ``(batch, bucket_n, nrhs)`` shape — and
the cache holds at most ``capacity`` of them in LRU order, keyed

    (bucket_n, nrhs_bucket, batch_bucket, dtype, engine, refine_steps, mesh)

which is everything that changes the compiled program. ``mesh`` is None for
the single-chip batched lane (oversized requests route through
``solve_handoff`` and are never cached here); it sits in the key so a future
sharded batched lane slots in without a schema change.

Every hit/miss/evict is an obs event (``serve_cache``) plus counters, so the
loadgen's cache hit-rate is computed from the same stream the summarizer
renders.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject


def storage_dtype(key_dtype: str) -> np.dtype:
    """The numpy staging dtype for a CacheKey.dtype name. "bf16x3" is a
    GEMM mode, not a storage format — its executables stage float32 and
    run the split-GEMM trailing updates (core.matmul.dot_bf16x3);
    "bfloat16" resolves through ml_dtypes (registered by jax)."""
    return np.dtype("float32" if key_dtype == "bf16x3" else key_dtype)


class CacheKey(NamedTuple):
    bucket_n: int
    nrhs: int
    batch: int
    #: batched-lane precision: "float32", "bfloat16" (lowered storage,
    #: f32-accumulate contract), or "bf16x3" (f32 storage, split-GEMM
    #: updates) — core.lowered's ladder names. A key field since PR 3;
    #: the serve layer now actually varies it (ServeConfig.dtype /
    #: submit(dtype=)), so lowered and f32 executables cannot alias.
    dtype: str
    engine: str
    refine_steps: int
    mesh: Optional[str] = None
    #: structure routing tag (gauss_tpu.structure): "spd" compiles the
    #: vmapped blocked-Cholesky executable (half the factor FLOPs, no
    #: pivot gathers); other tags share the LU program but keep their own
    #: cache entries so structure-homogeneous batches stay together. None
    #: (the default) is the structure-unaware key — pre-existing keys and
    #: behavior are unchanged.
    structure: Optional[str] = None


class BatchedExecutable:
    """One compiled lane: vmapped blocked factor + solve at a fixed shape.

    ``factor`` and ``solve`` are jit-compiled over the BATCH axis — one
    device step factors all B systems and one more back-solves all B right-
    hand sides (the MAGMA-batched execution shape). Refinement reuses the
    batched factors: each step is one host-f64 batched residual (O(B n^2)
    matvec work) plus one more batched device solve — no refactorization.
    """

    def __init__(self, key: CacheKey, panel: Optional[int] = None):
        import jax

        from gauss_tpu.core import blocked
        from gauss_tpu.tune import apply as _tune

        self.key = key
        if panel is None:
            # Serve warmup consults the tuned store (gauss_tpu.tune): a
            # per-hardware winning panel width for this bucket replaces the
            # auto heuristic. The CACHE KEY is unchanged — tuning changes
            # how an executable is built, never which entry it is — and
            # with no store this resolves to None (the pre-existing auto
            # path). The consult emits the obs ``tune`` provenance event
            # the tune-check gate asserts on.
            panel = _tune.override("lu_factor", key.bucket_n, "panel",
                                   dtype=key.dtype, engine=key.engine)
            panel = int(panel) if panel else None
        self.panel = panel
        dtype = storage_dtype(key.dtype)
        gemm_precision = "bf16x3" if key.dtype == "bf16x3" else "highest"

        if key.structure == "spd":
            # The half-price lane: batched blocked Cholesky. Only
            # Gershgorin-CERTIFIED tags reach this key (the server's
            # detector never guesses SPD), and the bucket's identity
            # extension preserves definiteness, so the factorization is
            # well-posed for every padded member.
            from gauss_tpu.structure import cholesky as _chol

            def factor_one(a):
                return _chol.cholesky_factor_blocked(a, panel=panel)

            def solve_one(fac, b):
                return _chol.cholesky_solve(fac, b)
        else:
            def factor_one(a):
                return blocked.lu_factor_blocked(
                    a, panel=panel, gemm_precision=gemm_precision)

            def solve_one(fac, b):
                return blocked.lu_solve(fac, b)

        # Buffer donation on both lanes: the factor's matrix stack and the
        # solve's right-hand-side stack are freshly-staged host arrays on
        # every call (warmup identities, per-batch `.astype` copies — see
        # solve()), dead the moment the dispatch lands, so XLA reuses
        # their device buffers instead of holding a copy per step — the
        # copy-per-step the doctor diff shows riding along hook_sync. The
        # factors (arg 0 of _solve) are NOT donated: refinement reuses
        # them across every step of a batch. A bucket narrower than its
        # resolved panel pads inside the factor (output shape differs —
        # the donation would be unusable and warn), so only panel-multiple
        # buckets donate the factor operand; the solve output matches its
        # RHS shape at every bucket.
        from gauss_tpu.core.blocked import _resolve_panel

        p_res = _resolve_panel(key.bucket_n, panel, dtype.itemsize)
        fac_donate = (0,) if key.bucket_n % p_res == 0 else ()
        # The solve lane donates its RHS stack only when the output can
        # actually reuse it: a bf16 factor's solves return float32 (the
        # lu_solve accumulate contract), so the bf16 RHS buffer is
        # unusable for the result and the donation would warn per
        # compile instead of saving a copy.
        solve_donate = (1,) if key.dtype != "bfloat16" else ()
        self._factor = jax.jit(jax.vmap(factor_one),
                               donate_argnums=fac_donate)
        self._solve = jax.jit(jax.vmap(solve_one),
                              donate_argnums=solve_donate)
        # Compile at the exact serving shape now (identity systems), so the
        # one-time cost lands on the miss that created the entry — never
        # inside a later request's compute window.
        with obs.compile_span("serve_executable", bucket_n=key.bucket_n,
                              nrhs=key.nrhs, batch=key.batch,
                              dtype=key.dtype, engine=key.engine):
            eye = np.broadcast_to(np.eye(key.bucket_n, dtype=dtype),
                                  (key.batch, key.bucket_n, key.bucket_n))
            zer = np.zeros((key.batch, key.bucket_n, key.nrhs), dtype=dtype)
            fac = self._factor(np.ascontiguousarray(eye))
            jax.block_until_ready(self._solve(fac, zer))
        #: compile-time FLOP/byte budget per dispatch — computed lazily by
        #: cost_budget() (the attribution plane is its only reader; a
        #: server with attr=None never pays the cost analysis).
        self._cost = None       # lockset: ok — idempotent lazy cache; racing writers compute equal values

    def cost_budget(self) -> dict:
        """The per-dispatch FLOP/byte budget the attribution plane joins
        device time against: XLA's own ``cost_analysis`` numbers for the
        factor + ``refine_steps + 1`` solves (obs.compile.cost_summary over
        the already-jitted callables, at the warmup shapes), falling back
        to the analytic LU budget (obs.attr.lu_flop_budget) where XLA
        cannot report — so a roofline row exists for every engine
        exercised. Computed once per executable, cached; never raises."""
        cost = self._cost
        if cost is not None:
            return cost
        key = self.key
        flops = bytes_accessed = None
        try:
            from gauss_tpu.obs import compile as _compile

            dtype = storage_dtype(key.dtype)
            eye = np.broadcast_to(np.eye(key.bucket_n, dtype=dtype),
                                  (key.batch, key.bucket_n, key.bucket_n))
            eye = np.ascontiguousarray(eye)
            zer = np.zeros((key.batch, key.bucket_n, key.nrhs), dtype=dtype)
            fc = _compile.cost_summary(self._factor, eye) or {}
            fac = self._factor(eye)
            sc = _compile.cost_summary(self._solve, fac, zer) or {}
            rounds = 1 + key.refine_steps
            if fc.get("flops") or sc.get("flops"):
                flops = (float(fc.get("flops") or 0.0)
                         + float(sc.get("flops") or 0.0) * rounds)
            if fc.get("bytes_accessed") or sc.get("bytes_accessed"):
                bytes_accessed = (
                    float(fc.get("bytes_accessed") or 0.0)
                    + float(sc.get("bytes_accessed") or 0.0) * rounds)
        except Exception:  # noqa: BLE001 — accounting must not break serving
            pass
        if not flops or not bytes_accessed:
            from gauss_tpu.obs import attr as _attr

            if not flops:
                flops = _attr.lu_flop_budget(
                    key.bucket_n, key.nrhs, batch=key.batch,
                    refine_steps=key.refine_steps)
            if not bytes_accessed:
                bytes_accessed = _attr.lu_byte_budget(
                    key.bucket_n, key.nrhs, batch=key.batch,
                    itemsize=storage_dtype(key.dtype).itemsize,
                    refine_steps=key.refine_steps)
        cost = {"flops": flops, "bytes_accessed": bytes_accessed}
        self._cost = cost
        return cost

    def solve(self, a_pad: np.ndarray, b_pad: np.ndarray,
              placement=None) -> np.ndarray:
        """Solve the padded batch; returns float64 (B, bucket_n, nrhs).

        ``a_pad``/``b_pad`` are host float64 stacks at the cached shape.
        The device factors/solves in the key dtype; ``refine_steps`` rounds
        of host-f64 iterative refinement through the SAME batched factors
        recover the f64-residual accuracy the one-shot solvers get from
        ``solve_refined`` (each round: one batched residual + one batched
        device solve). Lowered keys ("bfloat16"/"bf16x3") stage at their
        storage dtype and lean on the same refinement — the f32-accuracy
        corrections of the lu_solve precision contract make each round
        contract by ~the factor's storage error.

        ``placement``: a jax Device or Sharding the operand stacks are
        device_put onto before dispatch — how the mesh serving lanes
        (gauss_tpu.serve.lanes) pin one executable's work to their own
        device (or shard its batch axis over their mesh slice). The TRACE
        is this one cached entry either way; jax compiles per distinct
        placement, so the backend cost is one compile per lane, paid at
        that lane's first dispatch, while every lane shares the Python-
        level build + warmup this cache exists to bound. None (default)
        is the pre-existing single-lane path, byte-identical.
        """
        dtype = storage_dtype(self.key.dtype)

        def _stage(arr):
            arr = arr.astype(dtype)
            if placement is not None:
                import jax

                arr = jax.device_put(arr, placement)
            return arr

        fac = self._factor(_stage(a_pad))
        x = np.asarray(self._solve(fac, _stage(b_pad)), dtype=np.float64)
        for _ in range(self.key.refine_steps):
            r = b_pad - np.einsum("bij,bjk->bik", a_pad, x)
            d = np.asarray(self._solve(fac, _stage(r)), dtype=np.float64)
            x = x + d
        return x


class ExecutableCache:
    """Bounded LRU over :class:`BatchedExecutable` entries (thread-safe)."""

    def __init__(self, capacity: int = 32):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        # guarded by: self._lock
        self._entries: "OrderedDict[CacheKey, BatchedExecutable]" = \
            OrderedDict()
        self._lock = threading.Lock()
        #: in-flight builds, for miss coalescing: key -> Event set when the
        #: owning builder finishes (successfully or not). Racing misses on
        #: the SAME key wait here instead of compiling a duplicate — with
        #: multiple dispatch lanes warming one shared cache, N lanes
        #: hitting a cold bucket must pay ONE build, not N.
        self._building: dict = {}       # guarded by: self._lock
        self.hits = 0                   # guarded by: self._lock
        self.misses = 0                 # guarded by: self._lock
        self.coalesced = 0              # guarded by: self._lock
        self.evictions = 0              # guarded by: self._lock

    def get(self, key: CacheKey,
            builder: Optional[Callable[[CacheKey], BatchedExecutable]] = None,
            panel: Optional[int] = None) -> BatchedExecutable:
        """The cached executable for ``key``, building (and possibly
        evicting the least-recently-used entry) on a miss. Concurrent
        misses on the same key COALESCE: one caller builds, the rest block
        on its completion and share the entry (counted as hits — they
        never compiled)."""
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    obs.counter("serve.cache.hits")
                    obs.emit("serve_cache", event="hit", **key._asdict())
                    return entry
                pending = self._building.get(key)
                if pending is None:
                    self._building[key] = threading.Event()
                    self.misses += 1
                    break
                self.coalesced += 1
            # Another thread owns this key's build: wait outside the lock
            # (a hit on a DIFFERENT key never queues behind a compile),
            # then re-check — normally a hit; if the build failed, the
            # loop claims the build slot and retries it.
            obs.counter("serve.cache.coalesced")
            obs.emit("serve_cache", event="coalesced", **key._asdict())
            pending.wait(timeout=600.0)
        # Build OUTSIDE the lock: compiles take seconds and a hit on a
        # different key must not wait behind them.
        obs.counter("serve.cache.misses")
        obs.emit("serve_cache", event="miss", **key._asdict())
        try:
            if _inject.enabled():
                # Hook point "serve.cache.compile": a simulated scoped-VMEM
                # / compile failure on executable build — RuntimeError-
                # shaped, so the server's transient-error retry/breaker
                # path owns it.
                _inject.maybe_raise("serve.cache.compile")
            entry = (builder
                     or (lambda k: BatchedExecutable(k, panel=panel)))(key)
            with self._lock:
                self._entries[key] = entry
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    evicted, _ = self._entries.popitem(last=False)
                    self.evictions += 1
                    obs.counter("serve.cache.evictions")
                    obs.emit("serve_cache", event="evict",
                             **evicted._asdict())
        finally:
            # Release the build slot whether or not the build succeeded —
            # strictly AFTER the entry insert, so a woken waiter always
            # finds either the entry (hit) or a free slot to retry a
            # FAILED build in (the failure still propagates to THIS
            # caller — the injected-compile-fault contract).
            with self._lock:
                done = self._building.pop(key, None)
            if done is not None:
                done.set()
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self):
        with self._lock:
            return list(self._entries.keys())

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses  # lockset: ok — stats snapshot
        return self.hits / total if total else 0.0  # lockset: ok — stats snapshot

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,  # lockset: ok — stats snapshot
                "coalesced": self.coalesced,  # lockset: ok — stats snapshot
                "evictions": self.evictions, "entries": len(self),  # lockset: ok — stats snapshot
                "capacity": self.capacity,
                "hit_rate": round(self.hit_rate, 4)}


#: Floor capacity of the process-shared cache: large enough that sharing
#: it never introduces eviction churn a private default cache would not
#: have had (the default ladder x a few dtype/structure variants).
SHARED_CAPACITY_MIN = 64

_shared: Optional[ExecutableCache] = None
_shared_lock = threading.Lock()


def shared_cache(capacity: int = SHARED_CAPACITY_MIN) -> ExecutableCache:
    """The process-shared :class:`ExecutableCache` — what a
    :class:`~gauss_tpu.serve.server.SolverServer` uses when its ctor is
    not handed an explicit ``cache=``. Respawned/supervised server
    incarnations, multi-lane warmup, and side-by-side servers in one
    process all land on the same entries, so a bucket executable is
    compiled once per process instead of once per server object (the
    PR-12 ``cache=`` sharing, made the default). Capacity only ever
    GROWS to the largest request seen — a later server asking for more
    room must not shrink an earlier one's working set."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = ExecutableCache(max(int(capacity),
                                          SHARED_CAPACITY_MIN))
        elif int(capacity) > _shared.capacity:
            _shared.capacity = int(capacity)
        return _shared


class CacheView:
    """One dispatch lane's view over a shared :class:`ExecutableCache`.

    The mesh serving plane (gauss_tpu.serve.lanes) runs one of these per
    lane: every ``get`` delegates to the ONE shared cache (so the Python-
    level build + warmup of a bucket executable is paid once per process —
    racing lane warmups coalesce on the in-flight build), while the view
    carries the lane-local state: which keys this lane has dispatched
    (``warmed`` — the per-lane backend compile has landed once a key is
    in it) and the lane's device placement, applied by the caller at
    ``solve(placement=...)`` time."""

    def __init__(self, cache: ExecutableCache):
        self.cache = cache
        self.warmed: set = set()

    def get(self, key: CacheKey,
            panel: Optional[int] = None) -> BatchedExecutable:
        entry = self.cache.get(key, panel=panel)
        self.warmed.add(key)
        return entry
