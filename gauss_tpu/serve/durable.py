"""Durable admission: the write-ahead request journal and crash recovery.

PR 5 made *worker* solves supervised and bit-identically resumable; PR 9
made the *math* SDC-proof. But ``SolverServer`` itself was still one
in-memory queue: ``kill -9`` the serving process mid-load and every admitted
request silently vanished with no terminal status — exactly the
exactly-one-terminal invariant PR 5/PR 8 established everywhere else. This
module is the missing durability layer:

- **Write-ahead journal.** With ``ServeConfig(journal_dir=...)`` every
  admitted request appends an ``admit`` record (operands included) to an
  append-only JSONL segment BEFORE ``submit()`` returns, and every terminal
  resolution appends a ``terminal`` record from the same first-resolve-wins
  CAS that already guarantees one terminal per request — so the journal
  carries exactly one terminal per admit by construction. Each record line
  is ``<crc32 hex> <json>``: a torn or truncated tail (kill mid-append, a
  merged partial line) fails its CRC and is DROPPED at scan time, never a
  crash — the journal parses to the longest valid record prefix no matter
  where the crash landed.
- **Batched fsync.** Appends flush to the OS on every record (a process
  kill — the failure mode the chaos campaign injects — cannot lose flushed
  bytes) and ``fsync`` every ``fsync_batch`` records plus at every
  shutdown-marker/rotation boundary (group commit against power loss).
- **Segment rotation.** When the live segment exceeds ``rotate_records``
  the journal compacts: live (unterminated) admits plus the recent
  idempotency terminals are rewritten into a fresh segment via the
  ``dcheckpoint`` atomic-write idiom (tmp + fsync + rename + parent fsync)
  and older segments are deleted — the journal's size tracks the live
  request set, not the traffic history.
- **Crash -> restart recovery.** On ``start()`` a server given a journal
  with unterminated admits (and no clean-shutdown marker) replays them
  through the normal dispatch path: still-in-deadline requests re-solve
  (and re-verify at the configured gate), past-deadline ones resolve as a
  typed ``STATUS_EXPIRED`` terminal. Replayed requests keep their ORIGINAL
  trace ids, so a request's obs span tree completes across the crash —
  ``requesttrace --check`` holds over kill -> restart.
- **Exactly-once from the client's view.** ``submit(request_id=...)``
  carries a client idempotency key into the journal; a resubmission whose
  key already has a journaled terminal resolves immediately from the
  journal — same status, same solution — without re-solving. (Execution is
  at-least-once across a crash window — a request killed after compute but
  before its terminal append is re-solved on recovery — but the terminal
  status, and anything a keyed client can observe, is exactly-once.)
- **Graceful drain.** ``stop(drain=True)`` — wired to SIGTERM in
  ``gauss-serve`` — stops admitting, flushes in-flight batches, resolves
  stragglers, and appends a clean-``shutdown`` marker so the next start
  replays nothing.
- **Supervision.** :func:`supervise` wraps the serving process in the PR-5
  fleet watchdog pattern: liveness + heartbeat-file freshness distinguish
  died from stalled, either one is restarted (bounded) against the SAME
  journal — warm via the PR-7 persistent compile cache — and recovery
  replays the dead process's unterminated admits. ``gauss-serve
  --supervised`` is the CLI form.

``journal_dir=None`` (the default) keeps all of this compiled out of the
serve path: one ``is None`` check at admission and none at resolve (the
terminal hook is only installed on journaled requests).

Fault hooks (gauss_tpu.resilience.inject): ``serve.server.batch`` fires at
every worker batch boundary (kind ``server_kill`` = os._exit — the honest
SIGKILL stand-in) and ``serve.journal.append`` fires per record append
(kind ``journal_torn_write`` writes a partial record then kills the
process: a crash mid-append, the torn tail recovery must drop).
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.resilience.checkpoint import fsync_dir

#: journal record schema (bumped on incompatible record changes; a scan of
#: a newer schema is a typed error, never a misparse)
JOURNAL_SCHEMA = 1

SEGMENT_PREFIX = "journal-"
SEGMENT_SUFFIX = ".jsonl"

#: idempotency terminals carried across a rotation compaction (the dedupe
#: window: a keyed resubmission older than this many terminals may re-solve)
IDEMPOTENCY_KEEP = 1024


class JournalError(RuntimeError):
    """The journal directory cannot be trusted (foreign schema, unreadable
    directory). Torn/truncated RECORDS are never this — they are dropped by
    construction; this is for damage recovery must not guess through."""


# -- array codec -----------------------------------------------------------

def encode_array(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(doc: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(doc["b64"])
    return np.frombuffer(raw, dtype=np.dtype(doc["dtype"])).reshape(
        doc["shape"]).copy()


# -- record line codec -----------------------------------------------------

def encode_record(doc: Dict[str, Any]) -> bytes:
    body = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {body}\n".encode("utf-8")


def decode_line(line: bytes) -> Optional[Dict[str, Any]]:
    """One journal line -> record dict, or None when the line is torn —
    short, CRC-mismatched (a partial record merged with the next append),
    or not JSON. Never raises: a corrupt line is a dropped line."""
    try:
        text = line.decode("utf-8")
    except UnicodeDecodeError:
        return None
    if len(text) < 10 or text[8] != " ":
        return None
    crc_hex, body = text[:8], text[9:].rstrip("\n")
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF != crc:
        return None
    try:
        doc = json.loads(body)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


# -- scan ------------------------------------------------------------------

class JournalState:
    """What a scan of a journal directory recovers: the admits still owed a
    terminal, the idempotency map, and whether the last run shut down
    cleanly."""

    def __init__(self):
        self.admits: Dict[int, Dict[str, Any]] = {}     # id -> admit record
        self.order: List[int] = []                      # admit ids, in order
        self.terminals: Dict[int, Dict[str, Any]] = {}  # id -> terminal
        #: client idempotency key -> terminal record (the dedupe map)
        self.by_rid: Dict[str, Dict[str, Any]] = {}
        #: pre-dispatch blame records (the quarantine evidence)
        self.blames: List[Dict[str, Any]] = []
        self.clean_shutdown = False
        self.records = 0
        self.torn_dropped = 0
        self.max_id = 0
        self.max_boot = 0

    def live_admits(self) -> List[Dict[str, Any]]:
        """Admit records with no terminal, in admission order — the replay
        set."""
        return [self.admits[i] for i in self.order if i not in self.terminals]

    def death_counts(self) -> Dict[int, int]:
        """For each admit still owed a terminal, the number of DISTINCT
        boots whose blame records implicate it — the quarantine evidence a
        replay consults. An id blamed twice in the SAME incarnation (two
        dispatch attempts before one crash) counts once: deaths, not
        dispatches. Terminated admits are excluded — a request that reached
        its terminal can no longer be the crash trigger being hunted."""
        boots: Dict[int, set] = {}
        for doc in self.blames:
            boot = doc.get("boot")
            for rid in doc.get("ids") or ():
                if isinstance(rid, int):
                    boots.setdefault(rid, set()).add(boot)
        return {i: len(boots[i]) for i in self.order
                if i not in self.terminals and i in boots}

    def apply(self, doc: Dict[str, Any]) -> None:
        rec = doc.get("rec")
        # Any record after a shutdown marker belongs to a NEWER run in the
        # same directory: the marker only means "clean" when final.
        if rec != "shutdown":
            self.clean_shutdown = False
        if rec == "admit":
            rid = doc.get("id")
            if isinstance(rid, int):
                self.admits[rid] = doc
                self.order.append(rid)
                self.max_id = max(self.max_id, rid)
        elif rec == "terminal":
            rid = doc.get("id")
            if isinstance(rid, int):
                self.terminals.setdefault(rid, doc)
                self.max_id = max(self.max_id, rid)
            key = doc.get("rid")
            if key:
                self.by_rid.setdefault(str(key), doc)
        elif rec == "blame":
            self.blames.append(doc)
            boot = doc.get("boot")
            if isinstance(boot, int):
                self.max_boot = max(self.max_boot, boot)
        elif rec == "shutdown":
            self.clean_shutdown = True


def segment_paths(dirpath: str) -> List[str]:
    try:
        names = sorted(n for n in os.listdir(dirpath)
                       if n.startswith(SEGMENT_PREFIX)
                       and n.endswith(SEGMENT_SUFFIX))
    except FileNotFoundError:
        return []
    return [os.path.join(dirpath, n) for n in names]


def scan(dirpath: str) -> JournalState:
    """Fold every segment (oldest first) into a :class:`JournalState`.
    Torn/truncated/merged lines are counted and dropped — by construction a
    scan parses to the longest valid prefix of each segment and NEVER
    raises on tail damage."""
    state = JournalState()
    for path in segment_paths(dirpath):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            if not line:
                continue
            doc = decode_line(line + b"\n")
            if doc is None:
                state.torn_dropped += 1
                continue
            if doc.get("schema", JOURNAL_SCHEMA) > JOURNAL_SCHEMA:
                raise JournalError(
                    f"journal segment {path} carries schema "
                    f"{doc.get('schema')} > {JOURNAL_SCHEMA}: refusing to "
                    f"replay records this build cannot interpret")
            state.records += 1
            state.apply(doc)
    return state


# -- the journal -----------------------------------------------------------

class RequestJournal:
    """Append-only, CRC-per-record, segment-rotated request journal.

    Thread-safe: client threads (admits, client-side cancels) and the
    worker thread (terminals) append concurrently under one lock. All
    appends go to the LIVE segment; a restart always opens a fresh segment
    so recovery appends never extend a possibly-torn tail.
    """

    def __init__(self, dirpath: str, *, fsync_batch: int = 8,
                 rotate_records: int = 4096):
        self.dir = os.fspath(dirpath)
        os.makedirs(self.dir, exist_ok=True)
        self.fsync_batch = max(1, int(fsync_batch))
        self.rotate_records = max(16, int(rotate_records))
        self._lock = threading.Lock()
        #: the state recovered from segments present at open (what a
        #: restart replays); live appends do NOT update it.
        self.recovered = scan(self.dir)
        #: this incarnation's boot number — monotone per journal open, so
        #: blame records from distinct incarnations are distinguishable and
        #: death_counts() counts deaths, not dispatch attempts.
        self.boot = self.recovered.max_boot + 1
        segs = segment_paths(self.dir)
        if segs:
            last = os.path.basename(segs[-1])
            seq = int(last[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]) + 1
        else:
            seq = 0
        self._seq = seq                 # guarded by: self._lock
        self._path = self._segment_path(seq)  # guarded by: self._lock
        self._f = open(self._path, "ab", buffering=0)  # guarded by: self._lock
        self._live_records = 0          # guarded by: self._lock
        #: rotate once the live segment holds this many records; reset
        #: past each compaction to carried + rotate_records, so a large
        #: carried set cannot re-trigger rotation on every append.
        self._rotate_at = self.rotate_records  # guarded by: self._lock
        self._since_fsync = 0           # guarded by: self._lock
        self.appends = 0
        self.fsyncs = 0
        self.rotations = 0
        self.closed = False             # guarded by: self._lock
        if self.recovered.torn_dropped:
            obs.emit("journal", event="torn_tail",
                     dropped=self.recovered.torn_dropped, dir=self.dir)
        obs.emit("journal", event="open", dir=self.dir, segment=self._seq,
                 recovered_records=self.recovered.records,
                 live=len(self.recovered.live_admits()),
                 clean_shutdown=self.recovered.clean_shutdown)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{SEGMENT_PREFIX}{seq:06d}"
                                      f"{SEGMENT_SUFFIX}")

    # -- append paths ------------------------------------------------------

    def _append(self, doc: Dict[str, Any], force_fsync: bool = False) -> None:
        payload = encode_record(doc)
        with self._lock:
            if self.closed:
                return
            if _inject.enabled():
                sp = _inject.poll_torn_write("serve.journal.append")
                if sp is not None:
                    # A crash MID-APPEND: a prefix of the record reaches the
                    # file, the process dies before the rest. `param` (0,1)
                    # picks the tear fraction; the torn line fails its CRC
                    # at the next scan and is dropped by construction.
                    frac = sp.param if 0 < sp.param < 1 else 0.5
                    cut = max(1, int(len(payload) * frac))
                    self._f.write(payload[:cut])
                    os._exit(_inject.KILL_EXIT_CODE)
            self._f.write(payload)  # unbuffered: flushed to the OS per record
            self.appends += 1
            self._live_records += 1
            self._since_fsync += 1
            if force_fsync or self._since_fsync >= self.fsync_batch:
                os.fsync(self._f.fileno())
                self.fsyncs += 1
                self._since_fsync = 0
            obs.counter("journal.appends")
            rotate = self._live_records >= self._rotate_at
        if rotate:
            self.rotate()

    def append_admit(self, *, id: int, request_id: Optional[str],
                     trace: str, a: np.ndarray, b: np.ndarray,
                     was_vector: bool, deadline_unix: Optional[float],
                     dtype: Optional[str], structure: Optional[str]) -> None:
        self._append({
            "rec": "admit", "schema": JOURNAL_SCHEMA, "id": int(id),
            "rid": request_id, "trace": trace,
            "n": int(a.shape[0]), "k": 1 if was_vector else int(b.shape[1]),
            "was_vector": bool(was_vector),
            "deadline_unix": deadline_unix, "t_unix": time.time(),
            "dtype": dtype, "structure": structure,
            "a": encode_array(np.asarray(a, np.float64)),
            "b": encode_array(np.asarray(b, np.float64)),
        })

    def append_terminal(self, *, id: int, request_id: Optional[str],
                        trace: str, status: str,
                        x: Optional[np.ndarray] = None,
                        lane: Optional[str] = None,
                        rel_residual: Optional[float] = None,
                        error: Optional[str] = None) -> Dict[str, Any]:
        doc = {"rec": "terminal", "schema": JOURNAL_SCHEMA, "id": int(id),
               "rid": request_id, "trace": trace, "status": status,
               "lane": lane, "t_unix": time.time(),
               "rel_residual": (float(rel_residual)
                               if rel_residual is not None else None),
               "error": (str(error)[:500] if error else None)}
        if x is not None:
            doc["x"] = encode_array(np.asarray(x, np.float64))
        self._append(doc)
        return doc

    def append_blame(self, *, ids: List[int],
                     rids: Optional[List[str]] = None,
                     boot: Optional[int] = None) -> None:
        """The pre-dispatch blame record: names every journal id (and
        client rid) the next dispatch puts at risk. A crash between this
        append and the batch's terminals leaves the ids implicated in this
        boot — the evidence ``JournalState.death_counts`` folds into the
        replay-time quarantine decision.

        ``boot`` overrides this incarnation's boot number; journal ADOPTION
        uses negative synthetic boots (never colliding with real boots,
        which start at 1) to carry a dead replica's death counts onto the
        adopter's journal id."""
        self._append({"rec": "blame", "schema": JOURNAL_SCHEMA,
                      "boot": int(self.boot if boot is None else boot),
                      "ids": [int(i) for i in ids],
                      "rids": [str(r) for r in (rids or [])],
                      "t_unix": time.time()})

    def append_shutdown(self) -> None:
        """The clean-shutdown marker: the next start replays nothing. Always
        fsynced — this is the record whose absence means 'crashed'."""
        self._append({"rec": "shutdown", "schema": JOURNAL_SCHEMA,
                      "t_unix": time.time()}, force_fsync=True)
        obs.emit("journal", event="shutdown_marker", dir=self.dir)

    # -- rotation ----------------------------------------------------------

    def rotate(self) -> None:
        """Compact into a fresh segment: re-journal the still-live admits
        plus the most recent :data:`IDEMPOTENCY_KEEP` keyed terminals (the
        dedupe window), atomically (tmp + fsync + rename + dir fsync), then
        delete the older segments. A kill at any instant leaves either the
        old segments or the complete new one."""
        with self._lock:
            if self.closed:
                return
            os.fsync(self._f.fileno())
            self.fsyncs += 1
            self._since_fsync = 0
            self._f.close()
            state = scan(self.dir)
            keep: List[Dict[str, Any]] = state.live_admits()
            keyed = [t for t in state.terminals.values() if t.get("rid")]
            keyed.sort(key=lambda t: t.get("t_unix") or 0.0)
            keep += keyed[-IDEMPOTENCY_KEEP:]
            # Blame evidence follows the admits it implicates: a rotation
            # must not amnesty a poison request's death history.
            live_ids = {d["id"] for d in state.live_admits()}
            keep += [bl for bl in state.blames
                     if live_ids.intersection(bl.get("ids") or ())]
            old = segment_paths(self.dir)
            self._seq += 1
            self._path = self._segment_path(self._seq)
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(self._path) + ".", suffix=".tmp",
                dir=self.dir)
            with os.fdopen(fd, "wb") as f:
                for doc in keep:
                    f.write(encode_record(doc))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
            fsync_dir(self.dir)
            for path in old:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._f = open(self._path, "ab", buffering=0)
            self._live_records = len(keep)
            self._rotate_at = len(keep) + self.rotate_records
            self.rotations += 1
            obs.counter("journal.rotations")
            obs.emit("journal", event="rotate", segment=self._seq,
                     carried=len(keep), deleted=len(old))

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            try:
                os.fsync(self._f.fileno())
                self.fsyncs += 1
            except OSError:
                pass
            self._f.close()
            self.closed = True

    def abandon(self) -> None:
        """Crash stand-in for in-process chaos: drop the file handle with
        no fsync, no marker, no close bookkeeping — the journal directory
        is left exactly as a kill would leave it."""
        with self._lock:
            try:
                self._f.close()
            finally:
                self.closed = True

    def stats(self) -> Dict[str, Any]:
        segs = segment_paths(self.dir)
        return {"dir": self.dir, "segments": len(segs),
                "appends": self.appends, "fsyncs": self.fsyncs,
                "rotations": self.rotations,
                "bytes": sum(os.path.getsize(p) for p in segs
                             if os.path.exists(p)),
                "recovered_records": self.recovered.records,
                "torn_dropped": self.recovered.torn_dropped}


def terminal_to_result(doc: Dict[str, Any]):
    """A journaled terminal record -> the client-visible ServeResult a
    deduped resubmission resolves with (solution included when journaled)."""
    from gauss_tpu.serve.admission import ServeResult

    x = decode_array(doc["x"]) if doc.get("x") is not None else None
    return ServeResult(status=doc.get("status"), x=x, lane=doc.get("lane"),
                       rel_residual=doc.get("rel_residual"),
                       error=doc.get("error"))


def quarantinable_ids(dirpath: str, k: int = 1) -> Dict[int, int]:
    """Scan a (possibly dead) journal directory for live admit ids
    implicated in at least ``k`` prior worker deaths: ``{id: deaths}``.
    The router/fleet reclassification and journal-adoption paths use this
    to recognize a poison-driven death without owning a journal handle.
    Never raises on a damaged directory — no evidence means no quarantine."""
    try:
        counts = scan(dirpath).death_counts()
    except (JournalError, OSError):
        return {}
    return {i: c for i, c in counts.items() if c >= k}


# -- the supervisor --------------------------------------------------------

def supervise(child_argv: List[str], *, heartbeat_path: str,
              max_restarts: int = 3, stall_after_s: float = 30.0,
              poll_s: float = 0.25, term_grace_s: float = 15.0,
              env: Optional[Dict[str, str]] = None,
              flight_dir: Optional[str] = None,
              journal_dir: Optional[str] = None,
              quarantine_deaths: int = 2,
              log=print) -> int:
    """Run ``child_argv`` under the PR-5 fleet watchdog pattern and restart
    it — against the same journal — when it dies or stalls.

    - *died*: the child process exited nonzero (crash, kill, preemption).
    - *stalled*: the child is alive but its heartbeat file (written from
      the serve worker loop) has not been touched for ``stall_after_s`` —
      it is killed, then restarted.
    - restarts are bounded by ``max_restarts``; a child that exits 0 ends
      supervision with 0. Respawns strip ``GAUSS_FAULTS`` from the
      environment: an injected kill models a ONE-OFF crash, the same
      max_triggers=1 contract the in-process hooks have — without this the
      replayed plan would re-kill every incarnation at the same boundary.

    The journal makes the restart correct: the replacement's ``--resume``
    replays the dead incarnation's unterminated admits, and the PR-7
    persistent compile cache (pass ``--compile-cache``/GAUSS_COMPILE_CACHE
    through) makes it warm. SIGTERM to the supervisor forwards to the
    child for a graceful drain (clean-shutdown marker) before exiting.

    With ``flight_dir`` set, every died/stalled detection ALSO harvests the
    dead incarnation's flight ring into a post-mortem bundle
    (``gauss_tpu.obs.postmortem``) BEFORE the restart overwrites the scene
    — the child inherits the dir through ``GAUSS_FLIGHT_DIR`` so its serve
    loop installs the ring sink without any extra flags.
    """
    base_env = dict(env if env is not None else os.environ)
    base_env["GAUSS_SERVE_HEARTBEAT"] = heartbeat_path
    if flight_dir:
        base_env["GAUSS_FLIGHT_DIR"] = os.fspath(flight_dir)

    def _capture(cause: str, **detail) -> None:
        """Supervisor-side post-mortem capture (owner of the
        serve.server.batch / serve.journal.append crash sites when
        supervised). Never raises — a capture failure must not cost the
        restart."""
        if not flight_dir:
            return
        try:
            from gauss_tpu.obs import postmortem

            postmortem.capture_bundle(
                postmortem.default_bundles_dir(flight_dir), cause,
                flight_dir=flight_dir, journal_dir=journal_dir,
                heartbeat_path=heartbeat_path, extra=detail, log=log)
        except Exception as e:  # pragma: no cover — capture is best-effort
            log(f"supervise: post-mortem capture failed: {e}")
    restarts = 0
    draining = {"flag": False}
    child: Dict[str, Optional[subprocess.Popen]] = {"proc": None}
    # Quarantine growth guard: a death is only "free" (uncharged) when some
    # live id's death count GREW TO the quarantine threshold or past it —
    # exactly when the next replay changes behavior for that suspect (solo
    # at K deaths, typed reject past K), so the respawn is the ladder
    # converging rather than a crash loop. Growth alone is not enough:
    # EVERY mid-dispatch crash blames its in-flight batch once, and an
    # environmental crasher under load would otherwise respawn for free
    # forever. Counts are bounded per id (past K the replay rejects
    # terminally), so free respawns are finite by construction; a crash
    # whose suspects stay under the threshold (innocent workload, broken
    # build) charges the budget as before. ``quarantine_deaths`` must
    # match the child server's ``ServeConfig.quarantine_deaths`` (both
    # default 2); 0 disables free respawns along with the policy.
    prev_deaths: Dict[int, int] = (
        quarantinable_ids(journal_dir) if journal_dir else {})

    def _forward_term(signum, frame):  # pragma: no cover — signal timing
        draining["flag"] = True
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)

    prev = None
    try:
        prev = signal.signal(signal.SIGTERM, _forward_term)
    except ValueError:  # not the main thread (tests drive this in-thread)
        prev = None

    def _hb_age() -> Optional[float]:
        try:
            return time.time() - os.path.getmtime(heartbeat_path)
        except OSError:
            return None

    try:
        spawn_env = base_env
        while True:
            t_spawn = time.time()
            proc = subprocess.Popen(child_argv, env=spawn_env)
            child["proc"] = proc
            obs.counter("serve.supervisor_spawns")
            obs.emit("serve_supervisor", event="spawn", pid=proc.pid,
                     restarts=restarts)
            log(f"supervise: spawned pid {proc.pid} (restart {restarts})")
            stalled = False
            while proc.poll() is None:
                time.sleep(poll_s)
                if draining["flag"]:
                    continue  # drain in progress; wait for clean exit
                age = _hb_age()
                # Only call a stall once the child has had time to write
                # its first beat (spawn + jax import can take seconds).
                if (age is not None and age > stall_after_s
                        and time.time() - t_spawn > stall_after_s):
                    stalled = True
                    obs.emit("serve_supervisor", event="stall",
                             pid=proc.pid, heartbeat_age_s=round(age, 3))
                    log(f"supervise: pid {proc.pid} stalled "
                        f"(heartbeat {age:.1f}s stale); killing")
                    proc.kill()
                    proc.wait(timeout=term_grace_s)
                    break
            rc = proc.returncode
            if rc == 0 and not stalled:
                obs.emit("serve_supervisor", event="done", restarts=restarts)
                return 0
            if draining["flag"]:
                obs.emit("serve_supervisor", event="drained", rc=rc)
                return rc if rc is not None else 0
            cause = "stalled" if stalled else f"died rc={rc}"
            quarantined = False
            if journal_dir and quarantine_deaths > 0:
                cur = quarantinable_ids(journal_dir)
                quarantined = any(c >= quarantine_deaths
                                  and c > prev_deaths.get(i, 0)
                                  for i, c in cur.items())
                prev_deaths = cur
            _capture("poison_quarantine" if quarantined
                     else "supervisor_stall" if stalled
                     else "supervisor_death",
                     rc=rc, restarts=restarts, pid=proc.pid)
            if quarantined:
                obs.counter("serve.quarantined_respawns")
                obs.emit("serve_supervisor", event="restart",
                         cause="quarantined", underlying=cause,
                         restarts=restarts)
                log(f"supervise: child {cause} with a suspect at the "
                    f"quarantine threshold — quarantined death; restarting "
                    f"without charging the budget "
                    f"({restarts}/{max_restarts} spent)")
            elif restarts >= max_restarts:
                obs.emit("serve_supervisor", event="gave_up", cause=cause,
                         restarts=restarts)
                log(f"supervise: {cause}; restart budget "
                    f"({max_restarts}) spent — giving up")
                return rc if rc else 1
            else:
                restarts += 1
                obs.counter("serve.supervisor_restarts")
                obs.emit("serve_supervisor", event="restart", cause=cause,
                         restarts=restarts)
                log(f"supervise: child {cause}; restarting against the same "
                    f"journal ({restarts}/{max_restarts})")
            # One-off-crash contract: injected fault plans die with the
            # incarnation they killed.
            spawn_env = {k: v for k, v in base_env.items()
                         if k != _inject.ENV_VAR}
    finally:
        if prev is not None:
            signal.signal(signal.SIGTERM, prev)
        proc = child["proc"]
        if proc is not None and proc.poll() is None:  # pragma: no cover
            proc.kill()
