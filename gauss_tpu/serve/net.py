"""The network request tier: HTTP serving on top of ``SolverServer``.

Until this module, ``submit()`` was an in-process Python call — one
process, one failure domain, one host's worth of clients. This is the
wire half of the replicated serving tier (ROADMAP "[scale] A real serving
tier"); the process half — consistent-hash routing across N replica
processes with journal-backed failover — is :mod:`gauss_tpu.serve.router`.

**Wire format** (``WIRE_SCHEMA = 1``, JSON over stdlib HTTP — the PR-8
``LiveServer`` pattern extended to a request API):

=============================  ===========================================
``POST /v1/solve``             body: ``schema``, ``request_id`` (the PR-12
                               idempotency key — journaled, so resubmitting
                               the same key after ANY crash dedupes to the
                               journaled terminal), ``matrix_id`` (routing
                               affinity), ``deadline_s``, ``dtype``,
                               ``structure``, ``b`` (inline array doc) and
                               ``a`` — inline for small systems or
                               ``{"upload": id}`` referencing a chunked
                               upload. 200 = terminal result doc (``x``
                               base64), 202 = still pending after
                               ``wait_s`` (poll ``GET /v1/requests/<rid>``),
                               503 = admission rejected, with the
                               ``Retry-After`` header carrying the server's
                               drain-rate hint, 409 = the ``a`` upload is
                               missing/incomplete (re-send the slabs).
``POST /v1/upload``            one row-slab of a big operand: ``upload``,
                               ``seq``/``total``, ``rows`` ``[r0, r1)``,
                               ``shape``/``dtype``, ``data`` (array doc).
                               Idempotent per ``(upload, seq)`` — a client
                               retrying a torn connection re-sends slabs
                               safely. Slab height comes from
                               :func:`slab_rows` — the out-of-core tile
                               framing (``outofcore.stream
                               .outofcore_window``: width = budget //
                               row-bytes) turned sideways for the wire.
``GET /v1/requests/<rid>``     streamed NDJSON status: ``pending`` lines
                               while the request runs, then the terminal
                               result doc; close-delimited.
``POST /v1/adopt``             failover: scan the journal dir in the body
                               and adopt it — import its terminals for
                               idempotent dedupe and replay its live
                               admits through this server
                               (:func:`adopt_journal`).
``GET /healthz``               liveness + queue depth + the retry hint.
=============================  ===========================================

**Client contract** (:class:`SolveClient`): deadline-capped retries —
the total retry budget never exceeds ``deadline_s`` plus a small slack,
because retrying past the deadline buys a typed expiry at best; full-
jitter exponential backoff (:func:`full_jitter_backoff` — ``uniform(0,
min(cap, base·2^attempt))``, the decorrelating form) on transport errors;
the ``Retry-After`` hint honored on 503; and every request carries an
idempotency key (client-minted when the caller gave none), so a resubmit
after a replica death can never double-solve — the journal answers.

Lockset note (gauss-lint audits this file like ``server.py``):
:func:`adopt_journal` mirrors ``SolverServer.submit``'s admission
critical section under ``server._depth_lock`` and is deliberately a
module-level function — the pending-map insert, journal append, and
depth bump form one atomic step against concurrent submits.
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import random
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterator, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from gauss_tpu import obs
from gauss_tpu.serve import durable
from gauss_tpu.serve.admission import (
    STATUS_EXPIRED,
    STATUS_FAILED,
    STATUS_POISON,
    STATUS_REJECTED,
    ServeRequest,
    ServeResult,
    poison_scan,
)

#: wire schema version; bumped on incompatible body changes.
WIRE_SCHEMA = 1
#: how long a POST /v1/solve parks server-side before answering 202.
DEFAULT_WAIT_S = 30.0
#: target bytes per upload slab (~1 MiB keeps any single request body
#: bounded regardless of n — the same bound the out-of-core tile window
#: puts on device-resident bytes).
UPLOAD_SLAB_BYTES = 1 << 20
#: in-progress uploads kept per replica (oldest evicted past this).
UPLOAD_KEEP = 64
#: operands above this many bytes go through chunked upload by default.
UPLOAD_THRESHOLD_BYTES = 4 << 20


# -- framing / codecs ------------------------------------------------------

def slab_rows(n_cols: int, itemsize: int,
              target_bytes: int = UPLOAD_SLAB_BYTES) -> int:
    """Rows per upload slab: how many fit ``target_bytes`` — the
    out-of-core window formula (bytes budget // bytes per row) with the
    budget meaning "one HTTP body" instead of "the device fraction"."""
    row_bytes = max(1, int(n_cols) * int(itemsize))
    return max(1, int(target_bytes) // row_bytes)


def iter_slabs(a: np.ndarray, target_bytes: int = UPLOAD_SLAB_BYTES,
               ) -> Iterator[Tuple[int, int, int, np.ndarray]]:
    """Yield ``(seq, r0, r1, rows)`` row-slabs covering ``a`` in order."""
    a = np.asarray(a)
    rows = slab_rows(a.shape[1] if a.ndim > 1 else 1, a.dtype.itemsize,
                     target_bytes)
    seq = 0
    for r0 in range(0, a.shape[0], rows):
        r1 = min(a.shape[0], r0 + rows)
        yield seq, r0, r1, a[r0:r1]
        seq += 1


def slab_count(n_rows: int, n_cols: int, itemsize: int,
               target_bytes: int = UPLOAD_SLAB_BYTES) -> int:
    """Total slabs :func:`iter_slabs` will produce for an (n_rows, n_cols)
    operand (what ``total`` must be on every upload body)."""
    rows = slab_rows(n_cols, itemsize, target_bytes)
    return -(-int(n_rows) // rows)


def full_jitter_backoff(base_s: float, attempt: int,
                        rng: Optional[random.Random] = None,
                        cap_s: float = 30.0) -> float:
    """Full-jitter exponential backoff: ``uniform(0, min(cap,
    base·2^attempt))``. The fully-jittered form decorrelates a resubmit
    storm — after a replica death every client retries, and the plain
    exponential (admission.retry_backoff) would march them into the
    survivor in lockstep waves."""
    ceiling = min(float(cap_s), float(base_s) * (2 ** int(attempt)))
    return (rng or random).uniform(0.0, max(0.0, ceiling))


def matrix_digest(a: np.ndarray) -> str:
    """Content digest of an operand — the default ``matrix_id`` routing
    affinity key: repeat-A traffic hashes to the same replica, so its
    bucket executables and (future) factor caches stay warm there."""
    a = np.ascontiguousarray(a)
    h = hashlib.md5(a.tobytes())
    h.update(str(a.shape).encode())
    return h.hexdigest()[:16]


def result_doc(res: ServeResult) -> Dict[str, Any]:
    """ServeResult -> wire terminal doc (x base64 via the journal codec)."""
    doc: Dict[str, Any] = {
        "schema": WIRE_SCHEMA, "status": res.status, "lane": res.lane,
        "bucket_n": res.bucket_n, "trace": res.trace,
        "latency_s": res.latency_s, "queue_s": res.queue_s,
        "retry_after_s": res.retry_after_s, "error": res.error,
        "rel_residual": res.rel_residual,
        "sdc_detected": bool(res.sdc_detected),
        "device_s": res.device_s, "compile_s": res.compile_s,
    }
    if res.x is not None:
        doc["x"] = durable.encode_array(np.asarray(res.x))
    return doc


def doc_result(doc: Dict[str, Any]) -> ServeResult:
    """Wire terminal doc -> ServeResult (the client-side inverse)."""
    x = None
    if doc.get("x") is not None:
        x = durable.decode_array(doc["x"])
    return ServeResult(
        status=str(doc.get("status")), x=x, lane=doc.get("lane"),
        bucket_n=doc.get("bucket_n"), trace=doc.get("trace"),
        latency_s=doc.get("latency_s"), queue_s=doc.get("queue_s"),
        retry_after_s=doc.get("retry_after_s"), error=doc.get("error"),
        rel_residual=doc.get("rel_residual"),
        sdc_detected=bool(doc.get("sdc_detected")),
        device_s=doc.get("device_s"), compile_s=doc.get("compile_s"))


# -- journal adoption (failover replay on a surviving peer) ----------------

def adopt_journal(server, dirpath: str) -> Dict[str, Any]:
    """Adopt a DEAD replica's journal onto ``server`` (the failover half
    of exactly-once): import its rid-keyed terminals into the adopter's
    dedupe map — in MEMORY only, so the adopter's journal never grows a
    second terminal record for a request the dead replica finished — and
    replay its unterminated admits through the adopter's own admission
    (fresh journal ids, ORIGINAL trace ids and request ids), so every
    admitted request still reaches exactly one terminal:

    - in-deadline live admits re-enter the adopter's queue (re-journaled
      here as the adopter's own admits — the retired journal is never
      written again);
    - admits whose deadline expired during the failover window resolve as
      typed ``STATUS_EXPIRED`` terminals, never a silent drop;
    - an admit whose rid is already pending or terminal on the adopter
      (a client resubmit raced the failover) is SKIPPED — the existing
      request owns the terminal.

    The pending-map check, journal append, and depth bump run as ONE
    critical section under ``server._depth_lock`` — the same section
    ``submit()`` admits under — so a resubmit racing this replay can
    never double-admit one logical request from either side.
    """
    st = durable.scan(dirpath)
    imported = 0
    for rid, doc in st.by_rid.items():
        if rid and rid not in server._rid_terminals:
            server._rid_terminals[rid] = doc
            imported += 1
    replayed = expired = skipped = poisoned = quarantined = 0
    cfg = server.config
    k_deaths = int(cfg.quarantine_deaths or 0)
    deaths = st.death_counts() if k_deaths else {}
    now = time.time()
    for doc in st.live_admits():
        try:
            a = durable.decode_array(doc["a"])
            b = durable.decode_array(doc["b"])
        except Exception:  # pragma: no cover — admit body damaged
            obs.counter("journal.replay_undecodable")
            continue
        if doc.get("was_vector"):
            b = b.reshape(-1)
        rid = doc.get("rid")
        remaining = None
        if doc.get("deadline_unix") is not None:
            remaining = float(doc["deadline_unix"]) - now
        req = ServeRequest(
            a, b,
            deadline_s=(remaining if remaining is None or remaining > 0
                        else None),
            structure=(doc.get("structure")
                       if server.config.structure_aware else None),
            dtype=doc.get("dtype") or server.config.dtype,
            request_id=rid)
        if doc.get("trace"):
            req.trace_id = str(doc["trace"])
        is_expired = remaining is not None and remaining <= 0
        # The dead replica's quarantine evidence crosses the failover: a
        # rid implicated in K prior deaths stays quarantined (solo) on the
        # adopter, past K it is typed-rejected — a naive re-replay here
        # would re-trigger the very crash that killed the donor.
        reason = (poison_scan(a, b) if cfg.poison_scan else None)
        implicated = deaths.get(doc.get("id"), 0)
        poison_reject = (not is_expired
                         and (reason is not None
                              or (k_deaths and implicated > k_deaths)))
        if (not is_expired and not poison_reject
                and k_deaths and implicated >= k_deaths):
            req.quarantine = True
        admitted = False
        duplicate = False
        with server._depth_lock:
            if server._closed:
                pass
            elif rid and (rid in server._rid_pending
                          or rid in server._rid_terminals):
                duplicate = True
            else:
                if server.journal is not None:
                    server.journal.append_admit(
                        id=req.id, request_id=rid, trace=req.trace_id,
                        a=req.a, b=req.b, was_vector=req.was_vector,
                        deadline_unix=doc.get("deadline_unix"),
                        dtype=req.dtype, structure=req.structure)
                    if implicated and not poison_reject:
                        # Re-journal the donor's death count against the
                        # ADOPTER's fresh journal id (synthetic negative
                        # boots: distinct from each other and from real
                        # boots), so a further crash or failover still
                        # sees the full history.
                        for d in range(implicated):
                            server.journal.append_blame(
                                ids=[req.id],
                                rids=[rid] if rid else None,
                                boot=-(d + 1))
                    req._on_terminal = server._journal_terminal
                    if rid:
                        server._rid_pending[rid] = req
                admitted = True
                if not is_expired and not poison_reject:
                    server._depth += 1
                    if server._lanes is None:
                        server._queue.put(req)
        if duplicate:
            skipped += 1
            continue
        if not admitted:
            # The adopter itself is stopping — refuse with a terminal
            # rather than dropping (the router will re-adopt elsewhere).
            if req.resolve(ServeResult(status=STATUS_REJECTED,
                                       error="adopter stopped during "
                                             "failover")):
                obs.counter("serve.rejected")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_REJECTED,
                         reason="adopter_stopped")
            continue
        if is_expired:
            expired += 1
            if req.resolve(ServeResult(
                    status=STATUS_EXPIRED,
                    error="deadline expired during replica failover "
                          "(journal replay on peer)")):
                obs.counter("serve.adopt_expired")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_EXPIRED,
                         replayed=True, adopted=True)
            continue
        if poison_reject:
            poisoned += 1
            err = (f"poisoned operands: {reason}" if reason is not None
                   else f"quarantined: implicated in {implicated} worker "
                        f"deaths (threshold {k_deaths})")
            if req.resolve(ServeResult(status=STATUS_POISON, error=err)):
                obs.counter("serve.poisoned")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_POISON,
                         reason="adopt_replay", deaths=implicated,
                         replayed=True, adopted=True)
            continue
        if req.quarantine:
            quarantined += 1
            obs.counter("serve.quarantined")
            obs.emit("quarantine", id=req.id, rid=rid, trace=req.trace_id,
                     deaths=implicated, action="solo", adopted=True)
        lanes = server._lanes  # lockset: ok — snapshot read, same as submit
        if lanes is not None and not lanes.place(req):
            server._depth_add(-1)
            if req.resolve(ServeResult(status=STATUS_REJECTED,
                                       error="adopter stopped during "
                                             "failover")):
                obs.counter("serve.rejected")
                obs.emit("serve_request", id=req.id, n=req.n,
                         trace=req.trace_id, status=STATUS_REJECTED,
                         reason="adopter_stopped")
            continue
        replayed += 1
        obs.counter("serve.adopted")
        obs.emit("serve_admit", id=req.id, trace=req.trace_id, n=req.n,
                 k=req.k, replayed=True, adopted=True)
    out = {"dir": dirpath, "imported": imported, "replayed": replayed,
           "expired": expired, "skipped": skipped, "poisoned": poisoned,
           "quarantined": quarantined, "torn_dropped": st.torn_dropped}
    obs.emit("replica_adopt", **out)
    return out


# -- the replica-side application ------------------------------------------

class ReplicaApp:
    """The HTTP-facing application around one :class:`SolverServer`:
    body parsing, chunked-upload assembly, and the status lookup the
    streamed GET reads. Transport lives in :class:`RequestApi`."""

    def __init__(self, server):
        self.server = server
        self._upload_lock = threading.Lock()
        #: upload id -> {"total", "shape", "dtype", "slabs": {seq: rows}}
        self._uploads: Dict[str, Dict[str, Any]] = {}  # guarded by: self._upload_lock

    # -- uploads -----------------------------------------------------------

    def handle_upload(self, doc: Dict[str, Any]) -> Tuple[int, Dict]:
        try:
            upload = str(doc["upload"])
            seq = int(doc["seq"])
            total = int(doc["total"])
            rows = durable.decode_array(doc["data"])
            shape = [int(v) for v in doc["shape"]]
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad upload body: {e}"}
        if seq < 0 or seq >= total:
            return 400, {"error": f"seq {seq} outside total {total}"}
        with self._upload_lock:
            entry = self._uploads.get(upload)
            if entry is None:
                entry = {"total": total, "shape": shape,
                         "dtype": str(doc.get("dtype", rows.dtype)),
                         "slabs": {}}
                self._uploads[upload] = entry
                while len(self._uploads) > UPLOAD_KEEP:
                    self._uploads.pop(next(iter(self._uploads)))
            # Idempotent per (upload, seq): a client re-sending after a
            # torn connection overwrites with identical bytes.
            entry["slabs"][seq] = rows
            have = len(entry["slabs"])
        return 200, {"upload": upload, "have": have, "total": total,
                     "complete": have >= total}

    def _take_upload(self, ref: Dict[str, Any]) -> Optional[np.ndarray]:
        """Assemble and CONSUME a completed upload; None when incomplete
        or unknown (the 409 path — the client re-sends its slabs)."""
        upload = str(ref.get("upload"))
        with self._upload_lock:
            entry = self._uploads.get(upload)
            if entry is None or len(entry["slabs"]) < entry["total"]:
                return None
            entry = self._uploads.pop(upload)
        parts = [entry["slabs"][i] for i in range(entry["total"])]
        a = np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        return np.asarray(a, dtype=np.dtype(entry["dtype"])).reshape(
            entry["shape"])

    # -- solve / status ----------------------------------------------------

    def _operand(self, doc_or_ref) -> Tuple[Optional[np.ndarray], bool]:
        """(array, upload_missing): decode an inline array doc or consume
        an upload reference."""
        if isinstance(doc_or_ref, dict) and "upload" in doc_or_ref:
            a = self._take_upload(doc_or_ref)
            return a, a is None
        return durable.decode_array(doc_or_ref), False

    def handle_solve(self, doc: Dict[str, Any]) -> Tuple[int, Dict]:
        schema = doc.get("schema", WIRE_SCHEMA)
        if schema != WIRE_SCHEMA:
            return 400, {"error": f"wire schema {schema} unsupported "
                                  f"(this replica speaks {WIRE_SCHEMA})"}
        try:
            a, a_missing = self._operand(doc["a"])
            if a_missing:
                return 409, {"error": "operand upload incomplete — "
                                      "re-send the slabs",
                             "upload": doc["a"].get("upload"),
                             "missing": True}
            b, _ = self._operand(doc["b"])
            deadline_s = doc.get("deadline_s")
            deadline_s = None if deadline_s is None else float(deadline_s)
            wait_s = float(doc.get("wait_s", DEFAULT_WAIT_S))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad solve body: {e}"}
        try:
            req = self.server.submit(
                a, b, deadline_s=deadline_s,
                structure=doc.get("structure"), dtype=doc.get("dtype"),
                request_id=doc.get("request_id"))
        except ValueError as e:
            return 400, {"error": str(e)}
        req.wait(max(0.0, wait_s))
        res = req.peek()
        if res is None:
            return 202, {"schema": WIRE_SCHEMA, "pending": True,
                         "request_id": doc.get("request_id"),
                         "trace": req.trace_id}
        if res.status == STATUS_REJECTED:
            out = result_doc(res)
            if out.get("retry_after_s") is None:
                out["retry_after_s"] = self.server.retry_after_hint()
            return 503, out
        if res.status == STATUS_POISON:
            # A typed verdict about the REQUEST, not the replica: 400, not
            # 500/503 — the client must not retry a poisoned operand.
            return 400, result_doc(res)
        return 200, result_doc(res)

    def lookup(self, rid: str) -> Tuple[Optional[ServeRequest],
                                        Optional[ServeResult]]:
        """Status by idempotency key: ``(pending request, None)``,
        ``(None, terminal result)``, or ``(None, None)`` for unknown."""
        req = self.server._rid_pending.get(rid)
        if req is not None:
            res = req.peek()
            return (None, res) if res is not None else (req, None)
        term = self.server._rid_terminals.get(rid)
        if term is not None:
            return None, durable.terminal_to_result(term)
        return None, None

    def handle_adopt(self, doc: Dict[str, Any]) -> Tuple[int, Dict]:
        dirpath = doc.get("dir")
        if not dirpath or not os.path.isdir(dirpath):
            return 400, {"error": f"adopt: no journal dir at {dirpath!r}"}
        return 200, adopt_journal(self.server, dirpath)

    def health(self) -> Dict[str, Any]:
        return {"status": "ok", "pid": os.getpid(),
                "depth": self.server._depth_snapshot(),
                "retry_after_s": self.server.retry_after_hint()}


class _NetHandler(BaseHTTPRequestHandler):
    """One request-API connection (the obs.export bound-handler idiom:
    ``RequestApi`` subclasses this with ``app`` bound per server)."""

    server_version = "gauss-net/1"
    app: ReplicaApp = None  # type: ignore[assignment] # set per server

    def log_message(self, fmt, *args):  # quiet: obs, not stderr noise
        pass

    def _json(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        data = (json.dumps(payload, sort_keys=True) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):  # client went away
            pass

    def _body(self) -> Optional[Dict[str, Any]]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
            return json.loads(raw)
        except (ValueError, OSError):
            return None

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = urlparse(self.path).path
        doc = self._body()
        if doc is None:
            self._json(400, {"error": "unparseable JSON body"})
            return
        if path == "/v1/solve":
            code, payload = self.app.handle_solve(doc)
            headers = None
            if code == 503 and payload.get("retry_after_s") is not None:
                # ceil: Retry-After is integer seconds and rounding a
                # 0.3 s hint down to 0 would tell clients to hammer.
                secs = max(1, int(float(payload["retry_after_s"]) + 0.999))
                headers = {"Retry-After": str(secs)}
            self._json(code, payload, headers)
        elif path == "/v1/upload":
            code, payload = self.app.handle_upload(doc)
            self._json(code, payload)
        elif path == "/v1/adopt":
            code, payload = self.app.handle_adopt(doc)
            self._json(code, payload)
        else:
            self._json(404, {"error": f"unknown endpoint {path!r}"})

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        if url.path == "/healthz":
            self._json(200, self.app.health())
            return
        if url.path.startswith("/v1/requests/"):
            rid = url.path[len("/v1/requests/"):]
            try:
                wait_s = float(parse_qs(url.query).get(
                    "wait", [str(DEFAULT_WAIT_S)])[0])
            except ValueError:
                self._json(400, {"error": "bad wait= value"})
                return
            self._stream_status(rid, wait_s)
            return
        self._json(404, {"error": f"unknown endpoint {url.path!r}",
                         "endpoints": ["/healthz", "/v1/requests/<rid>"]})

    def _stream_status(self, rid: str, wait_s: float) -> None:
        """NDJSON status stream: pending heartbeat lines while the request
        runs, then the terminal doc; the close delimits the stream."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        self.end_headers()
        t_end = time.monotonic() + max(0.0, wait_s)

        def _line(payload: Dict[str, Any]) -> None:
            self.wfile.write(
                (json.dumps(payload, sort_keys=True) + "\n").encode())
            self.wfile.flush()

        try:
            while True:
                req, res = self.app.lookup(rid)
                if res is not None:
                    _line(result_doc(res))
                    return
                if req is None:
                    _line({"unknown": True, "request_id": rid})
                    return
                now = time.monotonic()
                if now >= t_end:
                    _line({"pending": True, "timeout": True})
                    return
                _line({"pending": True})
                req.wait(min(0.5, t_end - now))
        except (BrokenPipeError, ConnectionResetError):
            pass


class RequestApi:
    """The embedded request endpoint: a daemon-threaded stdlib HTTP
    server bound to one :class:`ReplicaApp` (``port=0`` = ephemeral;
    read the bound address back from :attr:`url`)."""

    def __init__(self, app: ReplicaApp, port: int = 0,
                 host: str = "127.0.0.1"):
        self.app = app
        handler = type("BoundNetHandler", (_NetHandler,), {"app": app})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "RequestApi":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="gauss-net",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "RequestApi":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- the client ------------------------------------------------------------

class _NullCacheStats:
    """Client-side stand-in for the server's executable-cache stats: the
    loadgen report reads ``cache.hits``/``.misses``/``.stats()`` — over
    the wire those live in the replicas, so the client reports zeros."""

    hits = 0
    misses = 0

    @staticmethod
    def stats() -> Dict[str, int]:
        return {"entries": 0, "capacity": 0, "evictions": 0}


class _NetHandle:
    """The async handle :meth:`SolveClient.submit` returns — the network
    analog of :class:`ServeRequest` as far as ``result()`` goes."""

    def __init__(self):
        self._ev = threading.Event()
        self._box: Dict[str, ServeResult] = {}

    def _finish(self, res: ServeResult) -> None:
        self._box["res"] = res
        self._ev.set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._ev.wait(timeout):
            raise TimeoutError(f"network solve timed out after {timeout} s")
        return self._box["res"]

    @property
    def done(self) -> bool:
        return self._ev.is_set()


class SolveClient:
    """HTTP client for the replica/router tier with the retry contract
    baked in: deadline-capped budget, full-jitter exponential backoff,
    ``Retry-After`` honored, chunked upload for big operands, and an
    auto-minted idempotency key on every request so resubmission is
    always safe (the journal dedupes). API-compatible with the loadgen's
    server interface (``solve``/``submit``/``cache``/``batches``/
    ``retries``), so ``gauss-serve --net URL`` drives it unchanged."""

    def __init__(self, url: str, *, timeout_s: float = 600.0,
                 wait_s: float = DEFAULT_WAIT_S,
                 retry_base_s: float = 0.05, retry_cap_s: float = 2.0,
                 deadline_slack_s: float = 2.0,
                 upload_threshold: int = UPLOAD_THRESHOLD_BYTES,
                 seed: Optional[int] = None):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.wait_s = float(wait_s)
        self.retry_base_s = float(retry_base_s)
        self.retry_cap_s = float(retry_cap_s)
        self.deadline_slack_s = float(deadline_slack_s)
        self.upload_threshold = int(upload_threshold)
        self.cache = _NullCacheStats()
        self.batches = 0
        self._lock = threading.Lock()
        self.retries = 0        # guarded by: self._lock
        self._rng = random.Random(seed)  # guarded by: self._lock
        self._minted = 0        # guarded by: self._lock
        with self._lock:
            self._rid_prefix = f"net{self._rng.getrandbits(32):08x}"

    # -- bookkeeping -------------------------------------------------------

    def _count_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def _mint_rid(self) -> str:
        with self._lock:
            self._minted += 1
            return f"{self._rid_prefix}-{self._minted}"

    def _jitter(self, attempt: int) -> float:
        with self._lock:
            return full_jitter_backoff(self.retry_base_s, attempt,
                                       rng=self._rng,
                                       cap_s=self.retry_cap_s)

    # -- transport ---------------------------------------------------------

    def _http(self, method: str, path: str, doc: Optional[Dict],
              timeout: float) -> Tuple[int, Dict[str, str], Dict]:
        data = None if doc is None else json.dumps(doc).encode()
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return (resp.status, dict(resp.headers),
                        json.loads(resp.read()))
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                payload = json.loads(raw)
            except (ValueError, TypeError):
                payload = {"error": raw[:200].decode("utf-8", "replace")}
            return e.code, dict(e.headers or {}), payload

    def _upload(self, upload_id: str, a: np.ndarray, budget_s: float,
                rid: Optional[str] = None,
                matrix_id: Optional[str] = None) -> None:
        total = slab_count(a.shape[0], a.shape[1] if a.ndim > 1 else 1,
                           a.dtype.itemsize)
        for seq, r0, r1, rows in iter_slabs(a):
            # request_id/matrix_id ride along on every slab so a routing
            # front tier can land the upload on the same replica the
            # subsequent solve will hash to.
            code, _, payload = self._http(
                "POST", "/v1/upload",
                {"upload": upload_id, "seq": seq, "total": total,
                 "request_id": rid, "matrix_id": matrix_id,
                 "rows": [r0, r1], "shape": list(a.shape),
                 "dtype": str(a.dtype),
                 "data": durable.encode_array(rows)},
                timeout=max(1.0, min(30.0, budget_s)))
            if code != 200:
                raise urllib.error.URLError(
                    f"upload slab {seq}/{total} refused: HTTP {code} "
                    f"{payload.get('error')}")

    def _poll_status(self, rid: str, t_end: float) -> Optional[ServeResult]:
        """Follow the streamed status endpoint until a terminal doc, the
        budget runs out (None -> the caller re-POSTs; idempotent), or the
        replica reports the rid unknown (failover remapped it — re-POST
        lands on the adopter and dedupes against its imported journal)."""
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            return None
        req = urllib.request.Request(
            f"{self.url}/v1/requests/{rid}?wait={max(0.1, remaining):.3f}")
        try:
            with urllib.request.urlopen(req, timeout=remaining + 10.0) \
                    as resp:
                for raw in resp:
                    doc = json.loads(raw)
                    if doc.get("pending"):
                        continue
                    if doc.get("unknown"):
                        return None
                    return doc_result(doc)
        except (urllib.error.URLError, OSError, ValueError,
                http.client.HTTPException):
            return None
        return None

    # -- the request path --------------------------------------------------

    def solve(self, a, b, deadline_s: Optional[float] = None,
              timeout: Optional[float] = None,
              dtype: Optional[str] = None,
              structure: Optional[str] = None,
              request_id: Optional[str] = None) -> ServeResult:
        """One solve over the wire, retried to completion or budget
        exhaustion. The budget is DEADLINE-CAPPED: ``min(timeout,
        deadline_s + slack)`` — past the request's deadline every retry
        can only buy a typed expiry, so the client stops paying for it."""
        a = np.asarray(a)
        b = np.asarray(b)
        rid = request_id or self._mint_rid()
        budget = self.timeout_s if timeout is None else float(timeout)
        if deadline_s is not None:
            budget = min(budget, float(deadline_s) + self.deadline_slack_s)
        t_end = time.monotonic() + budget
        inline = a.nbytes <= self.upload_threshold
        body: Dict[str, Any] = {
            "schema": WIRE_SCHEMA, "request_id": rid,
            "matrix_id": matrix_digest(a), "deadline_s": deadline_s,
            "dtype": dtype, "structure": structure,
            "b": durable.encode_array(b)}
        if inline:
            body["a"] = durable.encode_array(a)
        else:
            body["a"] = {"upload": f"{rid}-a", "shape": list(a.shape),
                         "dtype": str(a.dtype)}
        uploaded = False
        attempt = 0
        last_error = "no attempt completed"
        while True:
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                break
            try:
                if not inline and not uploaded:
                    self._upload(f"{rid}-a", a, remaining, rid=rid,
                                 matrix_id=body["matrix_id"])
                    uploaded = True
                wait = max(0.1, min(self.wait_s, remaining))
                body["wait_s"] = round(wait, 3)
                code, headers, payload = self._http(
                    "POST", "/v1/solve", body, timeout=wait + 10.0)
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException) as e:
                # Transport failure: the replica may be dead mid-failover.
                # The POST is resubmit-safe (idempotency key), so back off
                # with full jitter and try again — the router remaps rids
                # to the adopter.
                last_error = f"transport: {type(e).__name__}: {e}"
                self._count_retry()
                time.sleep(max(0.0, min(self._jitter(attempt),
                                        t_end - time.monotonic())))
                attempt += 1
                continue
            if code == 200:
                return doc_result(payload)
            if code == 202:
                res = self._poll_status(rid, t_end)
                if res is not None:
                    return res
                last_error = "pending past the poll window"
                self._count_retry()
                continue
            if code == 409 and not inline:
                # The replica lost the upload (restart / failover moved
                # the rid): re-send the slabs, then re-POST.
                uploaded = False
                last_error = "operand upload missing on the replica"
                self._count_retry()
                continue
            if code == 503:
                hint = payload.get("retry_after_s")
                if hint is None and headers.get("Retry-After"):
                    try:
                        hint = float(headers["Retry-After"])
                    except ValueError:
                        hint = None
                delay = max(float(hint or 0.0), self._jitter(attempt))
                last_error = (f"rejected (retry after "
                              f"{float(hint or 0.0):.3g} s)")
                self._count_retry()
                time.sleep(max(0.0, min(delay, t_end - time.monotonic())))
                attempt += 1
                continue
            # 4xx and anything else: deterministic — retrying replays it.
            if payload.get("status") == STATUS_POISON:
                # The replica's typed poison verdict survives the wire:
                # the client sees STATUS_POISON, not a generic HTTP failure.
                return doc_result(payload)
            return ServeResult(
                status=STATUS_FAILED,
                error=f"HTTP {code}: {payload.get('error')}")
        return ServeResult(
            status=STATUS_FAILED,
            error=f"retry budget exhausted after {budget:.1f} s "
                  f"({last_error})")

    def submit(self, a, b, deadline_s: Optional[float] = None,
               dtype: Optional[str] = None,
               structure: Optional[str] = None,
               request_id: Optional[str] = None) -> _NetHandle:
        """Async form: run :meth:`solve` on a daemon thread and return a
        handle whose ``result(timeout)`` blocks (the loadgen warmup-burst
        surface)."""
        handle = _NetHandle()

        def _run():
            try:
                res = self.solve(a, b, deadline_s=deadline_s, dtype=dtype,
                                 structure=structure,
                                 request_id=request_id)
            except Exception as e:  # noqa: BLE001 — the handle must resolve
                res = ServeResult(status=STATUS_FAILED,
                                  error=f"{type(e).__name__}: {e}")
            handle._finish(res)

        threading.Thread(target=_run, name="gauss-net-client",
                         daemon=True).start()
        return handle


# -- the replica child process ---------------------------------------------

def build_replica_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.serve.net",
        description="One network serving replica: a journaled "
                    "SolverServer behind the request API. Spawned and "
                    "watched by gauss_tpu.serve.router; runnable solo "
                    "for tests.")
    p.add_argument("--replica", action="store_true",
                   help="required marker: this invocation is a replica "
                        "child (guards against accidental bare runs)")
    p.add_argument("--dir", required=True,
                   help="replica state dir: journal/, heartbeat.json, "
                        "endpoint.json, obs.jsonl, flight/")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="bind port (0 = ephemeral; the bound address is "
                        "published to <dir>/endpoint.json)")
    p.add_argument("--ladder", default=None,
                   help="comma-separated bucket ladder")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--linger", type=float, default=0.0)
    p.add_argument("--verify-gate", type=float, default=None)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--fsync-batch", type=int, default=4)
    return p


def replica_main(argv: Optional[List[str]] = None) -> int:
    """Entry point for one replica child. SIGTERM/SIGINT triggers a
    graceful drain (journal clean-shutdown marker) and exits with
    ``fleet.DRAIN_EXIT`` so the supervisor's restart accounting knows
    this was an operator drain, not a crash."""
    args = build_replica_parser().parse_args(argv)
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    from gauss_tpu.resilience import fleet as _fleet
    from gauss_tpu.serve import buckets
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.server import SolverServer
    from gauss_tpu.tune import compilecache as _cc

    _cc.enable_from_env()
    d = args.dir
    os.makedirs(d, exist_ok=True)
    ladder = ()
    if args.ladder:
        ladder = buckets.validate_ladder(
            int(r) for r in args.ladder.split(","))
    cfg = ServeConfig(
        ladder=ladder, max_batch=args.max_batch, max_queue=args.max_queue,
        batch_linger_s=args.linger, dtype=args.dtype,
        verify_gate=args.verify_gate,
        journal_dir=os.path.join(d, "journal"), resume=True,
        journal_fsync_batch=args.fsync_batch,
        heartbeat_path=os.path.join(d, "heartbeat.json"),
        flight_dir=(os.environ.get("GAUSS_FLIGHT_DIR")
                    or os.path.join(d, "flight")))
    # The handler touches ONLY this dict — never a threading primitive.
    # Event.set() from a signal handler can deadlock: the handler runs on
    # the main thread, and if the signal lands while that thread holds the
    # Event's internal (non-reentrant) lock inside wait(), set() blocks on
    # a lock its own thread owns and the drain never happens.
    drained = {"requested": False}

    def _term(signum, frame):
        drained["requested"] = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    with obs.run(metrics_out=os.path.join(d, "obs.jsonl"),
                 tool="gauss_serve_replica", replica_dir=d):
        with SolverServer(cfg) as server:
            app = ReplicaApp(server)
            api = RequestApi(app, port=args.port, host=args.host).start()
            tmp = os.path.join(d, "endpoint.json.tmp")
            with open(tmp, "w") as f:
                json.dump({"url": api.url, "pid": os.getpid()}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(d, "endpoint.json"))
            obs.emit("replica", event="listening", url=api.url,
                     pid=os.getpid(), dir=d,
                     resume=server.last_resume)
            while not drained["requested"]:
                time.sleep(0.2)
            api.stop()
            server.stop(drain=True)
            obs.emit("replica", event="drained", pid=os.getpid(), dir=d)
    return _fleet.DRAIN_EXIT if drained["requested"] else 0


if __name__ == "__main__":
    sys.exit(replica_main())
