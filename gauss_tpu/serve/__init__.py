"""gauss_tpu.serve — batched solver serving on top of the solver tiers.

The reference is twelve one-shot binaries; the ROADMAP north star is a
service. This package is that layer: a long-lived in-process server that
pads arbitrary-``n`` requests onto a small shape-bucket ladder, drains a
bounded queue into ``vmap``-batched blocked-LU solves through an LRU cache
of jitted executables, routes oversized systems through ``solve_handoff``,
and degrades to a host NumPy lane when the device lane is persistently
unhealthy — with admission control (queue bounds + deadlines) in front and
an open/closed-loop load generator beside it. Everything emits obs events,
so ``summarize``/``trace``/``regress`` cover serving the same way they
cover solves.

Quick tour::

    from gauss_tpu.serve import ServeConfig, SolverServer

    with SolverServer(ServeConfig(verify_gate=1e-4)) as srv:
        res = srv.solve(a, b)            # pads, batches, caches, verifies
        assert res.ok
        x = res.x

    # Load-test it:  gauss-serve --requests 200 --mix random:100*3,random:300
"""

from gauss_tpu.serve.admission import (  # noqa: F401
    STATUS_CANCELLED,
    STATUS_EXPIRED,
    STATUS_FAILED,
    STATUS_OK,
    STATUS_POISON,
    STATUS_REJECTED,
    LaneHealth,
    ServeConfig,
    ServeRequest,
    ServeResult,
    poison_scan,
)
from gauss_tpu.serve.buckets import (  # noqa: F401
    DEFAULT_LADDER,
    bucket_for,
    pad_system,
    pow2_bucket,
    unpad_solution,
)
from gauss_tpu.serve.cache import (  # noqa: F401
    BatchedExecutable,
    CacheKey,
    CacheView,
    ExecutableCache,
    shared_cache,
)
from gauss_tpu.serve.lanes import (  # noqa: F401
    Lane,
    LaneSet,
    compat_sig,
)
from gauss_tpu.serve.durable import (  # noqa: F401
    JournalError,
    RequestJournal,
)
from gauss_tpu.serve.server import SolverServer  # noqa: F401
