"""Admission control: request/response types, deadlines, retry, lane health.

The serving layer degrades gracefully instead of falling over (ROADMAP north
star: heavy traffic). Three mechanisms, in the order a request meets them:

- **Queue-full rejection with retry-after.** The request queue is bounded;
  a submit against a full queue is rejected immediately with a retry-after
  hint derived from the recent drain rate, so clients back off instead of
  building an unbounded memory balloon inside the server.
- **Deadlines.** A request may carry a relative deadline; the worker drops
  expired requests at drain time — BEFORE padding, H2D, or compute — so a
  latency spike sheds exactly the work whose answer nobody is waiting for.
- **Retry + fallback lane.** A batch that fails with a transient device
  error is retried with exponential backoff; requests that exhaust retries
  fail individually. When the device lane fails persistently
  (``unhealthy_after`` consecutive batch failures) the server trips into a
  NumPy fallback lane (host LAPACK ``solve`` — slow but always available)
  and probes the device lane again after a cooldown, the classic
  circuit-breaker shape.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional

import numpy as np

# Request terminal states.
STATUS_OK = "ok"
STATUS_REJECTED = "rejected"      # queue full — never entered the queue
STATUS_EXPIRED = "expired"        # deadline passed before compute
STATUS_FAILED = "failed"          # lane error after retries
STATUS_CANCELLED = "cancelled"    # client gave up waiting; worker skips it
STATUS_POISON = "poison"          # the REQUEST is the fault: non-finite
#                                   operands, a singular system, or a
#                                   payload implicated in repeated worker
#                                   deaths — typed blame, never a 500


def poison_scan(a, b) -> Optional[str]:
    """Admission-time operand scan: the reason string when ``(a, b)`` can
    never be served (non-finite values, non-numeric dtype), else None.

    This is the STATUS_POISON front door — every operand path (submit, the
    wire decode, journal replay) runs it so a poisoned request is rejected
    with typed blame before it can reach a batch, a device, or a journal
    record that a restart would faithfully replay. Shape/conformability
    errors stay plain ValueError (programming errors, not poison); this
    scan owns the *values*. O(n²) reads, no allocation beyond the
    reduction.
    """
    for name, arr in (("a", a), ("b", b)):
        arr = np.asarray(arr)
        if not np.issubdtype(arr.dtype, np.number):
            return f"non-numeric {name} (dtype {arr.dtype})"
        if np.issubdtype(arr.dtype, np.complexfloating):
            return f"complex {name} unsupported"
        if not np.isfinite(arr).all():
            bad = "nan" if np.isnan(arr).any() else "inf"
            return f"non-finite operand {name} ({bad})"
    return None


@dataclasses.dataclass
class ServeConfig:
    """Tuning knobs for :class:`gauss_tpu.serve.server.SolverServer`."""

    ladder: tuple = ()              # () -> buckets.DEFAULT_LADDER
    max_batch: int = 8              # dynamic-batching ceiling per dispatch
    max_queue: int = 256            # admission bound (queue-full rejection)
    batch_linger_s: float = 0.0     # wait this long for same-bucket company
    cache_capacity: int = 32        # LRU executable-cache entries
    refine_steps: int = 1           # host-f64 refinement rounds per batch
    panel: Optional[int] = None     # blocked-solver panel (None -> auto)
    engine: str = "blocked"         # batched lane engine label (cache key)
    dtype: str = "float32"          # batched-lane storage dtype: "float32"
    #                                 (the pre-existing path), "bfloat16"
    #                                 (lowered MXU storage, f32-accumulate
    #                                 contract), or "bf16x3" (f32 storage,
    #                                 split-GEMM trailing updates). The
    #                                 choice keys the executable cache —
    #                                 CacheKey.dtype, so f32 and lowered
    #                                 executables can never alias — and a
    #                                 per-request dtype (submit(dtype=) /
    #                                 the loadgen "dtype:" token) overrides
    #                                 it per batch. Lowered lanes lean on
    #                                 refine_steps + verify_gate for the
    #                                 1e-4 contract (core.lowered)
    max_retries: int = 2            # transient-failure retries per batch
    retry_backoff_s: float = 0.05   # base backoff (doubles per attempt)
    unhealthy_after: int = 3        # consecutive failures that trip fallback
    device_probe_cooldown_s: float = 5.0  # how long fallback lane holds
    deadline_default_s: Optional[float] = None  # applied when request has none
    verify_gate: Optional[float] = None  # rel-residual bar; None = no check
    supervised_handoff: bool = False  # route oversized single-RHS solves
    #                                   through the fleet supervisor
    fleet_workers: int = 2          # world size for the supervised lane
    outofcore_handoff: bool = False  # route handoff requests whose working
    #                                  set exceeds the device budget
    #                                  through the host-streamed engine
    #                                  (gauss_tpu.outofcore) under the
    #                                  recovery ladder — the giant-request
    #                                  lane; only the active panel group +
    #                                  a bounded tile window are ever
    #                                  device-resident
    device_budget: Optional[int] = None  # device-byte budget consulted by
    #                                      the handoff routing (None = the
    #                                      runtime-reported
    #                                      device_memory_budget(); an
    #                                      explicit value caps what the
    #                                      batched/single-chip lanes may
    #                                      claim and is how tests force
    #                                      the out-of-core lane at smoke
    #                                      sizes)
    abft: bool = False              # checksum-carrying (ABFT) solves on the
    #                                 single-request lanes (handoff): silent
    #                                 data corruption is detected within one
    #                                 panel group and repaired by localized
    #                                 replay (gauss_tpu.resilience.abft);
    #                                 results that saw a detection carry
    #                                 sdc_detected=True. The batched bucket
    #                                 lane keeps its vmapped executables and
    #                                 relies on verify_gate (documented in
    #                                 docs/RESILIENCE.md)
    structure_aware: bool = False   # detect/accept structure tags, batch by
    #                                 (bucket, tag), and give Gershgorin-
    #                                 certified SPD batches the half-price
    #                                 Cholesky executable (see
    #                                 gauss_tpu.structure)
    # -- live telemetry plane (gauss_tpu.obs.live / export / slo) ----------
    live_port: Optional[int] = None  # serve /metrics etc. on this port
    #                                  (0 = ephemeral; None = plane off —
    #                                  the hot path pays nothing)
    live_host: str = "127.0.0.1"    # bind address for the live endpoint
    live_window: int = 1024         # rolling-window samples per series
    slos: tuple = ()                # obs.slo.SLO definitions; () with the
    #                                 live plane on -> the default serving
    #                                 SLO (99% of requests terminate ok)
    slo_shed: bool = False          # while an SLO alert FIRES, admit only
    #                                 up to max_queue * degraded_queue_
    #                                 factor — degradation starts before
    #                                 the deadline cliff, not at it
    degraded_queue_factor: float = 0.5  # admission bound scale under alert
    # -- durable admission (gauss_tpu.serve.durable) -----------------------
    journal_dir: Optional[str] = None  # write-ahead request journal: every
    #                                    admit/terminal is journaled (CRC'd
    #                                    JSONL segments) and a restart
    #                                    replays unterminated admits. None
    #                                    (default) = journal off — the serve
    #                                    path is byte-identical to pre-
    #                                    journal behavior (one is-None check
    #                                    at admission)
    journal_fsync_batch: int = 8    # fsync every N journal appends (group
    #                                 commit; shutdown marker + rotation
    #                                 always fsync)
    journal_rotate_records: int = 4096  # compact the live segment past this
    #                                     many records (tmp+fsync+rename)
    resume: bool = True             # with a journal: replay unterminated
    #                                 admits at start() (in-deadline ones
    #                                 re-solve, expired ones get a typed
    #                                 STATUS_EXPIRED terminal). False =
    #                                 journal new traffic only
    heartbeat_path: Optional[str] = None  # worker-loop liveness file for
    #                                       the supervisor (durable
    #                                       .supervise); None = off
    # -- flight recorder (gauss_tpu.obs.flight / obs.postmortem) -----------
    flight_dir: Optional[str] = None  # crash-surviving telemetry: install
    #                                   the obs flight sink over an mmap
    #                                   ring in this dir (recent events
    #                                   survive kill -9, harvested into
    #                                   post-mortem bundles under
    #                                   <flight_dir>/bundles) and arm the
    #                                   in-process capture triggers (SLO
    #                                   firing, SDC escalation, unclean
    #                                   resume). None (default) = recorder
    #                                   off — the serve path is byte-
    #                                   identical pre-flight behavior (one
    #                                   is-None read per obs hook)
    flight_ring_bytes: int = 1 << 20  # flight ring capacity in bytes
    #                                   (fixed-size; oldest records are
    #                                   overwritten — the ring holds the
    #                                   final seconds, not the history)
    # -- device-time attribution (gauss_tpu.obs.attr / obs.prof) -----------
    attr: Optional[bool] = None     # device-time attribution plane: install
    #                                 a process AttributionMatrix at start()
    #                                 — every dispatched executable is timed
    #                                 at device-completion granularity into
    #                                 the (phase, executable, lane) matrix,
    #                                 joined with compile-time FLOP/byte
    #                                 budgets into roofline ``util.*``
    #                                 gauges, and each request accumulates
    #                                 device-seconds / amortized compile-
    #                                 seconds (ServeResult.device_s /
    #                                 .compile_s; per-compat-sig capacity
    #                                 model on /snapshot). None (default) =
    #                                 plane off — the serve path and its
    #                                 traces are byte-identical to the
    #                                 pre-attribution behavior (one is-None
    #                                 read per dispatch)
    # -- mesh serving (gauss_tpu.serve.lanes) ------------------------------
    lanes: int = 0                  # dispatch lanes across the device mesh:
    #                                 0 (default) = the single-queue/
    #                                 single-worker server, byte-identical
    #                                 to the pre-mesh path; N > 0 = a
    #                                 LaneSet of N async dispatch lanes,
    #                                 each pinned to its own device (or
    #                                 mesh slice), with key-affinity
    #                                 placement, work stealing, and
    #                                 continuous batching
    lane_width: int = 1             # devices per lane (a mesh SLICE): 1 =
    #                                 one device per lane; >1 device_puts
    #                                 the batched operand stacks with a
    #                                 NamedSharding over the slice's
    #                                 "batch" axis, so GSPMD runs one
    #                                 bucket executable data-parallel
    #                                 across the slice (oversized buckets'
    #                                 escape hatch; a batch not divisible
    #                                 by the width falls back to the
    #                                 slice's first device)
    continuous_batching: bool = True  # lanes only: admission places a
    #                                   compatible request (same bucket /
    #                                   dtype / structure — the CacheKey
    #                                   batch identity) into the lane's
    #                                   open IN-FLIGHT forming slot
    #                                   instead of the queue, and the next
    #                                   slot forms WHILE the previous
    #                                   batch computes. False = per-lane
    #                                   fixed drain cycles (the
    #                                   single-lane discipline, lingering
    #                                   batch_linger_s per batch)
    cb_window_s: float = 0.005      # batch-formation deadline: an
    #                                 unfilled forming slot dispatches
    #                                 this long after it opened — the
    #                                 bound on latency-for-occupancy;
    #                                 under load slots fill before it
    #                                 fires and the deadline costs nothing
    cb_deadline_margin_s: float = 0.01  # continuous batching is DEADLINE-
    #                                 AWARE: a forming slot also closes
    #                                 this margin before its earliest
    #                                 member's request deadline, so
    #                                 formation never lingers a member
    #                                 into expiry (the fixed drain cycle
    #                                 lingers blind — the A/B the
    #                                 mesh-serve-check gate measures)
    lane_warmup: bool = True        # lanes only: each lane warms its own
    #                                 device's executable for every ladder
    #                                 rung at startup (one backend compile
    #                                 per (lane, rung) — jax compiles per
    #                                 placement), so compiles land before
    #                                 serving, not inside a request's
    #                                 latency. False = lazy (tests)
    steal_threshold: int = 2        # work stealing: an idle lane steals
    #                                 from the deepest sibling queue once
    #                                 it holds at least this many requests
    #                                 (1 would steal work the owner is
    #                                 about to form into a batch)
    autoscale: bool = False         # lanes + live plane: grow the active
    #                                 lane count while an SLO burn-rate
    #                                 alert FIRES (add capacity, don't
    #                                 just shed) and shrink it back to
    #                                 min_lanes after a quiet period;
    #                                 placement targets active lanes only
    #                                 and dormant lanes' leftovers are
    #                                 stolen by active ones
    min_lanes: int = 1              # autoscale floor (and starting count)
    autoscale_interval_s: float = 0.25  # min seconds between scale steps
    autoscale_quiet_s: float = 2.0  # alert-free seconds before a shrink
    # -- poison isolation (admission scan / bisection / quarantine) --------
    poison_scan: bool = True        # scan every operand path (submit, wire
    #                                 decode, journal replay) for
    #                                 non-finite/non-numeric operands and
    #                                 reject with a typed STATUS_POISON
    #                                 terminal BEFORE the journal admit —
    #                                 a poisoned submit can never enter a
    #                                 batch, crash a worker, or leave a
    #                                 journal record a restart would
    #                                 replay. False = the pre-poison
    #                                 trusting path (tests)
    bisect_batches: bool = True     # when a batched dispatch fails
    #                                 NON-transiently, bisect the batch
    #                                 (O(log B) re-dispatches) to isolate
    #                                 the culprit member(s): innocents
    #                                 re-serve under their original
    #                                 journal/trace ids, culprits get a
    #                                 typed STATUS_POISON terminal. False
    #                                 = the whole batch fails together
    #                                 (the pre-bisection behavior)
    quarantine_deaths: int = 2      # journaled replay quarantines any rid
    #                                 whose blame records implicate it in
    #                                 at least this many DISTINCT prior
    #                                 process deaths: solo-executed on the
    #                                 host recovery ladder (finite
    #                                 operands) or typed-rejected
    #                                 (poisoned operands), never
    #                                 re-batched — replay cannot
    #                                 re-trigger the crash. 0 = quarantine
    #                                 off


@dataclasses.dataclass
class ServeResult:
    """What a completed (or refused) request resolves to."""

    status: str
    x: Optional[np.ndarray] = None
    lane: Optional[str] = None       # "batched" | "handoff" | "numpy"
    bucket_n: Optional[int] = None
    #: the request's end-to-end trace id, stamped at resolve so EVERY
    #: client-visible outcome — including synchronous admission rejects —
    #: can be joined against the obs stream (the loadgen-visible half of
    #: request tracing; the terminal obs events have carried it since PR 8).
    trace: Optional[str] = None
    latency_s: Optional[float] = None
    queue_s: Optional[float] = None
    retry_after_s: Optional[float] = None
    error: Optional[str] = None
    rel_residual: Optional[float] = None
    #: True when an ABFT-protected lane detected (and repaired) silent
    #: data corruption while serving this request — the per-request SDC
    #: status tag (ServeConfig.abft).
    sdc_detected: bool = False
    #: per-request cost accounting (ServeConfig.attr): the device-seconds
    #: this request consumed (its share of every batch solve it rode,
    #: summed across retries/steals) and the amortized compile/cache-get
    #: seconds paid on its behalf. None when the attribution plane is off
    #: — results are then byte-identical to the pre-attribution shape.
    device_s: Optional[float] = None
    compile_s: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class ServeRequest:
    """One queued solve: operands, deadline, and a completion latch."""

    _ids = iter(range(1, 1 << 62))
    _ids_lock = threading.Lock()

    def __init__(self, a: np.ndarray, b: np.ndarray,
                 deadline_s: Optional[float] = None,
                 structure: Optional[str] = None,
                 dtype: Optional[str] = None,
                 request_id: Optional[str] = None):
        from gauss_tpu.obs import requesttrace

        with ServeRequest._ids_lock:
            self.id = next(ServeRequest._ids)
        #: end-to-end trace identity, minted at admission and carried by
        #: every event this request touches (obs.requesttrace folds the
        #: stream back into one span tree per request).
        self.trace_id = requesttrace.mint()
        #: client-supplied idempotency key (durable serving): journaled
        #: with the admit/terminal records so a resubmission after a crash
        #: dedupes against the journal instead of re-solving. None = the
        #: request has no cross-restart identity.
        self.request_id = request_id
        #: journal identity — the id the durable layer pairs admit/terminal
        #: records under. Defaults to this request's id; RECOVERY replays
        #: set it to the original (journaled) id so the replayed terminal
        #: pairs with the original admit.
        self.journal_id = self.id
        #: terminal hook: the durable layer installs its journal append
        #: here at admission; resolve() calls it EXACTLY when the CAS is
        #: won, so journal terminals inherit the one-terminal guarantee.
        #: None (no journal) costs one is-None check.
        self._on_terminal = None
        self.a = np.asarray(a)
        self.b = np.asarray(b)
        #: structure routing tag ("spd" / "banded" / "blockdiag" / "dense"),
        #: None when the server is not structure-aware. Part of the batch
        #: compatibility key AND the executable cache key: identity-
        #: extension bucket padding preserves SPD and bandwidth (tested in
        #: tests/test_structure.py), so a tag survives padding.
        self.structure = structure
        #: batched-lane storage dtype ("float32" / "bfloat16" / "bf16x3");
        #: None defers to the server's ServeConfig.dtype at submit. Part
        #: of the batch compatibility key AND the executable cache key —
        #: a bf16 batch and an f32 batch can never share an executable.
        self.dtype = dtype
        self.n = self.a.shape[0]
        if self.a.shape != (self.n, self.n):
            raise ValueError(f"expected square matrix, got {self.a.shape}")
        if self.b.shape[:1] != (self.n,) or self.b.ndim > 2:
            raise ValueError(
                f"b must be (n,) or (n, k) with n={self.n}, got {self.b.shape}")
        self.was_vector = self.b.ndim == 1
        self.k = 1 if self.was_vector else self.b.shape[1]
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s is not None else None)
        #: wall-clock deadline (the journalable form: perf_counter has no
        #: meaning across a process restart)
        self.deadline_unix = (time.time() + deadline_s
                              if deadline_s is not None else None)
        self._done = threading.Event()
        self._resolve_lock = threading.Lock()
        self._result: Optional[ServeResult] = None  # guarded by: self._resolve_lock
        #: cost accumulators (ServeConfig.attr): device-seconds and
        #: amortized compile-seconds, summed across every batch/steal this
        #: request rides. Written only by the worker currently dispatching
        #: the request (a request is in exactly one batch at a time — lane
        #: handoff moves the whole object), read at _finish.
        self.cost_device_s = 0.0  # lockset: ok — owned by the dispatching worker
        self.cost_compile_s = 0.0  # lockset: ok — owned by the dispatching worker
        #: poison quarantine flag (blame-journal replay, adopt import): a
        #: quarantined request is solo-executed on the host recovery
        #: ladder — never co-batched, never the device lane. Set before
        #: the request is visible to any worker (replay/adopt), read by
        #: the dispatch path.
        self.quarantine = False  # lockset: ok — set before queue insertion, read-only after

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline

    def resolve(self, result: ServeResult) -> bool:
        """Set the terminal result. FIRST resolve wins (a compare-and-set
        under a lock): the worker finishing and the client cancelling can
        race, and exactly one of them may own the terminal status — the
        same exactly-one-terminal guarantee stop() gives the shutdown race.
        Returns True when this call won; callers emit their terminal obs
        event only then, so the stream carries one terminal per request
        too."""
        with self._resolve_lock:
            if self._result is not None:
                return False
            result.latency_s = time.perf_counter() - self.t_submit
            result.trace = self.trace_id
            hook = self._on_terminal
            if hook is not None:
                # The durable layer's terminal append — BEFORE the done
                # event: a client must never observe a terminal the
                # journal doesn't hold yet (a fast keyed resubmission
                # would miss the dedupe map and re-solve). Runs only on
                # the WINNING resolve, so the journal carries exactly one
                # terminal per request; the hook never raises (journal
                # failures are counted, not propagated). The lock is
                # per-request — the append cost blocks only this
                # request's waiters.
                hook(self, result)
            self._result = result
            self._done.set()
            return True

    def cancel(self, error: str = "cancelled by client") -> bool:
        """Resolve as cancelled (if still pending). The worker observes
        ``done`` at drain/dispatch time and skips the request — a client
        that stopped waiting no longer costs padding, H2D, or compute.
        Returns True when the cancellation won the race."""
        won = self.resolve(ServeResult(status=STATUS_CANCELLED, error=error))
        if won:
            from gauss_tpu import obs

            obs.counter("serve.cancelled")
            obs.emit("serve_request", id=self.id, n=self.n,
                     trace=self.trace_id, status=STATUS_CANCELLED,
                     reason=error)
        return won

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        """Block until the request resolves (the client-side wait).

        A timeout CANCELS the request before raising: the abandoned entry
        is skipped by the worker instead of being served into the void
        (and, before this, silently orphaned in the queue). If the worker
        resolves in the race window the real result is returned instead —
        either way the request ends with exactly one terminal status."""
        if not self._done.wait(timeout):
            if self.cancel(error="client stopped waiting "
                                 f"(result timeout {timeout} s)"):
                raise TimeoutError(
                    f"request {self.id} timed out after {timeout} s and "
                    f"was cancelled")
        return self._result  # lockset: ok — read after the done event; _done.set() under the lock is the happens-before edge

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request resolves WITHOUT cancelling on timeout.

        The server-side wait: the network tier parks an HTTP handler here
        while the client may retry/poll on other connections — a timeout
        means "respond 202 and keep serving", not "the client gave up", so
        cancelling (what :meth:`result` does) would be wrong. Returns True
        when the request holds a terminal."""
        return self._done.wait(timeout)

    def peek(self) -> Optional[ServeResult]:
        """The terminal result if resolved, else None (never blocks)."""
        if not self._done.is_set():
            return None
        return self._result  # lockset: ok — read after the done event; _done.set() under the lock is the happens-before edge

    @property
    def done(self) -> bool:
        return self._done.is_set()


class LaneHealth:
    """Circuit breaker for the device lane (thread-safe).

    Healthy until ``unhealthy_after`` CONSECUTIVE batch failures; then the
    device lane is held open (fallback serves) for ``cooldown_s``, after
    which ONE probe batch is allowed through — success closes the circuit,
    failure re-opens it for another cooldown.
    """

    def __init__(self, unhealthy_after: int, cooldown_s: float):
        self.unhealthy_after = max(1, int(unhealthy_after))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._consecutive = 0           # guarded by: self._lock
        self._open_until: Optional[float] = None  # guarded by: self._lock

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._open_until = None

    def record_failure(self) -> bool:
        """Count one batch failure; returns True when this trips the lane."""
        with self._lock:
            self._consecutive += 1
            tripped = (self._consecutive >= self.unhealthy_after
                       and self._open_until is None)
            if self._consecutive >= self.unhealthy_after:
                self._open_until = time.perf_counter() + self.cooldown_s
            return tripped

    def device_allowed(self) -> bool:
        """May the next batch try the device lane? (True once per cooldown
        expiry — the probe; steady-state True when healthy.)"""
        with self._lock:
            if self._open_until is None:
                return True
            if time.perf_counter() >= self._open_until:
                # Let one probe through; a failure re-opens via record_failure.
                self._open_until = None
                self._consecutive = self.unhealthy_after - 1
                return True
            return False

    @property
    def open(self) -> bool:
        with self._lock:
            return (self._open_until is not None
                    and time.perf_counter() < self._open_until)


def retry_backoff(base_s: float, attempt: int) -> float:
    """Exponential backoff delay for retry ``attempt`` (0-based)."""
    return base_s * (2 ** attempt)


def is_transient_device_error(e: BaseException) -> bool:
    """Heuristic for retryable device failures vs programming errors.

    Shape/value errors are deterministic — retrying replays the bug — while
    runtime/device errors (XlaRuntimeError, RESOURCE_EXHAUSTED, tunnel
    hiccups) are worth a bounded retry and count against lane health.
    """
    if isinstance(e, (ValueError, TypeError)):
        return False
    return True
