"""The poison-the-server chaos campaign: ``python -m gauss_tpu.serve.poisoncheck``.

Asserts the poison-isolation invariant the admission scan
(gauss_tpu.serve.admission.poison_scan), batch bisection
(SolverServer._serve_batched), and the blame-journal quarantine
(gauss_tpu.serve.durable blame records + ServeConfig.quarantine_deaths)
exist to provide:

    **a hostile request can cost the service AT MOST its own answer —
    every poison operand (non-finite entries, exactly-singular systems,
    torn wire payloads, kill-on-dispatch pills) draws EXACTLY ONE typed
    ``poison`` terminal, every innocent co-batched next to it is served
    and verified at the gate, no worker dies for it twice, and a
    journaled poison admit can never turn a restart into a crash loop.**

Phases:

- **isolation cases** (``--cases``, in-process, cycled over kinds):
  seeded poison-next-to-innocents scenarios against a live journaled
  :class:`SolverServer` — ``nan``/``inf`` (non-finite operands must be
  typed-rejected at admission, BEFORE the journal admit: a poison the
  journal never saw cannot crash-loop a replay), ``singular`` (an
  exactly-singular system admits finitely, fails the batched verify, and
  must surface the host ladder's :class:`SingularSystemError` verdict as
  a typed poison terminal — never a generic failure, never a worker
  death), ``bisect`` (a batch member that makes the whole batched
  dispatch raise: bisection must isolate it in O(log B) re-dispatches,
  re-serve every innocent under its ORIGINAL journal id and deadline,
  and type only the hunted singleton). Every case ends with a raw-line
  journal audit: one terminal per admitted rid, poison rids typed,
  nan/inf rids absent (rejected pre-admit).
- **mesh leg**: the same nan/singular mix through ``lanes=2`` dispatch
  lanes — per-lane dispatch must reach the same typed verdicts.
- **replica leg**: a real 3-replica router under concurrent network
  load with nan/singular poison in the mix (typed ``poison`` results
  ride the 400 lane back through the router proxy), plus torn WIRE
  payloads (truncated JSON, truncated base64 operand) that must be 400s
  — and after all of it, zero replica restarts: poison never kills a
  worker.
- **crash-loop leg** (subprocess): a kill-on-dispatch pill — a healthy
  admit whose dispatch tears the journal mid-append and dies
  (``journal_torn_write``) — re-armed for FOUR incarnations. Blame
  records (one distinct boot per death) must quarantine the rid at
  ``quarantine_deaths`` deaths: incarnations 1-2 die, incarnation 3
  replays the rid SOLO on the host ladder and survives with the fault
  still armed, incarnation 4 replays nothing. Three restarts, one ``ok``
  terminal, loop broken.
- **supervised leg**: the same pill under
  :func:`gauss_tpu.serve.durable.supervise` with ``max_restarts=0`` —
  the quarantined death must respawn WITHOUT charging the restart
  budget (a budget of zero only survives if the charge never lands).

The summary is regress-ingestable (``kind: poison_campaign``). Exit 2
when the invariant is violated (innocent casualty, unverified serve,
untyped culprit, duplicate terminal, crash loop, charged quarantine),
1 when ``--regress-check`` finds an out-of-band metric, 0 otherwise.
``make poison-check`` runs the CI configuration; like the other
timing-gated gates it must not run concurrently with them (Makefile
serial-ordering note).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

POISON_KINDS = ("nan", "inf", "singular", "bisect")

#: finite sentinel the ``bisect`` kind plants at a[0,0]: invisible to the
#: admission scan (finite), fatal to the tripwired executable below — the
#: stand-in for "this member makes the batched dispatch raise".
SENTINEL = 777.0

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fresh_dir(path: str) -> str:
    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    return path


def _system(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def poison_system(rng: np.random.Generator, n: int, kind: str):
    """A seeded system carrying one poison of ``kind``. ``singular`` zeroes
    a full row (b kept nonzero — inconsistent, rank-deficient): LAPACK
    reports it exactly and the batched LU cannot produce a finite
    accidental answer for it."""
    a, b = _system(rng, n)
    if kind == "nan":
        a[0, 0] = np.nan
    elif kind == "inf":
        a[0, 0] = np.inf
    elif kind == "singular":
        a[n // 2, :] = 0.0
        b[n // 2] = 1.0
    elif kind == "bisect":
        a[0, 0] = SENTINEL
    else:  # pragma: no cover — campaign-internal
        raise ValueError(f"unknown poison kind {kind!r}")
    return a, b


def _case_config(journal_dir: Optional[str], gate: float, **over):
    from gauss_tpu.serve.admission import ServeConfig

    kw = dict(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
              verify_gate=gate, journal_dir=journal_dir,
              journal_fsync_batch=4, max_queue=256)
    kw.update(over)
    return ServeConfig(**kw)


class _TrippingExecutable:
    """Delegates to the real batched executable unless the padded operand
    stack contains the SENTINEL pill — then raises the deterministic
    (non-transient) error batch bisection exists to localize."""

    def __init__(self, exe):
        self._exe = exe

    def solve(self, a_pad, b_pad, placement=None):
        if np.any(a_pad[:, 0, 0] == SENTINEL):
            raise ValueError("sentinel poison member in batch")
        return self._exe.solve(a_pad, b_pad, placement=placement)

    def __getattr__(self, name):
        return getattr(self._exe, name)


class _TrippingCache:
    """ExecutableCache wrapper returning tripwired executables (shared
    inner cache: the campaign measures isolation, not XLA compiles)."""

    def __init__(self, inner):
        self._inner = inner

    def get(self, key, builder=None, panel=None):
        return _TrippingExecutable(
            self._inner.get(key, builder=builder, panel=panel))

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _residual_ok(a, x, b, gate: float) -> bool:
    from gauss_tpu.verify import checks

    rel = checks.residual_norm(a, x, b, relative=True)
    return bool(np.isfinite(rel) and rel <= gate)


def journal_records(journal_dirs: List[str]
                    ) -> Tuple[Dict[str, Dict], Dict[str, List[Dict]]]:
    """(admits_by_rid, terminals_by_rid) from RAW segment lines across a
    set of journal dirs — raw so duplicate terminals (the violation the
    scanner's keyed state would hide) stay visible."""
    from gauss_tpu.serve import durable

    admits: Dict[str, Dict] = {}
    terms: Dict[str, List[Dict]] = {}
    for jd in journal_dirs:
        if not jd or not os.path.isdir(jd):
            continue
        for seg in durable.segment_paths(jd):
            with open(seg, "rb") as f:
                for line in f.read().split(b"\n"):
                    if not line:
                        continue
                    doc = durable.decode_line(line + b"\n")
                    if doc is None:
                        continue
                    rid = doc.get("rid")
                    if not rid:
                        continue
                    if doc.get("rec") == "admit":
                        admits.setdefault(rid, doc)
                    elif doc.get("rec") == "terminal":
                        terms.setdefault(rid, []).append(doc)
    return admits, terms


def check_verdicts(journal_dirs: List[str], innocents, culprits,
                   results: Dict[str, Any], gate: float) -> List[str]:
    """The per-case invariant, judged from the client results AND the raw
    journal: every innocent ok + verified + exactly one ok terminal;
    every culprit exactly one typed poison terminal (nan/inf culprits are
    rejected BEFORE the admit — they must be absent from the journal
    entirely)."""
    admits, terms = journal_records(journal_dirs)
    bad: List[str] = []
    for rid, (a, b) in innocents.items():
        res = results.get(rid)
        if res is None or res.status != "ok":
            bad.append(f"innocent {rid}: status="
                       f"{getattr(res, 'status', None)} "
                       f"error={getattr(res, 'error', None)}")
            continue
        if res.x is None or not _residual_ok(a, res.x, b, gate):
            bad.append(f"innocent {rid}: unverified at {gate}")
        n_terms = len(terms.get(rid, ()))
        if rid in admits and n_terms != 1:
            bad.append(f"innocent {rid}: {n_terms} journal terminals")
    for rid, kind in culprits.items():
        res = results.get(rid)
        if res is None or res.status != "poison" or not res.error:
            bad.append(f"culprit {rid} [{kind}]: status="
                       f"{getattr(res, 'status', None)} "
                       f"error={getattr(res, 'error', None)}")
            continue
        if kind in ("nan", "inf"):
            # Scan precedes the journal admit: a non-finite poison must
            # leave NO journal record — nothing for a replay to chew on.
            if rid in admits or rid in terms:
                bad.append(f"culprit {rid} [{kind}]: journaled pre-scan")
        else:
            tl = terms.get(rid, ())
            if len(tl) != 1 or tl[0].get("status") != "poison":
                bad.append(f"culprit {rid} [{kind}]: terminals="
                           f"{[t.get('status') for t in tl]}")
    return bad


# -- in-process isolation cases --------------------------------------------

def run_case(i: int, seed: int, gate: float, tmpdir: str, kind: str,
             cache=None) -> Dict:
    """One poison-next-to-innocents case; returns its outcome record."""
    from gauss_tpu.serve.server import SolverServer

    rng = np.random.default_rng(np.random.SeedSequence((seed, i, 0xB15)))
    jd = os.path.join(_fresh_dir(os.path.join(
        tmpdir, f"case-{kind}-{i:03d}")), "journal")
    out: Dict = {"case": i, "kind": kind}
    over: Dict[str, Any] = {}
    if kind == "bisect":
        # The pill only meets its batch-mates if they form ONE batch: hold
        # the dispatch long enough for all four submits to co-batch.
        over["batch_linger_s"] = 0.25
    innocents: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    culprits: Dict[str, str] = {}
    results: Dict[str, Any] = {}
    srv = SolverServer(_case_config(jd, gate, **over), cache=cache)
    srv.start()
    try:
        n_inno = 3 if kind == "bisect" else 4 + int(rng.integers(0, 3))
        handles = []
        plan: List[Tuple[str, str]] = [("innocent", f"i{j}")
                                       for j in range(n_inno)]
        plan.insert(int(rng.integers(0, n_inno + 1)), (kind, "pill"))
        for tag, label in plan:
            n = int(rng.integers(8, 29))
            rid = f"p{seed}-{i}-{label}"
            if tag == "innocent":
                a, b = _system(rng, n)
                innocents[rid] = (a, b)
            else:
                a, b = poison_system(rng, n, kind)
                culprits[rid] = kind
            handles.append((rid, srv.submit(a, b, request_id=rid)))
        for rid, h in handles:
            results[rid] = h.result(timeout=120.0)
    finally:
        srv.stop(drain=True, timeout=120.0)
    bad = check_verdicts([jd], innocents, culprits, results, gate)
    out["requests"] = len(results)
    out["innocents"] = len(innocents)
    out["outcome"] = "violation" if bad else "ok"
    if bad:
        out["error"] = "; ".join(bad[:4])
    return out


# -- mesh-lane leg ---------------------------------------------------------

def run_mesh_leg(seed: int, gate: float, tmpdir: str, cache=None) -> Dict:
    """nan + singular poison through ``lanes=2`` mesh dispatch lanes:
    per-lane admission placement and per-lane dispatch must reach the
    same typed verdicts with every lane-mate verified."""
    from gauss_tpu.serve.server import SolverServer

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x1A2E)))
    jd = os.path.join(_fresh_dir(os.path.join(tmpdir, "leg-mesh")),
                      "journal")
    leg: Dict = {"leg": "mesh"}
    innocents: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    culprits: Dict[str, str] = {}
    results: Dict[str, Any] = {}
    t0 = time.perf_counter()
    srv = SolverServer(_case_config(jd, gate, lanes=2), cache=cache)
    srv.start()
    try:
        handles = []
        for j in range(8):
            a, b = _system(rng, int(rng.integers(8, 29)))
            rid = f"m{seed}-i{j}"
            innocents[rid] = (a, b)
            handles.append((rid, srv.submit(a, b, request_id=rid)))
        for kind in ("nan", "singular"):
            a, b = poison_system(rng, int(rng.integers(8, 29)), kind)
            rid = f"m{seed}-{kind}"
            culprits[rid] = kind
            handles.append((rid, srv.submit(a, b, request_id=rid)))
        for rid, h in handles:
            results[rid] = h.result(timeout=120.0)
    finally:
        srv.stop(drain=True, timeout=120.0)
    bad = check_verdicts([jd], innocents, culprits, results, gate)
    leg["requests"] = len(results)
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    leg["outcome"] = "violation" if bad else "ok"
    if bad:
        leg["error"] = "; ".join(bad[:4])
    return leg


# -- replica leg -----------------------------------------------------------

def _router_config(root: str, replicas: int, gate: float, **over):
    from gauss_tpu.serve.router import RouterConfig

    kw = dict(replicas=replicas, dir=root, ladder=(32,), max_batch=4,
              verify_gate=gate, max_restarts=3, poll_s=0.1,
              stall_after_s=30.0)
    kw.update(over)
    return RouterConfig(**kw)


def _net_load(client, mats, rids: List[str]) -> Dict[str, Any]:
    results: Dict[str, Any] = {}
    lock = threading.Lock()

    def _one(idx: int) -> None:
        a, b = mats[idx]
        res = client.solve(a, b, deadline_s=120.0, request_id=rids[idx])
        with lock:
            results[rids[idx]] = res

    threads = [threading.Thread(target=_one, args=(i,))
               for i in range(len(rids))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    return results


def _raw_post(url: str, body: bytes) -> int:
    """POST raw bytes, returning the HTTP status (4xx/5xx included)."""
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def run_replica_leg(seed: int, gate: float, tmpdir: str, log=print) -> Dict:
    """3 replicas behind the router under concurrent load with poison in
    the mix: typed ``poison`` results ride the 400 lane back through the
    proxy, torn WIRE payloads are 400s, and — the point — zero replica
    deaths and zero restart-budget spend for any of it."""
    import glob

    from gauss_tpu.serve import durable
    from gauss_tpu.serve.net import SolveClient
    from gauss_tpu.serve.router import Router

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x4E7)))
    root = _fresh_dir(os.path.join(tmpdir, "leg-replica"))
    leg: Dict = {"leg": "replica"}
    innocents: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    culprits: Dict[str, str] = {}
    mats, rids = [], []
    for j in range(9):
        a, b = _system(rng, int(rng.integers(8, 29)))
        rid = f"r{seed}-i{j}"
        innocents[rid] = (a, b)
        mats.append((a, b))
        rids.append(rid)
    for kind in ("nan", "singular"):
        a, b = poison_system(rng, int(rng.integers(8, 29)), kind)
        rid = f"r{seed}-{kind}"
        culprits[rid] = kind
        mats.append((a, b))
        rids.append(rid)
    t0 = time.perf_counter()
    with Router(_router_config(root, 3, gate)) as router:
        client = SolveClient(router.url, timeout_s=180.0, wait_s=5.0,
                             seed=seed)
        results = _net_load(client, mats, rids)
        # Torn wire payloads against the proxied solve path: a truncated
        # JSON body and a truncated base64 operand — both must be typed
        # 400s, neither may cost a worker.
        a, b = _system(rng, 12)
        doc = {"schema": 1, "a": durable.encode_array(a),
               "b": durable.encode_array(b), "request_id": f"r{seed}-torn"}
        whole = json.dumps(doc).encode()
        leg["torn_json_http"] = _raw_post(router.url + "/v1/solve",
                                          whole[:len(whole) // 2])
        doc["a"]["b64"] = doc["a"]["b64"][:-3]
        leg["torn_b64_http"] = _raw_post(router.url + "/v1/solve",
                                         json.dumps(doc).encode())
        stats = router.stats()
        live = router.live_replicas()
        leg["replicas_live"] = sum(1 for rp in live.values()
                                   if rp.proc.poll() is None)
        leg["restarts_used"] = stats["restarts_used"]
        jdirs = []
        for rdir in router.replica_dirs():
            jdirs.extend(sorted(glob.glob(os.path.join(rdir, "journal*"))))
        router.stop(drain=True)
    bad = check_verdicts(jdirs, innocents, culprits, results, gate)
    if leg["torn_json_http"] != 400:
        bad.append(f"torn JSON body -> {leg['torn_json_http']}, want 400")
    if leg["torn_b64_http"] != 400:
        bad.append(f"torn base64 operand -> {leg['torn_b64_http']}, "
                   f"want 400")
    if leg["restarts_used"] != 0:
        bad.append(f"poison load spent {leg['restarts_used']} restart(s)")
    if leg["replicas_live"] != 3:
        bad.append(f"only {leg['replicas_live']}/3 replicas alive")
    leg["requests"] = len(results)
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    leg["outcome"] = "violation" if bad else "ok"
    if bad:
        leg["error"] = "; ".join(bad[:4])
    return leg


# -- crash-loop + supervised legs (subprocess) -----------------------------

def _drive_argv(journal: str, requests: int, seed: int, gate: float,
                k_deaths: int) -> List[str]:
    return [sys.executable, "-m", "gauss_tpu.serve.poisoncheck", "--drive",
            "--journal", journal, "--requests", str(requests),
            "--seed", str(seed), "--gate", str(gate),
            "--k-deaths", str(k_deaths)]


def _torn_fault(skip: int) -> str:
    return (f"serve.journal.append=journal_torn_write:skip={skip}"
            f":param=0.6")


def _env_base() -> Dict[str, str]:
    env = {k: v for k, v in os.environ.items() if k != "GAUSS_FAULTS"}
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _audit_pill(jd: str, rid: str, gate: float) -> List[str]:
    """The pill is a HEALTHY request implicated only by crashes: across
    every incarnation it must hold exactly one ``ok`` terminal, verified
    from the journaled operands."""
    from gauss_tpu.serve import durable

    admits, terms = journal_records([jd])
    tl = terms.get(rid, ())
    if len(tl) != 1 or tl[0].get("status") != "ok":
        return [f"pill {rid}: terminals="
                f"{[t.get('status') for t in tl]}, want one ok"]
    adm = admits.get(rid)
    if adm is None or tl[0].get("x") is None:
        return [f"pill {rid}: missing admit or solution"]
    a = durable.decode_array(adm["a"])
    b = durable.decode_array(adm["b"])
    if adm.get("was_vector"):
        b = b.reshape(-1)
    x = durable.decode_array(tl[0]["x"])
    if not _residual_ok(a, x, b, gate):
        return [f"pill {rid}: unverified at {gate}"]
    return []


def run_crashloop_leg(seed: int, gate: float, tmpdir: str,
                      log=print) -> Dict:
    """The kill-on-dispatch pill, fault RE-ARMED every incarnation (the
    adversarial case supervise's fault-stripping cannot reach): with
    ``quarantine_deaths=2``, incarnations 1-2 tear the terminal append
    and die (one blame boot each), incarnation 3 must quarantine the rid
    — solo host-ladder execution clears it WITH THE FAULT STILL ARMED —
    and incarnation 4 must replay nothing. The loop is broken by
    evidence, not by luck."""
    from gauss_tpu.resilience.inject import KILL_EXIT_CODE

    jd = os.path.join(_fresh_dir(os.path.join(tmpdir, "leg-crashloop")),
                      "journal")
    leg: Dict = {"leg": "crashloop"}
    rid = f"q{seed}-0"
    env_base = _env_base()
    t0 = time.perf_counter()
    incs: List[Dict] = []
    # skip counts journal appends before the tear fires. Incarnation 1
    # appends admit, blame, terminal -> skip=2 tears the terminal;
    # incarnation 2 replays (no new admit): blame, terminal -> skip=1
    # tears the terminal again; incarnations 3-4 run under skip=3 with
    # fewer than four appends — armed, never reached.
    for idx, skip in enumerate((2, 1, 3, 3)):
        env = dict(env_base)
        env["GAUSS_FAULTS"] = _torn_fault(skip)
        p = subprocess.run(_drive_argv(jd, 1, seed, gate, k_deaths=2),
                           env=env, cwd=_REPO, timeout=300,
                           capture_output=True, text=True)
        inc: Dict = {"rc": p.returncode, "skip": skip}
        for line in p.stdout.splitlines():
            if line.startswith("DRIVE:"):
                inc["drive"] = json.loads(line[6:])
        if p.returncode not in (0, KILL_EXIT_CODE):
            inc["stderr"] = p.stderr[-1500:]
        incs.append(inc)
        log(f"  crashloop: incarnation {idx + 1} (skip={skip}) "
            f"rc={p.returncode}")
    leg["incarnations"] = incs
    bad: List[str] = []
    want_rcs = [KILL_EXIT_CODE, KILL_EXIT_CODE, 0, 0]
    got_rcs = [inc["rc"] for inc in incs]
    if got_rcs != want_rcs:
        bad.append(f"incarnation rcs {got_rcs}, want {want_rcs}")
    else:
        d3 = incs[2].get("drive") or {}
        if (d3.get("resume") or {}).get("quarantined") != 1:
            bad.append(f"incarnation 3 did not quarantine the pill: "
                       f"resume={d3.get('resume')}")
        if (d3.get("statuses") or {}).get(rid) != "ok":
            bad.append(f"quarantined solo replay: statuses="
                       f"{d3.get('statuses')}, want {rid} ok")
        d4 = incs[3].get("drive") or {}
        if (d4.get("resume") or {}).get("replayed", 0) != 0 \
                or d4.get("solved_fresh", 0) != 0:
            bad.append(f"incarnation 4 not idempotent: {d4}")
    bad += _audit_pill(jd, rid, gate)
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    leg["outcome"] = "violation" if bad else "ok"
    if bad:
        leg["error"] = "; ".join(bad[:4])
    return leg


def run_supervised_leg(seed: int, gate: float, tmpdir: str,
                       log=print) -> Dict:
    """The pill under the durable supervisor with a restart budget of
    ZERO: the torn-dispatch death leaves fresh blame evidence, so the
    respawn must be quarantined (uncharged) — a charged death would make
    supervise give up, so ``rc == 0`` IS the budget assertion."""
    from gauss_tpu import obs
    from gauss_tpu.serve import durable

    root = _fresh_dir(os.path.join(tmpdir, "leg-supervised"))
    jd = os.path.join(root, "journal")
    leg: Dict = {"leg": "supervised"}
    rid = f"q{seed + 1}-0"
    env = _env_base()
    env["GAUSS_FAULTS"] = _torn_fault(2)
    rec = obs.active()
    before_q = (rec.counters.get("serve.quarantined_respawns", 0)
                if rec else 0)
    before_r = (rec.counters.get("serve.supervisor_restarts", 0)
                if rec else 0)
    t0 = time.perf_counter()
    rc = durable.supervise(
        _drive_argv(jd, 1, seed + 1, gate, k_deaths=1),
        heartbeat_path=os.path.join(root, "heartbeat.json"),
        max_restarts=0, stall_after_s=60.0, env=env, log=log,
        flight_dir=os.path.join(root, "flight"), journal_dir=jd,
        quarantine_deaths=1)
    leg["supervise_rc"] = rc
    leg["quarantined_respawns"] = (
        (rec.counters.get("serve.quarantined_respawns", 0) if rec else 0)
        - before_q)
    leg["charged_restarts"] = (
        (rec.counters.get("serve.supervisor_restarts", 0) if rec else 0)
        - before_r)
    bad: List[str] = []
    if rc != 0:
        bad.append(f"supervise rc={rc} with max_restarts=0 — the "
                   f"quarantined death charged the budget")
    if leg["quarantined_respawns"] != 1:
        bad.append(f"quarantined respawns = "
                   f"{leg['quarantined_respawns']}, want 1")
    if leg["charged_restarts"] != 0:
        bad.append(f"charged restarts = {leg['charged_restarts']}, want 0")
    bad += _audit_pill(jd, rid, gate)
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    leg["outcome"] = "violation" if bad else "ok"
    if bad:
        leg["error"] = "; ".join(bad[:4])
    return leg


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records the campaign contributes to history.
    Slow-side gated: poison isolation getting slower (bisection waves,
    quarantine replays) shows up as s_per_case."""
    out: List[Tuple[str, float, str]] = []
    wall, cases = summary.get("wall_s"), summary.get("cases")
    if isinstance(wall, (int, float)) and wall > 0 and cases:
        out.append(("poison:s_per_case", round(wall / cases, 6), "s"))
    return out


# -- the self-driving server child (--drive) -------------------------------

def drive_main(args) -> int:
    """Subprocess worker mode: a journaled quarantine-enabled server fed
    a seeded HEALTHY plan under rid dedupe. With a torn-write fault
    armed, this process dies mid-dispatch; rerun against the same journal
    it replays — and once the blame evidence reaches ``--k-deaths``, the
    replay runs the implicated rid solo on the host ladder."""
    from gauss_tpu import obs
    from gauss_tpu.serve.server import SolverServer

    honor_jax_platforms()
    rng = np.random.default_rng(np.random.SeedSequence((args.seed, 0xD21)))
    cfg = _case_config(args.journal, args.gate, max_batch=1,
                       quarantine_deaths=args.k_deaths,
                       heartbeat_path=os.environ.get(
                           "GAUSS_SERVE_HEARTBEAT") or None,
                       flight_dir=os.environ.get("GAUSS_FLIGHT_DIR") or None)
    with obs.run(metrics_out=args.metrics_out, tool="poison_drive",
                 requests=args.requests, seed=args.seed):
        srv = SolverServer(cfg)
        srv.start()  # replay FIRST: submits below dedupe against it
        served_before = srv.requests_served
        handles = []
        for j in range(args.requests):
            a, b = _system(rng, 24)
            handles.append((f"q{args.seed}-{j}",
                            srv.submit(a, b, request_id=f"q{args.seed}-{j}")))
        statuses = {}
        for rid, h in handles:
            res = h.result(timeout=180.0)
            statuses[rid] = res.status if res is not None else None
        srv.stop(drain=True, timeout=180.0)
        print("DRIVE:" + json.dumps({
            "requests": args.requests,
            "resume": srv.last_resume,
            "statuses": statuses,
            "solved_fresh": srv.requests_served - served_before,
        }))
    return 0


# -- campaign main ---------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.serve.poisoncheck",
        description="Poison-the-server chaos campaign: non-finite/"
                    "singular/batch-pill/torn-wire poison next to "
                    "innocent traffic; every culprit must draw exactly "
                    "one typed poison terminal, every innocent must be "
                    "served and verified, and a journaled poison admit "
                    "must never crash-loop a restart.")
    p.add_argument("--cases", type=int, default=28,
                   help="in-process isolation cases, cycled over kinds "
                        f"{POISON_KINDS} (default 28)")
    p.add_argument("--seed", type=int, default=777201)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--tmpdir", default="/tmp/gauss_poison",
                   help="journal/replica scratch directory")
    p.add_argument("--no-subprocess", action="store_true",
                   help="skip the crash-loop/supervised subprocess legs "
                        "and the 3-replica leg (in-process cases + mesh "
                        "leg only)")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append campaign records to the regression history "
                        "(default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    # -- the subprocess worker mode ---------------------------------------
    p.add_argument("--drive", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--journal", default=None, help=argparse.SUPPRESS)
    p.add_argument("--requests", type=int, default=1,
                   help=argparse.SUPPRESS)
    p.add_argument("--k-deaths", type=int, default=2, dest="k_deaths",
                   help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.drive:
        if not args.journal:
            print("poisoncheck --drive needs --journal", file=sys.stderr)
            return 2
        return drive_main(args)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve.cache import ExecutableCache

    os.makedirs(args.tmpdir, exist_ok=True)
    inner = ExecutableCache(64)  # shared across cases: the campaign
    #                              measures isolation, not XLA compiles
    cache = _TrippingCache(inner)
    t0 = time.perf_counter()
    outcomes: List[Dict] = []
    with obs.run(metrics_out=args.metrics_out, tool="poison_campaign",
                 cases=args.cases, seed=args.seed):
        with obs.span("poison_isolation_phase", cases=args.cases):
            for i in range(args.cases):
                kind = POISON_KINDS[i % len(POISON_KINDS)]
                outcomes.append(run_case(i, args.seed, args.gate,
                                         args.tmpdir, kind, cache=cache))
                if (i + 1) % 8 == 0:
                    print(f"  isolation cases: {i + 1}/{args.cases}")
        legs: List[Dict] = [run_mesh_leg(args.seed, args.gate, args.tmpdir,
                                         cache=inner)]
        if not args.no_subprocess:
            with obs.span("poison_leg_phase"):
                legs.append(run_replica_leg(args.seed, args.gate,
                                            args.tmpdir))
                legs.append(run_crashloop_leg(args.seed, args.gate,
                                              args.tmpdir))
                legs.append(run_supervised_leg(args.seed, args.gate,
                                               args.tmpdir))
        wall = round(time.perf_counter() - t0, 3)

        rec = obs.active()
        counters = dict(rec.counters) if rec else {}
        requests = sum(o.get("requests", 0) for o in outcomes) + \
            sum(leg.get("requests", 0) for leg in legs)
        innocents = sum(o.get("innocents", 0) for o in outcomes)
        case_violations = [o for o in outcomes if o["outcome"] != "ok"]
        leg_violations = [leg for leg in legs
                          if leg["outcome"] == "violation"]
        violations = len(case_violations) + len(leg_violations)
        # A crash loop = the crashloop/supervised legs failing to converge
        crash_loops = sum(1 for leg in leg_violations
                          if leg["leg"] in ("crashloop", "supervised"))
        total_cases = args.cases + len(legs)
        summary = {
            "kind": "poison_campaign", "seed": args.seed,
            "gate": args.gate, "cases": total_cases,
            "in_process_cases": args.cases, "requests": requests,
            "innocents": innocents,
            "innocents_verified": innocents - sum(
                1 for o in case_violations if "innocent" in
                (o.get("error") or "")),
            "culprits": args.cases + 2 * len(
                [leg for leg in legs if leg["leg"] in ("mesh", "replica")]),
            "culprits_typed": args.cases - len(case_violations),
            "bisections": counters.get("serve.bisections", 0),
            "nonfinite_rescues": counters.get("serve.nonfinite_rescues", 0),
            "poisoned": counters.get("serve.poisoned", 0),
            "case_violations": [
                {k: o.get(k) for k in ("case", "kind", "error")}
                for o in case_violations],
            "legs": legs, "wall_s": wall,
            "violations": violations, "crash_loops": crash_loops,
            "invariant_ok": violations == 0,
        }
        obs.emit("poison_campaign",
                 **{k: v for k, v in summary.items() if k != "kind"})

    print(f"poison campaign: {args.cases} isolation case(s) + "
          f"{len(legs)} leg(s), {requests} request(s) "
          f"({innocents} innocents)")
    print(f"  poisoned: {summary['poisoned']} typed terminal(s), "
          f"{summary['bisections']} bisection(s), "
          f"{summary['nonfinite_rescues']} non-finite rescue(s)")
    for leg in legs:
        print(f"  leg[{leg['leg']}]: {leg['outcome']}"
              + (f" — {leg['error']}" if leg.get("error") else ""))
    print(f"  invariant {'HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "poisoncheck",
                "kind": "poison"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 and not violations:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        print(f"poisoncheck: INVARIANT VIOLATED ({violations} case(s))",
              file=sys.stderr)
        for o in case_violations[:5]:
            print(f"  case {o['case']} [{o['kind']}]: {o.get('error')}",
                  file=sys.stderr)
        for leg in leg_violations[:4]:
            print(f"  leg [{leg['leg']}]: {leg.get('error')}",
                  file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
