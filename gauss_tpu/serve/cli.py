"""``gauss-serve`` — drive the batched solver service under load.

Runs the open/closed-loop load generator (gauss_tpu.serve.loadgen) against
an in-process :class:`SolverServer`, prints the serving report, and
optionally: writes the machine-readable summary JSON, records the run in
the benchmark-regression history (``reports/history.jsonl``), and gates it
against that history (``--regress-check``) — the serving analog of
``bench.py --regress``.

Examples::

    # CPU smoke load (what `make serve-check` runs):
    JAX_PLATFORMS=cpu gauss-serve --requests 50 \
        --mix random:96*2,random:200,internal:160 --metrics-out serve.jsonl

    # Open-loop at 80 req/s with deadlines, summary + history:
    gauss-serve --mode open --rate 80 --requests 500 --deadline 0.5 \
        --summary-json serve_summary.json --history --regress-check
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from gauss_tpu.utils.env import honor_jax_platforms


def _install_drain_handler(server) -> None:
    """SIGTERM = graceful drain (journal runs only): stop admitting, serve
    what was accepted, journal the clean-shutdown marker, exit cleanly.
    The handler runs in the main thread between bytecodes; stop() is
    thread-safe against the worker and any in-flight client waits (their
    requests resolve as served or rejected — exactly one terminal each)."""
    def _drain(signum, frame):
        print("gauss-serve: SIGTERM — draining (clean-shutdown marker "
              "journaled on completion)", file=sys.stderr)
        server.stop(drain=True)
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _drain)
    except ValueError:  # pragma: no cover — not the main thread
        pass


def _run_supervised(args, argv) -> int:
    """``--supervised``: re-exec this same serve command as a CHILD under
    gauss_tpu.serve.durable.supervise (the PR-5 fleet watchdog pattern).
    Died/stalled children restart against the same journal; the journal's
    resume makes the restart correct and --compile-cache makes it warm."""
    from gauss_tpu import obs
    from gauss_tpu.serve import durable

    if not args.journal:
        print("gauss-serve: --supervised requires --journal (the restart "
              "is only correct against a journal)", file=sys.stderr)
        return 2
    child = [a for a in (argv if argv is not None else sys.argv[1:])
             if a != "--supervised"]
    child_argv = [sys.executable, "-m", "gauss_tpu.serve.cli"] + child
    hb = os.path.join(args.journal, "heartbeat.json")
    with obs.run(tool="gauss_serve_supervisor", journal=args.journal):
        return durable.supervise(
            child_argv, heartbeat_path=hb, max_restarts=args.max_restarts,
            stall_after_s=args.stall_after,
            flight_dir=args.flight_dir, journal_dir=args.journal)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gauss-serve",
        description="Batched solver serving load test: request queue, "
                    "shape-bucketed executable cache, admission control.")
    p.add_argument("--mix", default="random:100*2,random:200,internal:160",
                   help="weighted workload tokens kind:arg[*weight] "
                        "(kinds: random:<n>, internal:<n>, dat:<path>, "
                        "dataset:<name>, spd:<n>, banded:<n>/<b>, "
                        "blockdiag:<n>/<k>, dtype:<dt>/<n> — drives the "
                        "lowered bf16/bf16x3 batched lanes — and "
                        "poison:<nan|inf|singular>/<n> — deliberately bad "
                        "operands at a controlled rate; typed poison "
                        "rejects are reported separately from failures)")
    p.add_argument("--requests", type=int, default=50,
                   help="measured request count (default 50)")
    p.add_argument("--warmup", type=int, default=8,
                   help="warmup requests excluded from the report "
                        "(default 8)")
    p.add_argument("--mode", choices=("closed", "open"), default="closed",
                   help="closed: N clients submit+wait; open: Poisson "
                        "arrivals at --rate regardless of completions")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop client threads (default 4)")
    p.add_argument("--rate", type=float, default=50.0,
                   help="open-loop arrival rate, requests/s (default 50)")
    p.add_argument("--nrhs", type=int, default=1,
                   help="right-hand-side columns per request (default 1)")
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="per-request deadline in seconds (expired requests "
                        "are shed before compute)")
    p.add_argument("--seed", type=int, default=258458)
    # -- server knobs -----------------------------------------------------
    p.add_argument("--ladder", default=None,
                   help="comma-separated bucket sizes (default: 128,256,"
                        "...,4096 — panel-aligned powers of two)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--cache-capacity", type=int, default=32)
    p.add_argument("--refine-steps", type=int, default=1,
                   help="host-f64 refinement rounds per batch (default 1)")
    p.add_argument("--dtype", choices=("float32", "bfloat16", "bf16x3"),
                   default="float32",
                   help="batched-lane storage dtype default (per-request "
                        "dtype: mix tokens override it); lowered dtypes "
                        "key their own cache entries and rely on "
                        "--refine-steps + the verify gate for the 1e-4 "
                        "contract (default float32)")
    p.add_argument("--linger", type=float, default=0.0, metavar="S",
                   help="batching linger: wait this long for same-bucket "
                        "company before dispatching (default 0)")
    p.add_argument("--panel", type=int, default=None,
                   help="blocked-solver panel width (default: auto, "
                        "consulting the tuned store when one exists)")
    # -- mesh serving (gauss_tpu.serve.lanes) ------------------------------
    p.add_argument("--lanes", type=int, default=0,
                   help="mesh serving: N async dispatch lanes across the "
                        "device mesh (key-affinity placement, work "
                        "stealing, continuous batching; 0 = the single-"
                        "lane server, the pre-mesh path)")
    p.add_argument("--lane-width", type=int, default=1,
                   help="devices per lane (a mesh slice; >1 shards the "
                        "batch axis over the slice via NamedSharding — "
                        "the oversized-bucket escape hatch; default 1)")
    p.add_argument("--cb-window", type=float, default=0.005, metavar="S",
                   help="continuous batching formation deadline: an "
                        "unfilled in-flight batch slot dispatches this "
                        "long after opening (default 0.005)")
    p.add_argument("--continuous-batching",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="lanes only: admit compatible requests into the "
                        "next in-flight batch slot (--no-continuous-"
                        "batching = per-lane fixed drain cycles, the A/B "
                        "baseline)")
    p.add_argument("--autoscale", action="store_true",
                   help="grow/shrink the active lane count on the SLO "
                        "burn-rate alert (requires --live-port; grows on "
                        "burn up to --lanes, shrinks to --min-lanes after "
                        "a quiet period)")
    p.add_argument("--min-lanes", type=int, default=1,
                   help="autoscale floor and starting count (default 1)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="enable JAX's persistent compilation cache at DIR "
                        "(gauss_tpu.tune.compilecache; also honored from "
                        "the GAUSS_COMPILE_CACHE env). A second process "
                        "sharing DIR warms up from cached executables — "
                        "the report's warmup_s shows the delta")
    # -- durable admission (gauss_tpu.serve.durable) -----------------------
    p.add_argument("--journal", default=None, metavar="DIR",
                   help="write-ahead request journal at DIR: every admit/"
                        "terminal is journaled (CRC'd JSONL segments, "
                        "batched fsync, atomic rotation) and a restarted "
                        "server replays unterminated admits — exactly-once "
                        "terminal statuses across kill -9 (docs/SERVING.md "
                        "durability section)")
    p.add_argument("--resume", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="with --journal: replay unterminated admits at "
                        "start (in-deadline requests re-solve, expired "
                        "ones get typed STATUS_EXPIRED terminals); "
                        "--no-resume journals new traffic only "
                        "(default: resume)")
    p.add_argument("--request-ids", action="store_true",
                   help="mint a deterministic idempotency key per loadgen "
                        "request (submit(request_id=...)); with --journal, "
                        "resubmissions after a crash dedupe against "
                        "journaled terminals instead of re-solving")
    p.add_argument("--supervised", action="store_true",
                   help="wrap this serve run in the fleet watchdog "
                        "pattern: a supervisor process restarts a died/"
                        "stalled server against the same journal (requires "
                        "--journal; warm restarts via --compile-cache)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="supervised mode: restart budget (default 3)")
    p.add_argument("--stall-after", type=float, default=30.0, metavar="S",
                   help="supervised mode: heartbeat staleness that calls "
                        "a stall (default 30)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="crash-surviving flight recorder at DIR: every obs "
                        "event also lands in an mmap ring that outlives "
                        "kill -9, and dead/stalled/unclean-resume detection "
                        "freezes it into a post-mortem bundle under "
                        "DIR/bundles (inspect with gauss-debug; also "
                        "honored from the GAUSS_FLIGHT_DIR env — how "
                        "--supervised hands it to the child)")
    p.add_argument("--attr", action="store_true",
                   help="install the device-time attribution plane for the "
                        "run: per-(phase, executable, lane) device-seconds, "
                        "util.* gauges (gauss_util_* on /metrics, gauss-top "
                        "utilization panel), per-request cost fields on "
                        "every result, and the cost section in the report "
                        "(docs/OBSERVABILITY.md 'Attribution & roofline'); "
                        "off = byte-identical pre-attribution traces")
    # -- the network tier (gauss_tpu.serve.net / serve.router) -------------
    p.add_argument("--net", default=None, metavar="URL",
                   help="drive the load over HTTP against a running "
                        "request endpoint (a replica or a router front) "
                        "instead of an in-process server; same mix tokens "
                        "and verification gate, history metrics tagged "
                        "serve:net:<mode> (docs/SERVING.md network tier)")
    p.add_argument("--replicas", type=int, default=0, metavar="N",
                   help="spawn N journaled replica processes behind a "
                        "consistent-hash router front and drive the load "
                        "through it; a replica killed mid-load fails its "
                        "journal over to a surviving peer with zero lost "
                        "requests (the replica-check invariant)")
    p.add_argument("--port", type=int, default=0, metavar="P",
                   help="with --replicas: the router front's listen port "
                        "(default 0 = ephemeral)")
    p.add_argument("--fleet-dir", default=None, metavar="DIR",
                   help="with --replicas: fleet state root (per-replica "
                        "journal/flight/heartbeat dirs + the router's "
                        "assign log; default: a fresh temp dir)")
    # -- live telemetry plane ---------------------------------------------
    p.add_argument("--live-port", type=int, default=None, metavar="PORT",
                   help="embed the live telemetry endpoint on PORT "
                        "(0 = ephemeral): /metrics Prometheus exposition, "
                        "/slo burn-rate alert states, /trace on-demand "
                        "Chrome-trace capture; read it live with "
                        "`gauss-top --url http://127.0.0.1:PORT`")
    p.add_argument("--slo-shed", action="store_true",
                   help="while an SLO burn-rate alert fires, shrink the "
                        "admission bound (degradation before the deadline "
                        "cliff); requires --live-port")
    # -- outputs ----------------------------------------------------------
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="append the run's obs JSONL event stream here "
                        "(summarize/trace/aggregate-compatible)")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the serving report as JSON (regress-"
                        "ingestable: kind=serve_loadgen)")
    p.add_argument("--slo-json", default=None, metavar="PATH",
                   help="write the run's SLO report as JSON (regress-"
                        "ingestable: kind=slo_report; requires "
                        "--live-port)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's throughput/latency records to "
                        "the regression history (default "
                        "reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate this run against the history baselines "
                        "(exit 1 when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.supervised:
        return _run_supervised(args, argv)
    honor_jax_platforms()

    from gauss_tpu.tune import compilecache

    cache_dir = compilecache.enable(args.compile_cache)
    if cache_dir:
        print(f"compile cache: {cache_dir}")

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve import buckets
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import (
        LoadgenConfig,
        format_summary,
        history_records,
        run_load,
        write_summary,
    )
    from gauss_tpu.serve.server import SolverServer

    ladder = ()
    if args.ladder:
        ladder = buckets.validate_ladder(
            int(r) for r in args.ladder.split(","))
    serve_cfg = ServeConfig(
        ladder=ladder, max_batch=args.max_batch, max_queue=args.max_queue,
        batch_linger_s=args.linger, cache_capacity=args.cache_capacity,
        refine_steps=args.refine_steps, panel=args.panel,
        dtype=args.dtype, live_port=args.live_port, slo_shed=args.slo_shed,
        journal_dir=args.journal, resume=args.resume,
        lanes=args.lanes, lane_width=args.lane_width,
        continuous_batching=args.continuous_batching,
        cb_window_s=args.cb_window, autoscale=args.autoscale,
        min_lanes=args.min_lanes, attr=(args.attr or None),
        heartbeat_path=os.environ.get("GAUSS_SERVE_HEARTBEAT") or None,
        flight_dir=(args.flight_dir
                    or os.environ.get("GAUSS_FLIGHT_DIR") or None))
    cfg = LoadgenConfig(
        mix=args.mix, requests=args.requests, warmup=args.warmup,
        mode=args.mode, concurrency=args.concurrency, rate=args.rate,
        nrhs=args.nrhs, seed=args.seed, deadline_s=args.deadline,
        request_ids=args.request_ids, serve=serve_cfg)

    if args.net and args.replicas:
        print("gauss-serve: --net and --replicas are exclusive (--net "
              "targets an endpoint that already exists)", file=sys.stderr)
        return 2
    with obs.run(metrics_out=args.metrics_out, tool="gauss_serve",
                 mode=args.mode, mix=args.mix, requests=args.requests):
        if args.net or args.replicas:
            # The network tier: the same loadgen plan through
            # serve.net.SolveClient — against an existing endpoint
            # (--net) or a freshly spawned replica fleet (--replicas).
            import tempfile

            from gauss_tpu.serve.net import SolveClient
            from gauss_tpu.serve.router import Router, RouterConfig

            router = None
            try:
                if args.replicas:
                    fleet_dir = (args.fleet_dir
                                 or tempfile.mkdtemp(prefix="gauss_fleet-"))
                    router = Router(RouterConfig(
                        replicas=args.replicas, port=args.port,
                        dir=fleet_dir, ladder=tuple(ladder),
                        max_batch=args.max_batch, max_queue=args.max_queue,
                        linger_s=args.linger, dtype=args.dtype)).start()
                    url = router.url
                    print(f"replica fleet: {args.replicas} replica(s) "
                          f"behind {url} (state: {fleet_dir})")
                else:
                    url = args.net
                summary = run_load(SolveClient(url), cfg)
            finally:
                if router is not None:
                    out = router.stop(drain=True)
                    print(f"fleet drained: {out['causes']}")
        else:
            with SolverServer(serve_cfg) as server:
                if args.journal:
                    # Graceful drain: SIGTERM -> stop admitting, flush
                    # in-flight batches, journal the clean-shutdown marker,
                    # exit 0 — the next start replays nothing.
                    _install_drain_handler(server)
                if server.live_url:
                    print(f"live telemetry: {server.live_url}/metrics "
                          f"(watch with: gauss-top --url {server.live_url})")
                if args.journal and server.last_resume:
                    print(f"journal: {args.journal} "
                          f"resume={server.last_resume}")
                summary = run_load(server, cfg)
    print(format_summary(summary))
    if args.metrics_out:
        print(f"metrics: {args.metrics_out}")

    if args.summary_json:
        write_summary(summary, args.summary_json)
        print(f"summary: {args.summary_json}")

    if args.slo_json:
        if summary.get("slo"):
            write_summary(summary["slo"], args.slo_json)
            print(f"slo report: {args.slo_json}")
        else:
            print("gauss-serve: --slo-json needs --live-port (no SLO "
                  "monitors ran)", file=sys.stderr)

    rc = 0
    records = [{"metric": m, "value": v, "unit": "s",
                "source": "gauss-serve", "kind": "serve"}
               for m, v in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(records,
                                         regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if summary["incorrect"]:
        print(f"gauss-serve: {summary['incorrect']} INCORRECT solution(s) "
              f"(relative residual above {cfg.verify_gate:g})",
              file=sys.stderr)
        rc = max(rc, 2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
