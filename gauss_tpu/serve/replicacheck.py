"""The kill-the-replica chaos campaign: ``python -m gauss_tpu.serve.replicacheck``.

Asserts the replicated-serving invariant the network tier
(gauss_tpu.serve.net) and the router (gauss_tpu.serve.router) exist to
provide:

    **kill any replica mid-load and lose zero requests — every admitted
    request reaches EXACTLY ONE terminal status (served results
    re-verified at the gate from the journaled operands) across replica
    SIGKILLs, stalls, torn journal tails, graceful drains, router
    restarts, and client resubmission storms; and no request is ever
    solved twice.**

Like the durable campaign this is judged journal-vs-ledger: the runner
keeps its own client-side LEDGER of every admitted request, then audits
the UNION of every replica journal (live incarnations AND the retired
``journal-failed-*`` directories handed to adopters) against it — one
terminal per ledger entry across the whole fleet, no matter which
replica answered.

Phases:

- **failover cases** (``--cases``, in-process, cycled over kinds):
  seeded victim-journal → adopt-on-survivor scenarios driving
  :func:`gauss_tpu.serve.net.adopt_journal` directly — ``sigkill`` (live
  victim crashed mid-batch), ``stall`` (victim admitted but never
  dispatched), ``torn`` (victim's journal tail torn mid-terminal-append),
  ``drain`` (clean shutdown: adoption must import terminals and replay
  NOTHING), ``expired`` (admit whose deadline passed during the failover
  window must resolve as a typed expiry, never a silent drop),
  ``router_restart`` (assign-log pins survive close/reopen; a torn tail
  loses only rehash-recoverable pins). Every case ends with a
  resubmission storm through the survivor that must dedupe to the
  journaled terminals without one new solve.
- **fleet legs** (``--no-subprocess`` to skip): a REAL 3-replica router
  (``gauss-serve --replicas 3`` shape) under concurrent network load
  where every replica in turn is SIGKILLed mid-load (the acceptance
  drill: zero lost, exactly-once under the storm, each kill leaving a
  post-mortem bundle that passes ``gauss-debug --check``); a SIGTERM
  drain that must respawn WITHOUT spending the restart budget; a
  SIGSTOP-stalled replica the router must detect by heartbeat staleness
  and fail over.
- **scaling** (``--no-tput`` to skip): the same injected-device-time mix
  (``serve.worker.dispatch`` delay — a sleep stands in for device time on
  this 1-core box) through 1 replica then 3; aggregate throughput must
  reach ``--min-speedup`` (default 2x, the ISSUE-19 gate). The 3-replica
  seconds-per-request and the kill legs' failover recovery latency land
  in history (``replica:s_per_request``, ``replica:failover_recovery_s``)
  and are regress-gated.

The summary is regress-ingestable (``kind: replica_campaign``). Exit 2
when the invariant is violated, 1 when ``--regress-check`` finds an
out-of-band metric, 0 otherwise. ``make replica-check`` runs the CI
configuration; it must not run concurrently with the other timing-gated
gates (Makefile serial-ordering note).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

CASE_KINDS = ("sigkill", "stall", "torn", "drain", "expired",
              "router_restart")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fresh_dir(path: str) -> str:
    """Leg/case roots must start empty: a retired ``journal-failed-*``
    or stale ``endpoint.json`` left by a previous campaign in the same
    tmpdir would be adopted as live state and corrupt the audit."""
    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    return path


def _system(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _case_config(journal_dir: Optional[str], gate: float, **over):
    from gauss_tpu.serve.admission import ServeConfig

    kw = dict(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
              verify_gate=gate, journal_dir=journal_dir,
              journal_fsync_batch=4, max_queue=256)
    kw.update(over)
    return ServeConfig(**kw)


def _wait_batches(srv, k: int, timeout_s: float = 20.0) -> None:
    t0 = time.monotonic()
    while srv.batches < k and time.monotonic() - t0 < timeout_s:
        time.sleep(0.002)


def _tear_tail(journal_dir: str, admit_id: int,
               rng: np.random.Generator) -> None:
    """A crash DURING a terminal append: a CRC-less prefix of a would-be
    terminal for ``admit_id`` at the live segment's tail. Adoption must
    drop it and replay the request."""
    from gauss_tpu.serve import durable

    segs = durable.segment_paths(journal_dir)
    payload = durable.encode_record({
        "rec": "terminal", "schema": durable.JOURNAL_SCHEMA,
        "id": int(admit_id), "rid": None, "trace": "torn", "status": "ok",
        "t_unix": time.time()})
    cut = int(rng.integers(1, len(payload) - 1))
    with open(segs[-1], "ab") as f:
        f.write(payload[:cut])


def audit_union(journal_dirs: List[str], ledger: List[Tuple[str, int]],
                gate: float) -> Dict:
    """Journal-vs-ledger audit over the UNION of replica journals (the
    failover handoff legitimately re-admits a request on the adopter, so
    duplicate ADMITS across directories are expected — duplicate
    TERMINALS are the violation). Scans RAW segment lines: the scanner's
    in-memory state dedupes terminals by id, which would hide exactly
    the double-terminal this audit exists to catch."""
    from gauss_tpu.serve import durable
    from gauss_tpu.verify import checks

    admits_by_rid: Dict[str, Dict[str, Any]] = {}
    term_statuses: Dict[str, List[str]] = {}
    term_docs: Dict[str, Dict[str, Any]] = {}
    torn_dropped = 0
    for jd in journal_dirs:
        if not os.path.isdir(jd):
            continue
        for seg in durable.segment_paths(jd):
            with open(seg, "rb") as f:
                for line in f.read().split(b"\n"):
                    if not line:
                        continue
                    doc = durable.decode_line(line + b"\n")
                    if doc is None:
                        torn_dropped += 1
                        continue
                    rid = doc.get("rid")
                    if not rid:
                        continue
                    if doc.get("rec") == "admit":
                        admits_by_rid.setdefault(rid, doc)
                    elif doc.get("rec") == "terminal":
                        term_statuses.setdefault(rid, []).append(
                            doc.get("status"))
                        term_docs.setdefault(rid, doc)
    missing: List[str] = []
    duplicates: List[str] = []
    incorrect: List[str] = []
    statuses: Dict[str, int] = {}
    for rid, _n in ledger:
        terms = term_statuses.get(rid, [])
        if not terms:
            missing.append(rid)
            continue
        if len(terms) > 1:
            duplicates.append(rid)
        term = term_docs[rid]
        statuses[term["status"]] = statuses.get(term["status"], 0) + 1
        if term["status"] == "ok":
            adm = admits_by_rid.get(rid)
            if adm is None or term.get("x") is None:
                incorrect.append(rid)
                continue
            a = durable.decode_array(adm["a"])
            b = durable.decode_array(adm["b"])
            if adm.get("was_vector"):
                b = b.reshape(-1)
            x = durable.decode_array(term["x"])
            rel = checks.residual_norm(a, x, b, relative=True)
            if not (np.isfinite(rel) and rel <= gate):
                incorrect.append(rid)
    return {"admitted": len(ledger), "terminals": len(term_docs),
            "statuses": statuses, "missing": missing,
            "duplicates": duplicates, "incorrect": incorrect,
            "torn_dropped": torn_dropped}


# -- in-process failover cases ---------------------------------------------

def _assign_log_case(i: int, seed: int, tmpdir: str) -> Dict:
    """``router_restart``: the assign-log pin map must survive a router
    restart byte-for-byte, and a TORN tail must lose only pins that the
    deterministic rehash re-derives identically (the documented recovery
    contract — the live set did not change, so the hash agrees)."""
    from gauss_tpu.serve.router import AssignLog, HashRing

    rng = np.random.default_rng(np.random.SeedSequence((seed, i, 0xA551)))
    names = ["r0", "r1", "r2"]
    ring = HashRing(names)
    path = os.path.join(
        _fresh_dir(os.path.join(tmpdir, f"case-router_restart-{i:03d}")),
        "assign.log")
    out: Dict = {"case": i, "kind": "router_restart"}
    pins: Dict[str, str] = {}
    al = AssignLog(path)
    for j in range(24):
        rid = f"rr{seed}-{i}-{j}"
        node = ring.lookup(rid)
        al.assign(rid, node)
        pins[rid] = node
    victim = names[int(rng.integers(0, 3))]
    survivors = {n for n in names if n != victim}
    adopter = ring.lookup(victim, survivors)
    moved = al.failover(victim, adopter)
    for rid, node in pins.items():
        if node == victim:
            pins[rid] = adopter
    # pins assigned AFTER the failover route over the live set only
    for j in range(24, 36):
        rid = f"rr{seed}-{i}-{j}"
        node = ring.lookup(rid, survivors)
        al.assign(rid, node)
        pins[rid] = node
    al.close()
    al2 = AssignLog(path)
    survived = al2.pins()
    al2.close()
    if survived != pins:
        out["outcome"] = "violation"
        out["error"] = (f"pins did not survive restart: "
                        f"{len(survived)} != {len(pins)}")
        return out
    # torn tail: the last record is half-written; reload drops it and
    # rehash over the unchanged live set must re-derive the lost pin
    with open(path, "rb") as f:
        raw = f.read()
    cut = int(rng.integers(3, 20))
    with open(path, "wb") as f:
        f.write(raw[:-cut])
    al3 = AssignLog(path)
    after_torn = al3.pins()
    al3.close()
    lost = {rid: node for rid, node in pins.items()
            if rid not in after_torn}
    bad = {rid: node for rid, node in lost.items()
           if ring.lookup(rid, survivors) != node}
    out["moved"] = moved
    out["torn_lost"] = len(lost)
    out["outcome"] = "violation" if bad else "ok"
    if bad:
        out["error"] = f"torn-tail pins not rehash-recoverable: {bad}"
    return out


def run_failover_case(i: int, seed: int, gate: float, tmpdir: str,
                      kind: str, cache=None) -> Dict:
    """One victim-journal → adopt-on-survivor case; returns its outcome
    record. The in-process analog of a replica death: the victim's
    journal state is exactly what a SIGKILL leaves (``_crash()`` abandons
    the queue and drops the journal handle cold), and the survivor runs
    the same :func:`net.adopt_journal` the router's failover calls."""
    if kind == "router_restart":
        return _assign_log_case(i, seed, tmpdir)

    from gauss_tpu.serve import durable
    from gauss_tpu.serve.net import adopt_journal
    from gauss_tpu.serve.server import SolverServer

    rng = np.random.default_rng(np.random.SeedSequence((seed, i, 0xF417)))
    case_dir = _fresh_dir(os.path.join(tmpdir, f"case-{kind}-{i:03d}"))
    victim_dir = os.path.join(case_dir, "victim")
    survivor_dir = os.path.join(case_dir, "survivor")
    out: Dict = {"case": i, "kind": kind}
    ledger: List[Tuple[str, int]] = []
    operands: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    n_req = 6 + int(rng.integers(0, 5))

    # -- phase 1: load the victim, then kill (or drain) it -----------------
    victim = SolverServer(_case_config(victim_dir, gate), cache=cache)
    if kind not in ("stall", "expired"):
        # A STALLED victim admitted work but never dispatched it; the
        # expired kind needs every admit still live when the deadline
        # passes. Both model that by never starting the worker.
        victim.start()
    for j in range(n_req):
        n = 16 + int(rng.integers(0, 13))
        a, b = _system(rng, n)
        rid = f"r{seed}-{i}-{j}"
        # short-deadline requests: dead by adoption time — the replay
        # must type them expired (or serve them honestly pre-kill),
        # never lose them
        expiring = (kind == "expired" and j % 2 == 0) or \
                   (kind in ("sigkill", "stall") and j == n_req - 1)
        h = victim.submit(a, b, request_id=rid,
                          deadline_s=0.2 if expiring else None)
        if not (h.done and h.result(0).status == "rejected"):
            ledger.append((rid, n))
            operands[rid] = (a, b)
    if kind == "drain":
        victim.stop(drain=True, timeout=120.0)
    else:
        if kind not in ("stall", "expired"):
            _wait_batches(victim, int(rng.integers(0, 3)))
        victim._crash()
        if kind == "torn":
            st = durable.scan(victim_dir)
            live = st.live_admits()
            vid = live[0]["id"] if live else next(iter(st.admits), 0)
            _tear_tail(victim_dir, vid, rng)
    if kind == "expired":
        time.sleep(0.35)  # every 0.2 s deadline is dead before adoption

    # -- phase 2: a surviving peer adopts the victim's journal -------------
    survivor = SolverServer(_case_config(survivor_dir, gate), cache=cache)
    survivor.start()
    adopt = adopt_journal(survivor, victim_dir)
    out["adopt"] = {k: adopt.get(k) for k in
                    ("imported", "replayed", "expired", "skipped",
                     "torn_dropped")}
    if kind == "drain" and adopt.get("replayed", 0) != 0:
        out["outcome"] = "violation"
        out["error"] = ("clean shutdown journal replayed "
                        f"{adopt['replayed']} request(s) on the adopter")
        survivor.stop()
        return out
    # Quiescence = every ledger rid holds a terminal on the survivor
    # (imported at adoption or resolved by the replay) — NOT depth==0:
    # the worker decrements depth BEFORE dispatching the final batch, so
    # a depth wait races the last in-flight solve and would misread it
    # as a storm-triggered fresh solve.
    t0 = time.monotonic()
    while (time.monotonic() - t0 < 120
           and any(rid not in survivor._rid_terminals
                   for rid, _n in ledger)):
        time.sleep(0.01)
    served_before_storm = survivor.requests_served
    while time.monotonic() - t0 < 120:
        time.sleep(0.05)
        now_served = survivor.requests_served
        if now_served == served_before_storm:
            break
        served_before_storm = now_served

    # -- phase 3: resubmission storm races the completed replay ------------
    storm_mismatch = 0
    threads: List[threading.Thread] = []
    storm_out: Dict[str, str] = {}
    lock = threading.Lock()

    def _resubmit(rid: str) -> None:
        a, b = operands[rid]
        res = survivor.solve(a, b, request_id=rid, timeout=60.0)
        with lock:
            storm_out[rid] = res.status

    for rid, _n in ledger:
        t = threading.Thread(target=_resubmit, args=(rid,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=90)
    for rid, _n in ledger:
        if storm_out.get(rid) is None:
            storm_mismatch += 1
    fresh_solves = survivor.requests_served - served_before_storm
    survivor.stop(drain=True, timeout=120.0)

    # -- audit -------------------------------------------------------------
    out["audit"] = audit_union([victim_dir, survivor_dir], ledger, gate)
    out["storm_unanswered"] = storm_mismatch
    out["storm_fresh_solves"] = fresh_solves
    a_ = out["audit"]
    if kind == "expired":
        want_expired = sum(1 for j in range(n_req) if j % 2 == 0)
        if a_["statuses"].get("expired", 0) < want_expired:
            out["outcome"] = "violation"
            out["error"] = (f"expected >= {want_expired} typed expiries, "
                            f"got {a_['statuses']}")
            return out
    violated = bool(a_["missing"] or a_["duplicates"] or a_["incorrect"]
                    or storm_mismatch or fresh_solves > 0)
    out["outcome"] = "violation" if violated else "ok"
    if violated:
        out["error"] = (f"missing={a_['missing'][:3]} "
                        f"duplicates={a_['duplicates'][:3]} "
                        f"incorrect={a_['incorrect'][:3]} "
                        f"storm_unanswered={storm_mismatch} "
                        f"storm_fresh_solves={fresh_solves}")
    return out


# -- fleet legs (real replica processes behind the router) -----------------

def _router_config(root: str, replicas: int, **over):
    from gauss_tpu.serve.router import RouterConfig

    kw = dict(replicas=replicas, dir=root, ladder=(32,), max_batch=4,
              verify_gate=None, max_restarts=3, poll_s=0.1,
              stall_after_s=30.0)
    kw.update(over)
    return RouterConfig(**kw)


def _net_load(client, mats, rids: List[str], deadline_s: float = 120.0,
              ) -> Dict[str, Any]:
    """Fire every (rid, system) through the client concurrently; returns
    rid -> ServeResult."""
    results: Dict[str, Any] = {}
    lock = threading.Lock()

    def _one(idx: int) -> None:
        a, b = mats[idx]
        res = client.solve(a, b, deadline_s=deadline_s,
                           request_id=rids[idx])
        with lock:
            results[rids[idx]] = res

    threads = [threading.Thread(target=_one, args=(i,))
               for i in range(len(rids))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    return results


def _journal_dirs(router) -> List[str]:
    import glob

    dirs = []
    for rdir in router.replica_dirs():
        dirs.extend(sorted(glob.glob(os.path.join(rdir, "journal*"))))
    return dirs


def _bundle_ok(replica_dir: str) -> Tuple[Optional[str], bool]:
    """The latest post-mortem bundle under a replica's flight ring, and
    whether ``gauss-debug --check`` passes on it — the operator-facing
    artifact every charged kill must leave behind."""
    from gauss_tpu.obs import debug as _gdebug
    from gauss_tpu.obs import postmortem

    bundle = postmortem.latest_bundle(
        postmortem.default_bundles_dir(os.path.join(replica_dir, "flight")))
    if bundle is None:
        return None, False
    return bundle, _gdebug.main([bundle, "--check"]) == 0


def _wait_respawn(router, name: str, old_pid: int,
                  timeout_s: float = 120.0) -> float:
    """Seconds from now until ``name`` is live again with a NEW pid —
    the client-observable failover recovery latency."""
    t0 = time.perf_counter()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        rp = router.live_replicas().get(name)
        if rp is not None and rp.proc.pid != old_pid and rp.url:
            return time.perf_counter() - t0
        time.sleep(0.02)
    raise TimeoutError(f"replica {name} did not respawn in {timeout_s} s")


def run_kill_leg(seed: int, gate: float, tmpdir: str, log=print) -> Dict:
    """The acceptance drill: 3 replicas under concurrent network load,
    every replica SIGKILLed in turn mid-load — zero lost requests, ok
    terminals re-verified from journaled operands, the resubmission storm
    dedupes to the same terminals, and each kill leaves a checkable
    post-mortem bundle."""
    from gauss_tpu.serve.net import SolveClient
    from gauss_tpu.serve.router import Router

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x4B11)))
    root = _fresh_dir(os.path.join(tmpdir, "leg-kill3"))
    leg: Dict = {"leg": "kill3"}
    n_req = 45
    mats = []
    for _ in range(n_req):
        n = 8 + int(rng.integers(0, 33))
        mats.append(_system(rng, n))
    rids = [f"k3-{seed}-{j}" for j in range(n_req)]
    ledger = [(rid, mats[j][0].shape[0]) for j, rid in enumerate(rids)]
    t0 = time.perf_counter()
    recoveries: List[float] = []
    with Router(_router_config(root, 3)) as router:
        client = SolveClient(router.url, timeout_s=180.0, wait_s=5.0,
                             seed=seed)
        load = threading.Thread(
            target=lambda: leg.update(results=_net_load(client, mats, rids)))
        load.start()
        for victim in ("r0", "r1", "r2"):
            time.sleep(0.4)
            old_pid = router.kill_replica(victim)
            recoveries.append(_wait_respawn(router, victim, old_pid))
            log(f"  kill3: SIGKILLed {victim} (pid {old_pid}), live again "
                f"in {recoveries[-1]:.2f} s")
        load.join(timeout=300)
        results = leg.pop("results", {})
        # resubmission storm: every rid again — must agree, no new solves
        storm = _net_load(client, mats, rids)
        stats = router.stats()
        leg["restarts_used"] = stats["restarts_used"]
        jdirs = _journal_dirs(router)
        router.stop(drain=True)
    lost = [rid for rid in rids if rid not in results
            or results[rid].status is None]
    not_ok = [rid for rid, res in results.items() if not res.ok]
    storm_mismatch = [rid for rid in rids
                      if storm.get(rid) is None
                      or storm[rid].status != results[rid].status]
    leg["audit"] = audit_union(jdirs, ledger, gate)
    leg["recovery_s"] = [round(r, 3) for r in recoveries]
    leg["client_lost"] = lost
    leg["client_not_ok"] = not_ok
    leg["storm_mismatch"] = storm_mismatch
    leg["client_retries"] = client.retries
    bundles = {}
    for victim in ("r0", "r1", "r2"):
        bundle, ok = _bundle_ok(os.path.join(root, victim))
        bundles[victim] = {"bundle": bundle, "check_ok": ok}
    leg["bundles"] = bundles
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    a_ = leg["audit"]
    violated = bool(lost or not_ok or storm_mismatch or a_["missing"]
                    or a_["duplicates"] or a_["incorrect"]
                    or leg["restarts_used"] != 3
                    or not all(b["check_ok"] for b in bundles.values()))
    leg["outcome"] = "violation" if violated else "ok"
    if violated:
        leg["error"] = (f"lost={lost[:3]} not_ok={not_ok[:3]} "
                        f"storm={storm_mismatch[:3]} "
                        f"missing={a_['missing'][:3]} "
                        f"duplicates={a_['duplicates'][:3]} "
                        f"incorrect={a_['incorrect'][:3]} "
                        f"restarts_used={leg['restarts_used']} "
                        f"bundles={ {k: v['check_ok'] for k, v in bundles.items()} }")
    return leg


def run_drain_leg(seed: int, gate: float, tmpdir: str, log=print) -> Dict:
    """SIGTERM mid-load: the replica drains, exits ``fleet.DRAIN_EXIT``,
    and the router respawns it WITHOUT spending the crash-restart budget
    (the ISSUE-19 fleet-accounting satellite, proven at the fleet level)."""
    from gauss_tpu.serve.net import SolveClient
    from gauss_tpu.serve.router import Router

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0xD7A1)))
    root = _fresh_dir(os.path.join(tmpdir, "leg-drain"))
    leg: Dict = {"leg": "drain_free"}
    n_req = 16
    mats = [_system(rng, 12 + int(rng.integers(0, 21)))
            for _ in range(n_req)]
    rids = [f"dr-{seed}-{j}" for j in range(n_req)]
    ledger = [(rid, mats[j][0].shape[0]) for j, rid in enumerate(rids)]
    with Router(_router_config(root, 2)) as router:
        client = SolveClient(router.url, timeout_s=120.0, wait_s=5.0,
                             seed=seed)
        results: Dict[str, Any] = {}
        load = threading.Thread(
            target=lambda: results.update(_net_load(client, mats, rids)))
        load.start()
        time.sleep(0.3)
        old_pid = router.terminate_replica("r1")
        recovery = _wait_respawn(router, "r1", old_pid)
        log(f"  drain_free: SIGTERMed r1 (pid {old_pid}), respawned in "
            f"{recovery:.2f} s")
        load.join(timeout=240)
        stats = router.stats()
        jdirs = _journal_dirs(router)
        router.stop(drain=True)
    leg["restarts_used"] = stats["restarts_used"]
    leg["failovers"] = stats["failovers"]
    leg["recovery_s"] = round(recovery, 3)
    leg["audit"] = audit_union(jdirs, ledger, gate)
    lost = [rid for rid in rids if rid not in results
            or results[rid].status is None]
    a_ = leg["audit"]
    violated = bool(lost or a_["missing"] or a_["duplicates"]
                    or a_["incorrect"]
                    or stats["restarts_used"] != 0
                    or stats["failovers"] < 1)
    leg["outcome"] = "violation" if violated else "ok"
    if violated:
        leg["error"] = (f"lost={lost[:3]} missing={a_['missing'][:3]} "
                        f"duplicates={a_['duplicates'][:3]} "
                        f"restarts_used={stats['restarts_used']} "
                        f"(drain must be budget-free) "
                        f"failovers={stats['failovers']}")
    return leg


def run_stall_leg(seed: int, gate: float, tmpdir: str, log=print) -> Dict:
    """A SIGSTOPped replica stops touching its heartbeat; the router must
    call the stall, kill it, fail its journal over, and leave a
    ``supervisor_stall`` bundle — without the clients noticing more than
    latency."""
    from gauss_tpu.serve.net import SolveClient
    from gauss_tpu.serve.router import Router

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x57A7)))
    root = _fresh_dir(os.path.join(tmpdir, "leg-stall"))
    leg: Dict = {"leg": "stall"}
    n_req = 12
    mats = [_system(rng, 12 + int(rng.integers(0, 21)))
            for _ in range(n_req)]
    rids = [f"st-{seed}-{j}" for j in range(n_req)]
    ledger = [(rid, mats[j][0].shape[0]) for j, rid in enumerate(rids)]
    with Router(_router_config(root, 2, stall_after_s=2.5,
                               poll_s=0.2)) as router:
        client = SolveClient(router.url, timeout_s=180.0, wait_s=3.0,
                             seed=seed)
        results: Dict[str, Any] = {}
        load = threading.Thread(
            target=lambda: results.update(
                _net_load(client, mats, rids, deadline_s=150.0)))
        load.start()
        time.sleep(0.3)
        victim = router.live_replicas()["r0"]
        os.kill(victim.proc.pid, signal.SIGSTOP)
        recovery = _wait_respawn(router, "r0", victim.proc.pid,
                                 timeout_s=180.0)
        log(f"  stall: SIGSTOPped r0 (pid {victim.proc.pid}), failed over "
            f"and respawned in {recovery:.2f} s")
        load.join(timeout=300)
        stats = router.stats()
        jdirs = _journal_dirs(router)
        router.stop(drain=True)
    bundle, bundle_ok = _bundle_ok(os.path.join(root, "r0"))
    leg["bundle"] = bundle
    leg["bundle_check_ok"] = bundle_ok
    leg["recovery_s"] = round(recovery, 3)
    leg["restarts_used"] = stats["restarts_used"]
    leg["audit"] = audit_union(jdirs, ledger, gate)
    lost = [rid for rid in rids if rid not in results
            or results[rid].status is None]
    a_ = leg["audit"]
    violated = bool(lost or a_["missing"] or a_["duplicates"]
                    or a_["incorrect"] or not bundle_ok
                    or stats["restarts_used"] != 1)
    leg["outcome"] = "violation" if violated else "ok"
    if violated:
        leg["error"] = (f"lost={lost[:3]} missing={a_['missing'][:3]} "
                        f"duplicates={a_['duplicates'][:3]} "
                        f"bundle_ok={bundle_ok} "
                        f"restarts_used={stats['restarts_used']}")
    return leg


def run_tput_phase(seed: int, tmpdir: str, min_speedup: float,
                   log=print) -> Dict:
    """Aggregate throughput: the same mix through 1 replica then 3, with
    an injected per-dispatch delay standing in for device time (this box
    has one core — real compute cannot scale with process count, but the
    serving path around a sleeping device must). 3 replicas must reach
    ``min_speedup`` x the single-replica throughput."""
    from gauss_tpu.resilience import inject as _inject
    from gauss_tpu.serve.net import SolveClient
    from gauss_tpu.serve.router import Router

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x7707)))
    n_req = 30
    mats = [_system(rng, 24) for _ in range(n_req)]
    out: Dict = {"min_speedup": min_speedup}
    # the same fault plan reaches EVERY replica in BOTH legs: dispatch
    # costs a fixed 0.12 s of injected "device time" per batch
    os.environ[_inject.ENV_VAR] = \
        "serve.worker.dispatch=delay:param=0.12:max=1000000"
    try:
        for replicas in (1, 3):
            root = _fresh_dir(os.path.join(tmpdir, f"leg-tput{replicas}"))
            with Router(_router_config(root, replicas,
                                       max_batch=1)) as router:
                client = SolveClient(router.url, timeout_s=240.0,
                                     wait_s=20.0, seed=seed)
                # warm every replica's executable cache off the clock
                warm = [_system(rng, 24) for _ in range(4 * replicas)]
                _net_load(client, warm,
                          [f"w{replicas}-{seed}-{j}"
                           for j in range(len(warm))], deadline_s=240.0)
                rids = [f"tp{replicas}-{seed}-{j}" for j in range(n_req)]
                t0 = time.perf_counter()
                results = _net_load(client, mats, rids, deadline_s=240.0)
                wall = time.perf_counter() - t0
                router.stop(drain=True)
            not_ok = sum(1 for r in results.values() if not r.ok)
            out[f"replicas_{replicas}"] = {
                "wall_s": round(wall, 3),
                "s_per_request": round(wall / n_req, 6),
                "throughput_rps": round(n_req / wall, 3),
                "not_ok": not_ok,
            }
            log(f"  tput: {replicas} replica(s) -> "
                f"{out[f'replicas_{replicas}']['throughput_rps']} req/s")
    finally:
        os.environ.pop(_inject.ENV_VAR, None)
    r1 = out["replicas_1"]["throughput_rps"]
    r3 = out["replicas_3"]["throughput_rps"]
    out["speedup"] = round(r3 / r1, 3) if r1 else None
    out["ok"] = bool(out["speedup"] and out["speedup"] >= min_speedup
                     and out["replicas_1"]["not_ok"] == 0
                     and out["replicas_3"]["not_ok"] == 0)
    return out


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records for history. Slow-side gated:
    per-request serving cost through 3 replicas, and how long a SIGKILL
    failover takes end-to-end (kill -> replica live again)."""
    out: List[Tuple[str, float, str]] = []
    tput = summary.get("tput") or {}
    spr = (tput.get("replicas_3") or {}).get("s_per_request")
    if isinstance(spr, (int, float)) and spr > 0:
        out.append(("replica:s_per_request", spr, "s"))
    recs: List[float] = []
    for leg in (summary.get("legs") or ()):
        r = leg.get("recovery_s")
        if leg.get("leg") == "kill3" and isinstance(r, list):
            recs.extend(float(v) for v in r)
    if recs:
        out.append(("replica:failover_recovery_s",
                    round(sum(recs) / len(recs), 4), "s"))
    return out


# -- campaign main ---------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.serve.replicacheck",
        description="Kill-the-replica chaos campaign: SIGKILL/stall/torn-"
                    "tail/drain/router-restart cases against the "
                    "replicated network tier; every admitted request must "
                    "reach exactly one terminal across failover, with "
                    "zero duplicate solves under resubmission storms and "
                    "aggregate throughput scaling across replicas.")
    p.add_argument("--cases", type=int, default=30,
                   help="in-process failover cases, cycled over kinds "
                        f"{CASE_KINDS} (default 30)")
    p.add_argument("--seed", type=int, default=190733)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--tmpdir", default="/tmp/gauss_replica",
                   help="replica/journal scratch directory")
    p.add_argument("--min-speedup", type=float, default=2.0,
                   help="required 3-replica/1-replica throughput ratio "
                        "(default 2.0 — the ISSUE-19 acceptance gate)")
    p.add_argument("--no-subprocess", action="store_true",
                   help="skip the real-replica fleet legs (in-process "
                        "failover cases only)")
    p.add_argument("--no-tput", action="store_true",
                   help="skip the 1-vs-3 replica throughput phase")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append campaign records to the regression history "
                        "(default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve.cache import ExecutableCache

    os.makedirs(args.tmpdir, exist_ok=True)
    cache = ExecutableCache(64)  # shared across in-process incarnations:
    #                              the campaign measures failover, not XLA
    t0 = time.perf_counter()
    outcomes: List[Dict] = []
    with obs.run(metrics_out=args.metrics_out, tool="replica_campaign",
                 cases=args.cases, seed=args.seed):
        with obs.span("replica_failover_phase", cases=args.cases):
            for i in range(args.cases):
                kind = CASE_KINDS[i % len(CASE_KINDS)]
                outcomes.append(run_failover_case(
                    i, args.seed, args.gate, args.tmpdir, kind,
                    cache=cache))
                if (i + 1) % 6 == 0:
                    print(f"  failover cases: {i + 1}/{args.cases}")
        legs: List[Dict] = []
        if not args.no_subprocess:
            with obs.span("replica_fleet_phase"):
                legs.append(run_kill_leg(args.seed, args.gate, args.tmpdir))
                legs.append(run_drain_leg(args.seed, args.gate,
                                          args.tmpdir))
                legs.append(run_stall_leg(args.seed, args.gate,
                                          args.tmpdir))
        tput = ({} if args.no_tput
                else run_tput_phase(args.seed, args.tmpdir,
                                    args.min_speedup))
        wall = round(time.perf_counter() - t0, 3)

        audited = [o for o in outcomes if "audit" in o]
        admitted = sum(o["audit"]["admitted"] for o in audited)
        statuses: Dict[str, int] = {}
        for o in audited:
            for k, v in o["audit"]["statuses"].items():
                statuses[k] = statuses.get(k, 0) + v
        replayed = sum((o.get("adopt") or {}).get("replayed", 0)
                       for o in outcomes)
        expired = sum((o.get("adopt") or {}).get("expired", 0)
                      for o in outcomes)
        imported = sum((o.get("adopt") or {}).get("imported", 0)
                       for o in outcomes)
        case_violations = [o for o in outcomes if o["outcome"] != "ok"]
        leg_violations = [leg for leg in legs
                          if leg["outcome"] == "violation"]
        violations = (len(case_violations) + len(leg_violations)
                      + (0 if (not tput or tput.get("ok")) else 1))
        summary = {
            "kind": "replica_campaign", "seed": args.seed,
            "gate": args.gate, "cases": args.cases + len(legs),
            "in_process_cases": args.cases,
            "admitted": admitted, "statuses": statuses,
            "replayed_on_peer": replayed,
            "expired_in_failover": expired,
            "terminals_imported": imported,
            "case_violations": [
                {k: o.get(k) for k in ("case", "kind", "error")}
                for o in case_violations],
            "legs": legs, "tput": tput, "wall_s": wall,
            "invariant_ok": violations == 0,
        }
        obs.emit("replica_campaign",
                 **{k: v for k, v in summary.items() if k != "kind"})

    print(f"replica campaign: {args.cases} failover case(s) + "
          f"{len(legs)} fleet leg(s), {admitted} admitted request(s)")
    print(f"  terminals: {statuses} — {replayed} replayed on a peer, "
          f"{expired} typed-expired in failover, {imported} imported for "
          f"dedupe")
    for leg in legs:
        a_ = leg["audit"]
        print(f"  leg[{leg['leg']}]: {leg['outcome']} "
              f"admitted={a_['admitted']} missing={len(a_['missing'])} "
              f"duplicates={len(a_['duplicates'])} "
              f"recovery_s={leg.get('recovery_s')}")
    if tput:
        print(f"  throughput: 1 replica "
              f"{tput['replicas_1']['throughput_rps']} req/s -> 3 replicas "
              f"{tput['replicas_3']['throughput_rps']} req/s "
              f"(speedup {tput['speedup']}x, gate {args.min_speedup}x: "
              f"{'ok' if tput['ok'] else 'FAIL'})")
    print(f"  invariant {'HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u,
                "source": "replicacheck", "kind": "replica"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 and not violations:
        # A gate-failing run must not ratchet its numbers into the
        # baseline — only campaigns whose invariant held get an epoch.
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        print(f"replicacheck: INVARIANT VIOLATED ({violations} case(s))",
              file=sys.stderr)
        for o in case_violations[:5]:
            print(f"  case {o['case']} [{o['kind']}]: {o.get('error')}",
                  file=sys.stderr)
        for leg in leg_violations[:3]:
            print(f"  leg [{leg['leg']}]: {leg.get('error')}",
                  file=sys.stderr)
        if tput and not tput.get("ok"):
            print(f"  tput: speedup {tput.get('speedup')} < "
                  f"{args.min_speedup}", file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
