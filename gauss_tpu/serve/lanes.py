"""Mesh serving: per-device dispatch lanes, work stealing, continuous
batching, and SLO-driven lane autoscaling (the pod-scale serving plane).

The single-lane :class:`~gauss_tpu.serve.server.SolverServer` drains one
queue into one executable lane on one device while the rest of the mesh
idles. This module is the multi-lane replacement
(``ServeConfig(lanes=N)``): a :class:`LaneSet` places the bucket
executables across the devices of the mesh — one async dispatch lane per
device (or per ``lane_width``-device mesh SLICE, over which GSPMD shards
the batch axis via ``NamedSharding`` — the SNIPPETS [2] pattern: sharding
is data placement, the application code is one shared executable). Four
mechanisms:

- **Key-affinity placement.** Admission routes a request to the lane
  that owns its batch-compatibility signature (bucket, dtype, structure
  — the CacheKey identity): the first time a signature is seen it is
  assigned the next lane round-robin and the assignment STICKS, so
  compatible traffic collects on a lane and batches densely instead of
  being sprayed thin across every queue, while distinct signatures
  spread across the set (a hash could collide them all onto one lane —
  CRCs of small-bucket signatures do exactly that).
- **Work stealing.** Affinity under a skewed token mix piles work onto
  few lanes; an idle lane steals a compatible run from the TAIL of the
  deepest sibling queue (the victim keeps its head-of-line FIFO order,
  the thief gets a ready-to-dispatch same-key batch). Occupancy skew
  self-corrects without a central balancer.
- **Continuous batching** (the Orca-style admission discipline, Yu et
  al. OSDI '22). Each lane publishes an open *forming slot* — the next
  in-flight batch. Admission appends a compatible request directly into
  the slot instead of the queue, and the slot for batch k+1 forms WHILE
  batch k computes, so batching costs no lane idle time. A
  **batch-formation deadline** (``cb_window_s``) bounds the wait for
  company: under load slots fill before it fires; at idle it is the
  only latency tax. ``continuous_batching=False`` keeps per-lane fixed
  drain cycles (the single-lane discipline: drain what is queued, linger
  ``batch_linger_s`` serially) — the A/B ``make mesh-serve-check``
  measures.
- **SLO-driven autoscaling.** With the live plane on and
  ``autoscale=True``, a firing burn-rate alert GROWS the active lane
  count (add capacity, don't just shed admission) and a quiet period
  shrinks it back to ``min_lanes``; placement targets active lanes only
  and active lanes steal dormant lanes' leftovers.

Every lane owns a :class:`~gauss_tpu.serve.cache.CacheView` over the ONE
shared :class:`~gauss_tpu.serve.cache.ExecutableCache`: the Python-level
build/warmup of a bucket executable is paid once per process (racing lane
warmups coalesce on the in-flight build), and each lane's device
placement is applied at dispatch (jax compiles per placement — one
backend compile per lane per key, landing at that lane's first dispatch).

Request lifecycle invariants are unchanged from the single-lane server:
admission increments the one global depth bound, ``resolve()`` keeps the
first-wins terminal CAS, the journal hooks ride the request object — so
stealing a journaled request across lanes moves WHERE it computes, never
how many terminals it gets.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.serve import buckets
from gauss_tpu.serve.cache import CacheView


def compat_sig(req, ladder) -> Optional[Tuple]:
    """The batch-compatibility signature admission, forming slots, and
    steals all key on: (bucket, dtype, structure) — exactly the fields of
    the CacheKey a batch compiles against, so two requests with equal
    sigs can always share one executable dispatch. None = oversized for
    the ladder (handoff lane; dispatches solo, never co-batched) — and
    QUARANTINED requests take the same solo path: a rid blamed for prior
    worker deaths must never share a forming slot with innocents."""
    if req.n > ladder[-1] or req.quarantine:
        return None
    return (buckets.bucket_for(req.n, ladder), req.dtype, req.structure)




class _Forming:
    """One in-flight batch slot: the batch currently being formed for a
    lane's next dispatch. Published under the lane lock so admission can
    join it (continuous batching) until it is closed or full. The close
    bound is DEADLINE-AWARE: the slot closes at its formation window OR a
    margin before the earliest member's request deadline, whichever is
    sooner — formation never lingers a member into expiry (the fixed
    drain cycle lingers blind; that delta is what mesh-serve-check's A/B
    measures)."""

    __slots__ = ("sig", "reqs", "deadline", "sealed")

    def __init__(self, sig: Optional[Tuple], deadline: float):
        self.sig = sig
        self.reqs: list = []
        self.deadline = deadline        # time.perf_counter() close bound
        self.sealed = False

    def note_member(self, req, margin: float) -> None:
        """Tighten the close bound for a member's request deadline
        (req.deadline is perf_counter-based, like the bound)."""
        if req.deadline is not None:
            self.deadline = min(self.deadline, req.deadline - margin)


class Lane:
    """One async dispatch lane: a device (or mesh slice), a deque, a
    worker thread, an open forming slot, and the lane-local stats the
    loadgen report / gauss-top panel render."""

    def __init__(self, idx: int, devices: Sequence, cache):
        self.idx = idx
        self.devices = tuple(devices)
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.queue: deque = deque()     # guarded by: self.lock
        self.forming: Optional[_Forming] = None  # guarded by: self.lock
        self.closed = False             # guarded by: self.lock — leftover
        #                                 collection has started
        self.thread: Optional[threading.Thread] = None
        self.warm = threading.Event()   # set once startup warmup finished
        self.cache_view = CacheView(cache)
        self.mesh = None
        if len(self.devices) > 1:
            from gauss_tpu.dist import mesh as _mesh

            self.mesh = _mesh.lane_mesh(self.devices)
        # -- stats (written by this lane's thread + the steal path) -------
        self.served = 0
        self.batches = 0
        self.stolen_in = 0              # requests this lane stole
        self.stolen_out = 0             # requests stolen FROM this lane
        self.cb_admits = 0              # requests admitted into a forming slot
        self.occupancy_sum = 0.0
        self.drain_rate = 0.0           # EWMA requests/s (retry-after input)
        self.device_s = 0.0             # blocked device wall this lane owns
        #                                 (ServeConfig.attr only; stays 0.0
        #                                 — and out of stats() — when the
        #                                 attribution plane is off)

    def placement_for(self, batch_bucket: int):
        """The device placement for one dispatch: the slice-sharded
        NamedSharding when this lane is wider than one device and the
        batch bucket divides across it, else the slice's first device
        (or None off-device — unit tests without placement)."""
        if self.mesh is not None and batch_bucket % len(self.devices) == 0:
            import jax

            return jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(self.mesh.axis_names[0]))
        return self.devices[0] if self.devices else None

    def note_batch(self, served: int, occupancy: float,
                   device_s: float = 0.0):
        self.batches += 1
        self.served += served
        self.occupancy_sum += occupancy
        self.device_s += device_s
        obs.gauge(f"serve.lane{self.idx}.served", self.served)
        obs.gauge(f"serve.lane{self.idx}.occupancy", occupancy)
        obs.gauge(f"serve.lane{self.idx}.queue_depth", len(self.queue))  # lockset: ok — gauge snapshot

    def stats(self) -> dict:
        return {
            "lane": self.idx,
            "devices": [str(d) for d in self.devices],
            "served": self.served,
            "batches": self.batches,
            "stolen_in": self.stolen_in,
            "stolen_out": self.stolen_out,
            "cb_admits": self.cb_admits,
            "occupancy_mean": (round(self.occupancy_sum / self.batches, 4)
                               if self.batches else None),
            "drain_rate": round(self.drain_rate, 4),
            "queue_depth": len(self.queue),  # lockset: ok — stats snapshot
            # Only with the attribution plane on — an attr=None server's
            # lane stats (and the loadgen mesh block folded from them)
            # stay byte-identical.
            **({"device_s": round(self.device_s, 6)}
               if self.device_s else {}),
        }


class LaneSet:
    """The mesh serving plane: ``config.lanes`` dispatch lanes over the
    visible devices, started/stopped by the server. See the module
    docstring for the four mechanisms; the server keeps owning admission
    bounds, journaling, verification, and terminal resolution."""

    def __init__(self, server, devices: Optional[Sequence] = None,
                 slo_firing=None):
        cfg = server.config
        self.server = server
        self.cfg = cfg
        # The SLO consult for autoscaling: default reads the server's
        # live plane; tests inject a stub.
        self._slo_firing = (slo_firing if slo_firing is not None
                            else self._server_slo_firing)
        count = max(1, int(cfg.lanes))
        slices: List[Tuple] = []
        if devices is None:
            try:
                import jax

                devices = jax.devices()
            except Exception:  # pragma: no cover — placement-less fallback
                devices = []
        if devices:
            from gauss_tpu.dist import mesh as _mesh

            slices = _mesh.lane_slices(devices, cfg.lane_width)
        if not slices:
            slices = [()]
        # More lanes than slices oversubscribes round-robin (the CPU
        # proxy's 8 virtual devices are one core anyway); fewer lanes
        # than slices leaves devices unused.
        self.lanes = [Lane(i, slices[i % len(slices)], server.cache)
                      for i in range(count)]
        self._active = (max(1, min(cfg.min_lanes, count)) if cfg.autoscale
                        else count)     # guarded by: self._scale_lock
        self._scale_lock = threading.Lock()
        self._scale_last = 0.0          # guarded by: self._scale_lock
        self._burn_last = 0.0           # guarded by: self._scale_lock
        self._stop = threading.Event()
        #: sticky sig -> lane-index affinity map (first seen = next lane
        #: round-robin); guarded by: self._place_lock
        self._sig_lane: dict = {}
        self._rr = 0                    # guarded by: self._place_lock
        self._place_lock = threading.Lock()
        #: overflow wake-up: admission notifies here when a lane queue
        #: reaches steal depth, so an IDLE lane steals immediately
        #: instead of sampling sibling queues and missing the brief
        #: windows a fast drain leaves them deep (the standard
        #: work-stealing runtime shape: wake sleepers on overflow)
        self._steal_cond = threading.Condition()
        self.steals = 0
        obs.gauge("serve.lanes_active", self._active)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LaneSet":
        for lane in self.lanes:
            if lane.thread is None or not lane.thread.is_alive():
                lane.thread = threading.Thread(
                    target=self._worker, args=(lane,),
                    name=f"gauss-serve-lane{lane.idx}", daemon=True)
                lane.thread.start()
        return self

    def stop(self, timeout: float = 60.0):
        """Stop the workers and collect every unserved request (queued or
        in an unclosed forming slot). Returns ``(leftovers, joined)`` —
        the server rejects the leftovers under its exactly-one-terminal
        contract; ``joined`` False means a worker is wedged (the journal
        must then NOT claim a clean shutdown)."""
        self._stop.set()
        for lane in self.lanes:
            with lane.lock:
                lane.cond.notify_all()
        joined = True
        for lane in self.lanes:
            if lane.thread is not None:
                lane.thread.join(timeout=timeout)
                joined = joined and not lane.thread.is_alive()
                lane.thread = None
        leftovers: list = []
        for lane in self.lanes:
            with lane.lock:
                lane.closed = True
                leftovers.extend(lane.queue)
                lane.queue.clear()
                if lane.forming is not None and not lane.forming.sealed:
                    lane.forming.sealed = True
                    leftovers.extend(lane.forming.reqs)
                lane.forming = None
        return leftovers, joined

    def kill(self) -> None:
        """Chaos hook (server._crash): stop workers, ABANDON queued work
        unresolved — the way a kill at a batch boundary leaves it."""
        self._stop.set()
        for lane in self.lanes:
            with lane.lock:
                lane.closed = True
                lane.cond.notify_all()
        for lane in self.lanes:
            if lane.thread is not None:
                lane.thread.join(timeout=60.0)
                lane.thread = None

    # -- admission side ----------------------------------------------------

    def active_count(self) -> int:
        with self._scale_lock:
            return self._active

    def active_lanes(self) -> List[Lane]:
        return self.lanes[:self.active_count()]

    def place(self, req) -> bool:
        """Place one admitted request: join a compatible open forming
        slot (continuous batching — the next in-flight batch), else the
        affinity lane's queue. False = the lane set is closing and cannot
        own the request (the caller rejects it; nothing is ever silently
        dropped between admission and the lane queues)."""
        sig = compat_sig(req, self.server.ladder)
        active = self.active_lanes()
        if sig is None:
            # Oversized: no batching to optimize — least-loaded active lane.
            home = min(active, key=lambda lane: len(lane.queue))  # lockset: ok — racy depth peek; any lane is correct
        else:
            with self._place_lock:
                idx = self._sig_lane.get(sig)
                if idx is None or idx >= len(active):
                    # First sight (or its lane went dormant): assign the
                    # next active lane round-robin and stick.
                    idx = self._rr % len(active)
                    self._rr += 1
                    self._sig_lane[sig] = idx
            home = active[idx]
        if self.cfg.continuous_batching and sig is not None:
            for cand in [home] + [ln for ln in active if ln is not home]:
                with cand.lock:
                    f = cand.forming
                    if (not cand.closed and f is not None and not f.sealed
                            and f.sig == sig
                            and len(f.reqs) < self.cfg.max_batch):
                        f.reqs.append(req)
                        f.note_member(req, self.cfg.cb_deadline_margin_s)
                        cand.cb_admits += 1
                        cand.cond.notify_all()
                        obs.counter("serve.cb_admits")
                        return True
        with home.lock:
            if home.closed:
                return False
            home.queue.append(req)
            depth = len(home.queue)
            obs.gauge(f"serve.lane{home.idx}.queue_depth", depth)
            home.cond.notify_all()
        # Wake idle workers: the home lane picks the request up, and at
        # steal depth a sibling may get there first. Idle workers park on
        # this one condition (not their lane cond), so every append must
        # signal it.
        with self._steal_cond:
            self._steal_cond.notify_all()
        return True

    def drain_rate(self) -> float:
        """Aggregate EWMA drain rate over the ACTIVE lanes — the
        lane-set-wide retry-after input (a single global rate
        over-estimates the wait once several lanes drain in parallel)."""
        return sum(lane.drain_rate for lane in self.active_lanes())

    # -- worker side -------------------------------------------------------

    def wait_warm(self, timeout: float = 600.0) -> bool:
        """Block until every lane finished its startup warmup (True) or
        the timeout passed. With ``lane_warmup=False`` lanes are warm by
        definition (compiles land lazily at first dispatch)."""
        deadline = time.monotonic() + timeout
        for lane in self.lanes:
            if not lane.warm.wait(max(0.0, deadline - time.monotonic())):
                return False
        return True

    def _warm_lane(self, lane: Lane) -> None:
        """Per-lane startup warmup: one dispatch per ladder rung at the
        lane's own placement, so the per-placement backend compile (jax
        compiles per device/sharding) lands HERE — inside warmup — and
        never inside a request's latency window. The Python-level
        build/warmup behind each key is still paid once process-wide
        (shared cache; racing lanes coalesce). Lanes serve the full batch
        slot (server._serve_batched pins the mesh batch bucket to
        max_batch), so one key per rung covers the steady state."""
        from gauss_tpu.serve.cache import CacheKey

        cfg = self.cfg
        for rung in self.server.ladder:
            if self._stop.is_set():
                break
            key = CacheKey(bucket_n=int(rung), nrhs=1,
                           batch=int(cfg.max_batch), dtype=cfg.dtype,
                           engine=cfg.engine,
                           refine_steps=cfg.refine_steps)
            try:
                exe = lane.cache_view.get(key, panel=cfg.panel)
                eye = np.broadcast_to(
                    np.eye(rung), (cfg.max_batch, rung, rung)).copy()
                zer = np.zeros((cfg.max_batch, rung, 1))
                with obs.span("lane_warm", lane=lane.idx, bucket_n=rung):
                    exe.solve(eye, zer,
                              placement=lane.placement_for(cfg.max_batch))
            except Exception as e:  # noqa: BLE001 — warmup must not kill serving
                obs.emit("lane", event="warm_error", lane=lane.idx,
                         bucket_n=int(rung),
                         error=f"{type(e).__name__}: {e}"[:200])

    def _worker(self, lane: Lane) -> None:
        srv = self.server
        if self.cfg.lane_warmup:
            self._warm_lane(lane)
        lane.warm.set()
        while not self._stop.is_set():
            if lane.idx == 0 and srv.config.heartbeat_path is not None:
                srv._heartbeat(srv.config.heartbeat_path)
            self._maybe_autoscale()
            if lane.idx >= self.active_count():
                # Dormant (autoscale shrink): no pulls, no steals. Our
                # queued leftovers are stolen by active lanes; placement
                # no longer targets us.
                with lane.lock:
                    lane.cond.wait(0.05)
                continue
            batch = self._next_batch(lane)
            if not batch:
                continue
            srv._depth_add(-len(batch))
            if _inject.enabled():
                # Hook point "serve.worker.dispatch" (parity with the
                # single-lane worker): injected stall = deadline pressure.
                _inject.maybe_delay("serve.worker.dispatch")
            t0 = time.perf_counter()
            served = srv._dispatch(batch, lane=lane)
            dt = time.perf_counter() - t0
            if served and dt > 0:
                inst = served / dt
                lane.drain_rate = (0.7 * lane.drain_rate + 0.3 * inst
                                   if lane.drain_rate else inst)
            if _inject.enabled():
                # Hook point "serve.server.batch": the batch boundary
                # (kind "server_kill" os._exits here — durable campaign).
                _inject.maybe_kill("serve.server.batch")

    def _next_batch(self, lane: Lane) -> Optional[list]:
        """One formed batch for ``lane``: close the published forming
        slot (waiting out its formation deadline if unfilled), seed the
        next slot from the queue head so formation overlaps this batch's
        compute, or — with an empty lane — steal from the deepest
        sibling."""
        cfg = self.cfg
        with lane.lock:
            f = lane.forming
            if f is None and lane.queue:
                f = self._open_forming(lane, lane.queue.popleft())
            if f is not None:
                self._fill_from_queue(lane, f)
                while (not self._stop.is_set() and f.sig is not None
                       and len(f.reqs) < cfg.max_batch):
                    remaining = f.deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    lane.cond.wait(min(0.005, remaining))
                    self._fill_from_queue(lane, f)
                f.sealed = True
                lane.forming = None
                batch = f.reqs
                if cfg.continuous_batching and lane.queue:
                    # The overlap that makes batching continuous: open
                    # batch k+1's slot BEFORE dispatching batch k, so
                    # admissions during k's compute join a live slot.
                    nxt = self._open_forming(lane, lane.queue.popleft())
                    self._fill_from_queue(lane, nxt)
                obs.gauge(f"serve.lane{lane.idx}.queue_depth",
                          len(lane.queue))
                return batch
        stolen = self._steal(lane)
        if stolen:
            return stolen
        with lane.lock:
            if lane.queue or lane.forming is not None:
                return None
        # Idle: sleep on the overflow condition — a sibling queue
        # reaching steal depth wakes us for an immediate steal attempt
        # (our own queue's appends wake us via the steal cond too, on
        # the next loop's own-queue check).
        with self._steal_cond:
            self._steal_cond.wait(0.02)
        return None

    def _open_forming(self, lane: Lane, head) -> _Forming:
        """Open (and publish) a forming slot seeded with ``head``. The
        formation window is the continuous-batching deadline — tightened
        per member by its request deadline — or, with continuous batching
        off, the single-lane linger: the fixed-drain discipline the A/B
        gate compares against, which lingers BLIND to member deadlines
        exactly like serve.server._drain_same_bucket always has."""
        # lockset: holds lane.lock — callers publish under the lane lock
        sig = compat_sig(head, self.server.ladder)
        cb = self.cfg.continuous_batching
        window = self.cfg.cb_window_s if cb else self.cfg.batch_linger_s
        f = _Forming(sig, time.perf_counter()
                     + (window if sig is not None else 0.0))
        f.reqs.append(head)
        if cb:
            f.note_member(head, self.cfg.cb_deadline_margin_s)
        lane.forming = f
        return f

    def _fill_from_queue(self, lane: Lane, f: _Forming) -> None:
        """Pull ``f.sig``-compatible requests from the lane's own queue
        into the slot (callers hold the lane lock). Incompatible requests
        keep their relative order at the queue front."""
        # lockset: holds lane.lock
        if f.sig is None:
            return
        cb = self.cfg.continuous_batching
        keep: deque = deque()
        while lane.queue and len(f.reqs) < self.cfg.max_batch:
            r = lane.queue.popleft()
            if compat_sig(r, self.server.ladder) == f.sig:
                f.reqs.append(r)
                if cb:
                    f.note_member(r, self.cfg.cb_deadline_margin_s)
            else:
                keep.append(r)
        lane.queue.extendleft(reversed(keep))

    def _steal(self, thief: Lane) -> Optional[list]:
        """Steal a compatible run from the tail of the deepest sibling
        queue (active or dormant). Returns a ready-to-dispatch batch —
        same sig throughout — or None when no sibling is deep enough."""
        cfg = self.cfg
        best = None
        for victim in self.lanes:
            if victim is thief:
                continue
            depth = len(victim.queue)   # lockset: ok — racy peek; confirmed under lock below
            if depth >= cfg.steal_threshold and (
                    best is None or depth > len(best.queue)):  # lockset: ok — racy victim ranking; confirmed under lock below
                best = victim
        if best is None:
            return None
        with best.lock:
            if best.closed or len(best.queue) < cfg.steal_threshold:
                return None
            take = min(max(1, len(best.queue) // 2), cfg.max_batch)
            got = [best.queue.pop()]
            sig = compat_sig(got[0], self.server.ladder)
            while (best.queue and len(got) < take
                   and compat_sig(best.queue[-1],
                                  self.server.ladder) == sig):
                got.append(best.queue.pop())
            best.stolen_out += len(got)
            depth_after = len(best.queue)
        got.reverse()                   # restore submission order
        thief.stolen_in += len(got)
        self.steals += 1
        obs.counter("serve.steals")
        obs.gauge(f"serve.lane{thief.idx}.stolen", thief.stolen_in)
        obs.emit("lane_steal", thief=thief.idx, victim=best.idx,
                 requests=len(got), victim_depth=depth_after)
        return got

    # -- autoscaling -------------------------------------------------------

    def _server_slo_firing(self) -> bool:
        live = getattr(self.server, "live", None)
        return live is not None and live.slo_firing()

    def _maybe_autoscale(self) -> None:
        """Grow the active lane count while an SLO burn-rate alert fires
        (capacity, not just shedding — the ISSUE-8 monitor driving the
        ISSUE-14 plane), shrink after a quiet period. Rate-limited; one
        step per interval so scaling never flaps batch-to-batch."""
        cfg = self.cfg
        if not cfg.autoscale:
            return
        now = time.monotonic()
        with self._scale_lock:
            if now - self._scale_last < cfg.autoscale_interval_s:
                return
            firing = self._slo_firing()
            if firing:
                self._burn_last = now
                if self._active < len(self.lanes):
                    self._active += 1
                    self._scale_last = now
                    obs.counter("serve.lane_scales")
                    obs.gauge("serve.lanes_active", self._active)
                    obs.emit("lane_scale", event="grow",
                             active=self._active, reason="slo_burn")
            elif (self._active > max(1, cfg.min_lanes)
                  and now - self._burn_last > cfg.autoscale_quiet_s):
                self._active -= 1
                self._scale_last = now
                obs.counter("serve.lane_scales")
                obs.gauge("serve.lanes_active", self._active)
                obs.emit("lane_scale", event="shrink",
                         active=self._active, reason="burn_quiet")

    # -- reporting ---------------------------------------------------------

    def stats(self) -> dict:
        """The lane-set report block (loadgen summary / meshcheck gate)."""
        return {
            "lanes": len(self.lanes),
            "active": self.active_count(),
            "width": max(1, int(self.cfg.lane_width)),
            "continuous_batching": bool(self.cfg.continuous_batching),
            "cb_window_s": self.cfg.cb_window_s,
            "steals": self.steals,
            "cb_admits": sum(lane.cb_admits for lane in self.lanes),
            "per_lane": [lane.stats() for lane in self.lanes],
        }
