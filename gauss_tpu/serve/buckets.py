"""Shape bucketing for the serving layer: the executable-cache key space.

A long-lived solver service cannot afford one XLA compile per distinct
``(n, nrhs, batch)`` it ever sees — arbitrary request shapes must collapse
onto a SMALL ladder of compiled shapes, the same move MAGMA-style batched
dense libraries make (PAPERS.md: many small systems per launch, one kernel
per size class). Three axes are bucketed:

- **System size** ``n`` rounds up to a ladder of bucket sizes. The default
  ladder is the powers-of-two multiples of :data:`core.blocked.DEFAULT_PANEL`
  (128, 256, ..., 4096) — every rung is a panel multiple, so the blocked
  factorization's own padding (:func:`core.blocked._pad_to_panel`) never
  adds a second layer of padding on top of the bucket's.
- **RHS count** ``k`` rounds up to a power of two (serving stacks RHS
  columns; ``lu_solve`` carries the k axis through its GEMMs for free).
- **Batch size** rounds up to a power of two capped by the server's
  ``max_batch``, so draining 3 queued requests reuses the batch-4
  executable instead of compiling a batch-3 one.

Padding is identity-extension, exactly the policy of
``core.blocked._pad_to_panel``: the padded diagonal is 1, padded RHS rows
are 0, so padded rows can never win a partial-pivot contest in a real
column, the padded block stays the identity through every update, and the
solution tail is exactly zero — ``unpad`` just slices ``x[:n]``.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from gauss_tpu.core.blocked import DEFAULT_PANEL

# Powers-of-two multiples of the panel width: 128 .. 4096. Past the top
# rung a request is OVERSIZED for the batched lane and routes through
# core.blocked.solve_handoff (single-chip refined or the dist engines).
DEFAULT_LADDER: Tuple[int, ...] = tuple(DEFAULT_PANEL * 2 ** i
                                        for i in range(6))


def validate_ladder(ladder: Sequence[int]) -> Tuple[int, ...]:
    """Sorted, deduplicated, all-positive ladder (ValueError otherwise)."""
    rungs = sorted(set(int(r) for r in ladder))
    if not rungs or rungs[0] < 1:
        raise ValueError(f"bucket ladder must be positive ints, got {ladder}")
    return tuple(rungs)


def bucket_for(n: int, ladder: Sequence[int] = DEFAULT_LADDER) -> int | None:
    """Smallest ladder rung >= n, or None when ``n`` overflows the ladder
    (the caller routes those through solve_handoff instead of batching)."""
    if n < 1:
        raise ValueError(f"system size must be >= 1, got {n}")
    for rung in ladder:
        if n <= rung:
            return rung
    return None


def pow2_bucket(k: int, cap: int | None = None) -> int:
    """Smallest power of two >= k (optionally capped)."""
    if k < 1:
        raise ValueError(f"count must be >= 1, got {k}")
    b = 1
    while b < k:
        b *= 2
    if cap is not None:
        b = min(b, cap)
    return b


def pad_system(a: np.ndarray, b: np.ndarray, bucket_n: int,
               nrhs_bucket: int | None = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Embed ``(a, b)`` in an identity-extended ``bucket_n`` system.

    ``a`` -> top-left of an identity-padded (bucket_n, bucket_n) matrix;
    ``b`` (n,) or (n, k) -> zero-extended (bucket_n, nrhs_bucket), the k
    axis zero-padded up to the RHS bucket. Returns host arrays in ``a``'s
    dtype; the caller stacks them into the batched device operand.
    """
    a = np.asarray(a)
    b = np.asarray(b)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    if b.shape[0] != n:
        raise ValueError(f"rhs rows {b.shape[0]} != system size {n}")
    if n > bucket_n:
        raise ValueError(f"system size {n} exceeds bucket {bucket_n}")
    b2 = b[:, None] if b.ndim == 1 else b
    if b2.ndim != 2:
        raise ValueError(f"b must be (n,) or (n, k), got {b.shape}")
    k = b2.shape[1]
    kb = k if nrhs_bucket is None else nrhs_bucket
    if k > kb:
        raise ValueError(f"nrhs {k} exceeds rhs bucket {kb}")
    ap = np.eye(bucket_n, dtype=a.dtype)
    ap[:n, :n] = a
    bp = np.zeros((bucket_n, kb), dtype=b2.dtype)
    bp[:n, :k] = b2
    return ap, bp


def unpad_solution(x: np.ndarray, n: int, k: int,
                   was_vector: bool) -> np.ndarray:
    """Slice the original system's solution back out of a padded one."""
    x = np.asarray(x)
    out = x[:n, :k]
    return out[:, 0] if was_vector else out
