"""Span-tree diff between two recorded runs: WHERE did the time go?

``python -m gauss_tpu.obs.doctor RUN_A RUN_B [--json] [--top N]``

The ROADMAP's open perf item is exactly this question: the n=2048 solve
was 1.476 ms in round 3 and 2.251 ms in round 5 — which PHASE absorbed the
+0.775 ms? Eyeballing two flat profiles answers it badly (ten numbers each,
mental subtraction); this tool answers it directly: align the two runs'
leaf-span profiles by phase name, attribute the wall-time delta to phases,
and sort by **regression contribution** (largest slowdown first), flagging
phases that only exist on one side (a hook compiled in, a phase renamed).

``RUN_A`` / ``RUN_B`` are metrics JSONL paths, optionally suffixed
``:RUN_ID`` to pick a run out of a multi-run file. A is the reference
(before / fast), B the candidate (after / slow); positive delta = B is
slower there.

A committed example lives under ``reports/``: ``doctor_r3_vs_r5.json`` is
the diff of the seeded round-3-like vs round-5-like streams
(``doctor_r3like.jsonl`` / ``doctor_r5like.jsonl``), showing the host-
stepped hook threading — not the factor math — absorbing the regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from gauss_tpu.obs import registry
from gauss_tpu.obs.summarize import _runs, flat_profile


def parse_target(target: str) -> Tuple[str, Optional[str]]:
    """Split ``path[:run_id]``; tolerates Windows-style drive colons by
    only treating the suffix as a run id when the prefix is a real file."""
    if ":" in target:
        path, _, rid = target.rpartition(":")
        if path and os.path.exists(path):
            return path, rid
    return target, None


def load_profile(target: str) -> Dict[str, Any]:
    """Read one diff side: the flat profile plus identity metadata."""
    path, rid = parse_target(target)
    events = registry.read_events(path)
    runs = _runs(events)
    if not runs:
        raise ValueError(f"no runs found in '{path}'")
    rid = rid or runs[0]
    if rid not in runs:
        raise ValueError(f"run '{rid}' not in '{path}'; runs: "
                         f"{', '.join(runs)}")
    evs = [ev for ev in events if ev.get("run") == rid]
    prof = flat_profile(evs)
    start = next((ev for ev in evs if ev.get("type") == "run_start"), {})
    return {"path": path, "run": rid, "tool": start.get("tool"),
            "profile": prof}


def profile_from_phases(phases: Dict[str, float], path: str = "<phases>",
                        tool: Optional[str] = None) -> Dict[str, Any]:
    """Adapt a flat ``{phase: seconds}`` map (e.g. a bench record's
    ``phases_s``) into the :func:`load_profile` shape so it can ride
    :func:`diff_profiles` / :func:`format_diff` — the auto-attribution
    path ``bench --regress`` takes when a ratchet fails: diff the fresh
    record's phases against the best committed prior epoch's and name the
    guilty phase, no recorded span stream required."""
    ph = {str(k): {"seconds": float(v), "calls": 1}
          for k, v in (phases or {}).items()
          if isinstance(v, (int, float))}
    return {"path": path, "run": None, "tool": tool,
            "profile": {"phases": ph,
                        "span_total_s": sum(e["seconds"]
                                            for e in ph.values()),
                        "wall_s": None, "lanes": {}}}


def diff_profiles(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """The span-tree diff document (the ``--json`` payload and the text
    renderer's single source). Phases sorted by delta descending — the
    top line IS the regression's biggest contributor."""
    pa, pb = a["profile"], b["profile"]
    names = sorted(set(pa["phases"]) | set(pb["phases"]))
    span_delta = pb["span_total_s"] - pa["span_total_s"]
    wall_a, wall_b = pa.get("wall_s"), pb.get("wall_s")
    wall_delta = (wall_b - wall_a
                  if isinstance(wall_a, (int, float))
                  and isinstance(wall_b, (int, float)) else None)
    phases: List[Dict[str, Any]] = []
    for name in names:
        ea = pa["phases"].get(name, {"seconds": 0.0, "calls": 0})
        eb = pb["phases"].get(name, {"seconds": 0.0, "calls": 0})
        delta = eb["seconds"] - ea["seconds"]
        entry = {
            "phase": name,
            "a_s": round(ea["seconds"], 6), "b_s": round(eb["seconds"], 6),
            "delta_s": round(delta, 6),
            "share_of_delta": (round(delta / span_delta, 4)
                               if span_delta else None),
            "a_calls": ea["calls"], "b_calls": eb["calls"],
            "a_per_call_s": (round(ea["seconds"] / ea["calls"], 9)
                             if ea["calls"] else None),
            "b_per_call_s": (round(eb["seconds"] / eb["calls"], 9)
                             if eb["calls"] else None),
            "only_in": ("b" if not ea["calls"] and eb["calls"] else
                        "a" if ea["calls"] and not eb["calls"] else None),
        }
        phases.append(entry)
    phases.sort(key=lambda p: -p["delta_s"])
    return {
        "kind": "span_diff",
        "a": {k: a[k] for k in ("path", "run", "tool")},
        "b": {k: b[k] for k in ("path", "run", "tool")},
        "a_span_total_s": round(pa["span_total_s"], 6),
        "b_span_total_s": round(pb["span_total_s"], 6),
        "span_delta_s": round(span_delta, 6),
        "a_wall_s": wall_a, "b_wall_s": wall_b,
        "wall_delta_s": (round(wall_delta, 6)
                         if wall_delta is not None else None),
        "phases": phases,
    }


def _ms(v) -> str:
    return "-" if not isinstance(v, (int, float)) else f"{v * 1e3:10.3f}"


def format_diff(diff: Dict[str, Any], top: Optional[int] = None) -> str:
    da, db = diff["a"], diff["b"]
    sd = diff["span_delta_s"]
    lines = [
        f"span-tree diff: A={da['path']} (run {da['run']}) -> "
        f"B={db['path']} (run {db['run']})",
        f"  span totals: {diff['a_span_total_s'] * 1e3:.3f} -> "
        f"{diff['b_span_total_s'] * 1e3:.3f} ms  "
        f"(delta {sd * 1e3:+.3f} ms)"
        + (f"; wall {diff['wall_delta_s'] * 1e3:+.3f} ms"
           if diff.get("wall_delta_s") is not None else ""),
        "",
        "   delta_ms     %delta        A_ms        B_ms   calls A->B  phase",
    ]
    shown = diff["phases"][:top] if top else diff["phases"]
    for p in shown:
        share = (f"{100 * p['share_of_delta']:7.1f}%"
                 if p["share_of_delta"] is not None else "       -")
        note = f"  [only in {p['only_in'].upper()}]" if p["only_in"] else ""
        lines.append(
            f" {p['delta_s'] * 1e3:+10.3f}   {share}  {_ms(p['a_s'])}  "
            f"{_ms(p['b_s'])}   {p['a_calls']:4d}->{p['b_calls']:<4d}"
            f"  {p['phase']}{note}")
    hidden = len(diff["phases"]) - len(shown)
    if hidden > 0:
        lines.append(f"  ... {hidden} more phase(s); rerun with --top 0")
    worst = next((p for p in diff["phases"] if p["delta_s"] > 0), None)
    if worst is not None and sd > 0:
        lines.append("")
        lines.append(
            f"  biggest regression contributor: {worst['phase']} "
            f"(+{worst['delta_s'] * 1e3:.3f} ms"
            + (f", {100 * worst['share_of_delta']:.0f}% of the delta"
               if worst["share_of_delta"] is not None else "") + ")")
    return "\n".join(lines)


def forbidden_phases(diff: Dict[str, Any], forbid: List[str]
                     ) -> List[Dict[str, Any]]:
    """The CANDIDATE-side (B) phases from ``forbid`` that actually ran —
    the CI gate's payload. A forbidden name matches a phase exactly or as
    a dotted/segmented prefix (``host_group_step`` catches
    ``host_group_step.factor`` too)."""
    hits = []
    for p in diff["phases"]:
        name = p["phase"]
        for f in forbid:
            if p["b_calls"] and (name == f or name.startswith(f + ".")
                                 or name.startswith(f + "/")):
                hits.append(p)
                break
    return hits


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.doctor",
        description="Diff two recorded runs' span trees: attribute the "
                    "wall-time delta to phases, sorted by regression "
                    "contribution.")
    p.add_argument("run_a", help="reference stream: path[:run_id]")
    p.add_argument("run_b", help="candidate stream: path[:run_id]")
    p.add_argument("--json", action="store_true",
                   help="emit the full diff document as JSON")
    p.add_argument("--top", type=int, default=12,
                   help="phases to show in text mode (0 = all; default 12)")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="also write the JSON diff here")
    p.add_argument("--forbid", default=None, metavar="PHASES",
                   help="comma-separated phase names that must NOT appear "
                        "in the candidate (B) stream; exit 1 when any ran. "
                        "The plain-path CI gate: host_group_step/hook_sync "
                        "leaves reappearing on the hooks-off path is the "
                        "exact regression shape PRs 4-5 introduced and "
                        "PR 10 reclaimed (reports/doctor_r3_vs_r5.json)")
    args = p.parse_args(argv)
    try:
        a = load_profile(args.run_a)
        b = load_profile(args.run_b)
    except (OSError, ValueError) as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2
    diff = diff_profiles(a, b)
    if args.out:
        parent = os.path.dirname(args.out)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(diff, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(diff, indent=1, sort_keys=True))
    else:
        print(format_diff(diff, args.top or None))
    if args.forbid:
        forbid = [f.strip() for f in args.forbid.split(",") if f.strip()]
        hits = forbidden_phases(diff, forbid)
        if hits:
            for h in hits:
                print(f"doctor: FORBIDDEN phase '{h['phase']}' ran "
                      f"{h['b_calls']} time(s) ({h['b_s'] * 1e3:.3f} ms) in "
                      f"the candidate stream — a host-stepped/hook leaf is "
                      f"back on the plain path", file=sys.stderr)
            return 1
        print(f"doctor: forbidden-phase gate clean "
              f"({', '.join(forbid)} absent from candidate)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
