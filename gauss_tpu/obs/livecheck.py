"""``make live-check`` — the live telemetry plane's end-to-end CI gate.

``python -m gauss_tpu.obs.livecheck [--requests N] [--summary-json PATH]``

Four legs against ONE running ``SolverServer`` with the live plane on
(ephemeral port), all CPU, exit 2 on any assertion failure:

1. **Scrape/report totals match.** Drive a closed-loop loadgen mix with
   zero warmup, scrape ``/metrics``, and assert the Prometheus counter
   totals agree EXACTLY with the loadgen's final report — requests
   submitted/served (verified), rejected (shed), expired, failed, retried.
   Two independent folds of the same stream (live ring counters vs
   client-side results) must not drift.
2. **Per-request traces.** Every terminal status in the recorded stream
   folds into exactly one request trace (obs.requesttrace invariant), and
   the tree count equals the terminal count.
3. **On-demand /trace.** Arm a capture over HTTP while traffic flows;
   the returned Chrome-trace JSON must contain a ``serve_batch_solve``
   span carrying request traces.
4. **SLO fire/clear.** Force a deadline-violation burst (requests whose
   deadline is already unmeetable) and assert the burn-rate alert FIRES;
   then let the short window drain and drive good traffic until it
   CLEARS — both transitions must appear as obs ``alert`` events.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np


def _fail(msg: str) -> None:
    print(f"live-check: FAIL: {msg}", file=sys.stderr)
    raise SystemExit(2)


def _ok(msg: str) -> None:
    print(f"live-check: ok: {msg}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.livecheck",
        description="End-to-end gate for the live telemetry plane "
                    "(/metrics totals, request traces, on-demand /trace, "
                    "SLO alert fire/clear).")
    p.add_argument("--requests", type=int, default=40)
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--burst", type=int, default=10,
                   help="deadline-violation burst size for the SLO leg")
    p.add_argument("--clear-timeout", type=float, default=30.0)
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    args = p.parse_args(argv)

    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import requesttrace
    from gauss_tpu.obs import top as _top
    from gauss_tpu.obs.slo import SLO
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    # Small windows so the fire->clear cycle fits in CI seconds; the burn
    # math is window-size independent.
    slo = SLO(name="serve_ok", objective=0.95, short_window_s=1.5,
              long_window_s=8.0, fire_burn=2.0, clear_burn=1.0, min_count=4)
    serve_cfg = ServeConfig(ladder=(16, 32), max_batch=4, panel=16,
                            refine_steps=1, verify_gate=1e-4,
                            live_port=0, slos=(slo,))
    lg = LoadgenConfig(mix="random:12*2,random:24,internal:20",
                       requests=args.requests, warmup=0, mode="closed",
                       concurrency=4, seed=args.seed, serve=serve_cfg)

    summary = {"kind": "live_check"}
    with obs.run(metrics_out=args.metrics_out, tool="live_check") as rec:
        with SolverServer(serve_cfg) as server:
            url = server.live_url
            _ok(f"live endpoint up at {url}")

            # -- leg 1: loadgen vs /metrics totals -------------------------
            report = run_load(server, lg)
            counts = report["counts"]
            pairs = [
                ("gauss_serve_served_total", counts.get("ok", 0),
                 "served (verified)"),
                ("gauss_serve_rejected_total", counts.get("rejected", 0),
                 "rejected (shed)"),
                ("gauss_serve_expired_total", counts.get("expired", 0),
                 "expired (shed)"),
                ("gauss_serve_failed_total", counts.get("failed", 0),
                 "failed"),
                ("gauss_serve_retries_total", report.get("retries", 0),
                 "retries"),
            ]
            # A client unblocks at resolve(), a hair before the worker's
            # counter increment lands — scrape with a short bounded retry
            # so the comparison reads the settled totals, not the race.
            mismatch = None
            for _ in range(25):
                samples = _top.parse_metrics(urllib.request.urlopen(
                    f"{url}/metrics", timeout=10).read().decode())
                flat = {name: v for name, labels, v in samples
                        if not labels}
                mismatch = next(
                    ((m, flat.get(m, 0), want, label)
                     for m, want, label in pairs
                     if flat.get(m, 0) != want), None)
                if mismatch is None:
                    break
                time.sleep(0.1)
            if mismatch is not None:
                metric, got, want, label = mismatch
                _fail(f"/metrics {metric}={got} but the loadgen "
                      f"report says {label}={want}")
            if report["incorrect"]:
                _fail(f"{report['incorrect']} INCORRECT solution(s)")
            _ok(f"scrape totals match the loadgen report exactly "
                f"({counts.get('ok', 0)} served, "
                f"{counts.get('rejected', 0)} rejected, "
                f"{counts.get('expired', 0)} expired, "
                f"{counts.get('failed', 0)} failed, "
                f"{report.get('retries', 0)} retries)")
            summary["loadgen"] = {k: report[k] for k in
                                  ("counts", "retries", "incorrect")}

            # -- leg 3 (concurrent with traffic): on-demand /trace ---------
            rng = np.random.default_rng(args.seed + 1)
            captured = {}

            def _grab():
                with urllib.request.urlopen(
                        f"{url}/trace?batches=1&timeout=20",
                        timeout=30) as resp:
                    captured["doc"] = json.loads(resp.read().decode())

            t = threading.Thread(target=_grab)
            t.start()
            time.sleep(0.2)  # let the capture arm before traffic flows
            for _ in range(4):
                n = 12
                a = rng.standard_normal((n, n))
                a[np.arange(n), np.arange(n)] += float(n)
                server.solve(a, rng.standard_normal(n))
            t.join(timeout=30)
            doc = captured.get("doc")
            if not doc:
                _fail("/trace capture returned nothing")
            names = {ev.get("name") for ev in doc.get("traceEvents", [])
                     if ev.get("ph") == "X"}
            if "serve_batch_solve" not in names:
                _fail(f"/trace capture has no serve_batch_solve span "
                      f"(spans: {sorted(names)})")
            _ok(f"on-demand /trace captured "
                f"{sum(1 for ev in doc['traceEvents'] if ev.get('ph') == 'X')}"
                f" span(s) from the running server")

            # -- leg 4: SLO alert fires, then clears -----------------------
            mon = server.live.slos[0]
            for _ in range(args.burst):
                n = 12
                a = rng.standard_normal((n, n))
                a[np.arange(n), np.arange(n)] += float(n)
                h = server.submit(a, rng.standard_normal(n),
                                  deadline_s=1e-6)
                try:
                    h.result(timeout=30)
                except TimeoutError:
                    _fail("deadline-burst request hung")
            if not mon.firing:
                _fail(f"SLO alert did not fire after {args.burst} "
                      f"deadline violations (burn "
                      f"short/long = {mon.burn_rates()})")
            _ok(f"SLO alert FIRED after the violation burst "
                f"(worst burn {mon.worst_burn:.1f}x)")
            time.sleep(slo.short_window_s + 0.2)  # let the bad obs age out
            deadline = time.monotonic() + args.clear_timeout
            while mon.firing and time.monotonic() < deadline:
                n = 12
                a = rng.standard_normal((n, n))
                a[np.arange(n), np.arange(n)] += float(n)
                server.solve(a, rng.standard_normal(n))
                time.sleep(0.05)
            if mon.firing:
                _fail(f"SLO alert did not clear within "
                      f"{args.clear_timeout}s of good traffic")
            _ok(f"SLO alert CLEARED under good traffic "
                f"({mon.alerts} fire(s), {mon.clears} clear(s))")
            summary["slo"] = mon.status()

        # -- leg 2: per-request trace invariant (whole recorded stream) ----
        terminal = [ev for ev in rec.events
                    if ev.get("type") == "serve_request"
                    and ev.get("status") in requesttrace.TERMINAL_STATUSES]
        trees = requesttrace.request_traces(rec.events)
        problems = requesttrace.check_traces(trees)
        if problems:
            _fail("; ".join(problems[:5]))
        if len(trees) != len(terminal):
            _fail(f"{len(terminal)} terminal statuses but {len(trees)} "
                  f"request traces — identities dropped somewhere")
        alerts = [ev for ev in rec.events if ev.get("type") == "alert"]
        if not any(ev.get("state") == "firing" for ev in alerts) \
                or not any(ev.get("state") == "clear" for ev in alerts):
            _fail(f"alert events missing a transition: {alerts}")
        _ok(f"every terminal status has exactly one request trace "
            f"({len(trees)} traces); alert fire+clear in the stream")
        summary["traces"] = len(trees)

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")
    print("live-check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
