"""SLO definitions + multi-window burn-rate alerts over the live windows.

A service promising "99% of requests verified-correct under deadline"
needs more than a violation counter: it needs to know how FAST it is
spending its error budget. The standard answer (the SRE-workbook
multi-window multi-burn-rate pattern) is encoded here:

- an :class:`SLO` declares the objective (fraction of requests that must be
  *good*) and which terminal statuses count *bad* (default: ``expired`` —
  the deadline was missed — and ``failed``; a shed/rejected request is
  load-control, not a broken promise, unless the SLO says otherwise);
- **burn rate** over a window = (bad fraction in the window) / (allowed bad
  fraction). Burn 1.0 means spending the budget exactly as fast as the SLO
  allows; burn 10 means the budget dies in a tenth of the period.
- an alert **fires** only when BOTH a short and a long window burn above
  ``fire_burn`` — the short window makes detection fast, the long window
  keeps one unlucky batch from paging — and **clears** only when the short
  window burns below ``clear_burn`` (< fire_burn: hysteresis, so the alert
  cannot flap at the threshold).

:class:`SLOMonitor` evaluates this incrementally per observation (O(window)
worst case, on small rings), emits nothing itself — the aggregator turns
transitions into obs ``alert`` events — and renders its state for
``/metrics`` (`gauss_slo_burn_rate{window=...}`) and ``/slo``.

The ``slo_report`` summary (:func:`slo_report`) is the post-run fold the
loadgen exports and ``obs.regress`` ingests (``kind: slo_report``): the
violation rate, the worst burn rate seen, and the alert count gate in CI
exactly like latency percentiles do.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

#: Statuses every monitor treats as a terminal observation; anything else
#: (e.g. a queued progress event) is ignored.
TERMINAL_STATUSES = ("ok", "rejected", "expired", "failed", "cancelled")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One service-level objective over terminal request statuses."""

    name: str = "serve_ok"
    #: fraction of counted requests that must be good (0.99 -> 1% budget)
    objective: float = 0.99
    #: detection window (seconds): fast to rise, fast to clear
    short_window_s: float = 60.0
    #: confirmation window (seconds): one bad batch cannot page alone
    long_window_s: float = 300.0
    #: both windows must burn at/above this to fire
    fire_burn: float = 2.0
    #: the short window must burn at/below this to clear (hysteresis)
    clear_burn: float = 1.0
    #: statuses that spend the error budget
    bad_statuses: Tuple[str, ...] = ("expired", "failed")
    #: statuses excluded from the denominator entirely (cancelled requests
    #: say nothing about the service; rejected ones are load control)
    ignored_statuses: Tuple[str, ...] = ("cancelled",)
    #: observations the short window needs before it may fire (keeps the
    #: very first bad request of a quiet service from burning "infinity")
    min_count: int = 4

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got "
                             f"{self.objective}")
        if self.short_window_s >= self.long_window_s:
            raise ValueError("short_window_s must be < long_window_s")
        if self.clear_burn >= self.fire_burn:
            raise ValueError("clear_burn must be < fire_burn (hysteresis)")


def default_serving_slo() -> SLO:
    """The serving default: 99% of requests terminate ok (verified under
    deadline — the server's verify gate and deadline shedding define ok)."""
    return SLO()


class SLOMonitor:
    """Incremental burn-rate evaluation + alert state for one :class:`SLO`.

    Not internally locked — the owning aggregator serializes ``observe``.
    """

    def __init__(self, slo: SLO, capacity: int = 4096):
        from gauss_tpu.obs.live import RollingWindow

        self.slo = slo
        # one ring, horizon = the long window; the short window filters by t
        self._obs = RollingWindow(capacity=capacity,
                                  horizon_s=slo.long_window_s)
        self.firing = False
        self.alerts = 0              # fire transitions (all-time)
        self.clears = 0              # clear transitions (all-time)
        self.good = 0                # all-time counted good
        self.bad = 0                 # all-time counted bad
        self.worst_burn = 0.0        # worst short-window burn seen
        self._last = (0.0, 0.0)      # last (short, long) burn rates

    def _burn(self, horizon_s: float, now: float) -> float:
        items = self._obs.items(now=now, horizon_s=horizon_s)
        if not items:
            return 0.0
        bad = sum(v for _, v in items)
        frac = bad / len(items)
        return frac / (1.0 - self.slo.objective)

    def burn_rates(self, now: Optional[float] = None) -> Tuple[float, float]:
        now = time.monotonic() if now is None else now
        return (self._burn(self.slo.short_window_s, now),
                self._burn(self.slo.long_window_s, now))

    def observe(self, status: str, now: Optional[float] = None,
                ) -> Optional[Dict[str, Any]]:
        """Count one terminal status; returns the alert-transition payload
        (``state="firing"`` / ``"clear"``) when this observation crossed a
        threshold, else None."""
        s = self.slo
        if status in s.ignored_statuses or status not in TERMINAL_STATUSES:
            return None
        now = time.monotonic() if now is None else now
        bad = status in s.bad_statuses
        self._obs.add(1.0 if bad else 0.0, t=now)
        if bad:
            self.bad += 1
        else:
            self.good += 1
        short, long_ = self.burn_rates(now)
        self._last = (short, long_)
        self.worst_burn = max(self.worst_burn, short)
        in_window = len(self._obs.items(now=now, horizon_s=s.short_window_s))
        if (not self.firing and in_window >= s.min_count
                and short >= s.fire_burn and long_ >= s.fire_burn):
            self.firing = True
            self.alerts += 1
            return self._transition("firing", short, long_)
        if self.firing and short <= s.clear_burn:
            self.firing = False
            self.clears += 1
            return self._transition("clear", short, long_)
        return None

    def _transition(self, state: str, short: float, long_: float,
                    ) -> Dict[str, Any]:
        return {"slo": self.slo.name, "state": state,
                "objective": self.slo.objective,
                "burn_short": round(short, 4), "burn_long": round(long_, 4),
                "fire_burn": self.slo.fire_burn,
                "clear_burn": self.slo.clear_burn,
                "windows_s": [self.slo.short_window_s,
                              self.slo.long_window_s]}

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The monitor's full render (the ``/slo`` payload and the
        per-SLO ``/metrics`` lines)."""
        short, long_ = self.burn_rates(now)
        counted = self.good + self.bad
        return {"name": self.slo.name, "objective": self.slo.objective,
                "firing": self.firing, "alerts": self.alerts,
                "clears": self.clears,
                "burn_short": round(short, 4), "burn_long": round(long_, 4),
                "worst_burn": round(self.worst_burn, 4),
                "good": self.good, "bad": self.bad,
                "violation_rate": (round(self.bad / counted, 6)
                                   if counted else 0.0),
                "windows_s": [self.slo.short_window_s,
                              self.slo.long_window_s],
                "fire_burn": self.slo.fire_burn,
                "clear_burn": self.slo.clear_burn}


def slo_report(monitors: List[SLOMonitor], **meta) -> Dict[str, Any]:
    """Fold monitor states into the regress-ingestable summary
    (``kind: slo_report``): per-SLO status plus the headline numbers —
    overall violation rate, worst burn rate, alert count."""
    statuses = [m.status() for m in monitors]
    good = sum(s["good"] for s in statuses)
    bad = sum(s["bad"] for s in statuses)
    counted = good + bad
    return {
        "kind": "slo_report",
        "slos": statuses,
        "requests_counted": counted,
        "violations": bad,
        "violation_rate": round(bad / counted, 6) if counted else 0.0,
        "worst_burn_rate": max((s["worst_burn"] for s in statuses),
                               default=0.0),
        "alerts": sum(s["alerts"] for s in statuses),
        "clears": sum(s["clears"] for s in statuses),
        **meta,
    }


def history_records(summary: Dict[str, Any]) -> List[Tuple[str, float, str]]:
    """The (metric, value, unit) pairs an slo_report contributes to the
    regression history. Regress gates the slow/bad side, so all three rise
    with degradation: violation rate, worst burn, alert count."""
    out: List[Tuple[str, float, str]] = []
    vr = summary.get("violation_rate")
    if isinstance(vr, (int, float)) and vr > 0:
        out.append(("slo/violation_rate", float(vr), "ratio"))
    wb = summary.get("worst_burn_rate")
    if isinstance(wb, (int, float)) and wb > 0:
        out.append(("slo/worst_burn", float(wb), "x"))
    al = summary.get("alerts")
    if isinstance(al, (int, float)) and al > 0:
        out.append(("slo/alerts", float(al), "count"))
    return out
