"""Benchmark-regression sentinel: baselines with a noise band, as a gate.

``python -m gauss_tpu.obs.regress {ingest|check|report} ...``

The unexplained 49% r3->r4 headline swing took a full manual bisection to
classify as tunnel-epoch noise (docs/BENCH_STABILITY.md): BENCH_r03's
1.476 ms was a favorable epoch, not faster code, and the records r1/r2/r4/r5
cluster at ~2.1-2.3 ms. This module encodes that decode key as an automated
gate:

- **History** is an append-only JSONL (``reports/history.jsonl``, seeded
  from the committed BENCH_r01-r05 driver records): one line per
  measurement — ``{"metric", "value", "unit", "source", "kind"}``.
  Ingestable sources: BENCH driver records (the ``parsed`` dict), bench-grid
  ``--json`` cell arrays, and obs JSONL streams (``cell`` events) — only
  VERIFIED cells enter history; a FAILED cell's 0.0 s must never become a
  baseline.
- **Baseline** per metric: the MEDIAN across epochs (robust to one lucky or
  unlucky epoch — exactly how r3 must not drag the baseline down) plus a
  noise band. The slow-side threshold is
  ``median * max(band, 1 + 3*MAD/median)``: the configured relative band
  (default 1.2 — the slope protocol's documented round-to-round spread is
  ~±10%) widened when the recorded scatter says the metric is noisier.
- **Verdict** per checked value: ``ok`` (within band), ``fast`` (below
  median — never flagged: a favorable epoch is not a regression),
  ``out-of-band`` (exit 1), or ``no-baseline`` (fewer than --min-samples
  epochs; informational). Out-of-band verdicts carry the epoch decode key:
  up to the documented 1.5x epoch-drift ceiling the report says "confirm
  with a same-epoch A/B before blaming code"; beyond it, "likely a code
  regression".

Applied to the committed history: r4 checked against r1-r3 is 1.08x the
median — in band, classified as epoch noise at first occurrence instead of
after a manual bisection — while an injected 30% slowdown exceeds the band
and exits nonzero (both asserted by tests/test_obs_dist.py).

CI wiring: ``make obs-check`` gates on the committed records;
``bench.py --regress`` gates a fresh headline; ``gauss-bench-grid
--regress-check`` gates every verified cell.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

DEFAULT_BAND = 1.2        # slow-side relative tolerance vs the median
EPOCH_DRIFT_CEILING = 1.5  # documented epoch envelope (BENCH_STABILITY.md)
MIN_SAMPLES = 3

#: Hard ratchet records: the best COMMITTED value per metric, gated by
#: ``evaluate_ratchet`` (used by ``bench.py --regress`` and ``check
#: --ratchet`` in CI). Unlike the median baseline — which a few slow
#: epochs can drag upward — a ratchet value only ever moves DOWN: update
#: it when a round beats it, never because regressing became normal. The
#: 1.476 ms n=2048 record is BENCH_r03 (round 3, ≈345x the reference CPU
#: baseline); the refined record is BENCH_r04's 2.647 ms, gated since the
#: PR-10 reclaim so the double-single path ratchets too.
RATCHET_BASELINES = {"gauss_n2048_wallclock": 0.001476,
                     "gauss_n2048_wallclock:refined": 0.002647,
                     # The THROUGHPUT record (ISSUE 11, bench.throughput):
                     # best committed batched seconds-per-solve through
                     # the serve executables on the CPU proxy (batch 8,
                     # refine_steps 1, 3 seeded epochs in history.jsonl).
                     # Like the latency record: only ever moves DOWN.
                     "tput:float32/n256/b8/s_per_solve": 0.009319,
                     "tput:float32/n1024/b8/s_per_solve": 0.332399,
                     "tput:float32/n2048/b8/s_per_solve": 1.430897,
                     # The MULTI-LANE record (ISSUE 14, bench.throughput
                     # --lanes 4): 4 concurrent device-pinned dispatch
                     # threads through ONE shared executable, best of 3
                     # committed epochs on the 1-core CPU proxy — which
                     # measures dispatch pipelining, not MXU scaling, so
                     # the value sits at the single-lane record, not 4x
                     # under it; the ratchet guards the dispatch path
                     # from regressing. Generic ceiling (sub-100ms legs
                     # see the documented scheduler jitter).
                     "tput:float32/n256/b8/l4/s_per_solve": 0.010606,
                     # The FLIGHT-RECORDER overhead record (ISSUE 16,
                     # obs.flightcheck): best committed flight-ON
                     # seconds-per-request through a recording server on
                     # the CPU proxy (best-of-2 passes, warm cache, 3
                     # seeded epochs in history.jsonl). The always-on
                     # ring getting more expensive can only ratchet DOWN;
                     # sub-ms dispatches see the documented scheduler
                     # jitter, so the generic 1.5x ceiling applies (no
                     # RATCHET_CEILINGS entry on purpose).
                     "flight:ring_s_per_request": 0.000466}
#: A fresh headline worse than ratchet * this ceiling fails the gate even
#: when the median band would wave it through (the default ceiling reuses
#: the documented epoch-drift envelope: beyond 1.5x the best-ever epoch,
#: the slowdown cannot be tunnel noise).
RATCHET_MAX_RATIO = EPOCH_DRIFT_CEILING
#: Per-metric TIGHTENED ceilings (PR-10 reclaim, ISSUE 10 acceptance):
#: with the fused panel+trailing kernel, end-to-end buffer donation, and
#: the compiled-out-hooks plain path in the tree, the r5-class 1.525x
#: "hooks tax" regression must FAIL the gate instead of hiding just under
#: the generic 1.5x epoch envelope. 1.35x still clears every committed
#: healthy epoch of the record round's code class (r1/r2 at 1.38-1.42x
#: were PRE-record code; the reclaimed path's unlucky epochs are expected
#: at or under ~1.3x best) — anything past it is a code regression, and
#: BENCH_STABILITY.md's same-epoch A/B protocol is the appeal path.
RATCHET_CEILINGS = {"gauss_n2048_wallclock": 1.35,
                    # Throughput-record ceilings (ISSUE 11): the large
                    # legs' committed epochs sit within ~2-3% of the best
                    # (pure local CPU, no tunnel), so a 1.4x excursion is
                    # code, not noise. n=256's sub-100ms dispatches see
                    # more scheduler jitter (25% observed epoch spread) —
                    # it keeps the generic 1.5x envelope via
                    # RATCHET_MAX_RATIO (no entry on purpose; the median
                    # band remains its day-to-day gate).
                    "tput:float32/n1024/b8/s_per_solve": 1.4,
                    "tput:float32/n2048/b8/s_per_solve": 1.4}


def default_history_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "reports", "history.jsonl")


def _record(metric: str, value, source: str, kind: str,
            unit: str = "s", **meta) -> Optional[Dict[str, Any]]:
    if not isinstance(value, (int, float)) or not value > 0:
        return None
    rec = {"metric": metric, "value": float(value), "unit": unit,
           "source": os.path.basename(os.fspath(source)), "kind": kind}
    rec.update({k: v for k, v in meta.items() if v is not None})
    return rec


def _cell_metric(cell: Dict[str, Any]) -> str:
    name = (f"cell:{cell.get('suite')}/{cell.get('key')}/"
            f"{cell.get('backend')}")
    if cell.get("span") == "device":
        name += "@device"
    # The --dtype column (bench.grid): lowered-precision cells are their
    # own metrics, so a bf16 epoch can never drag an f32 baseline (and
    # vice versa). Absent/float32 keeps every pre-existing metric name.
    dtype = cell.get("dtype")
    if dtype and dtype != "float32":
        name += f"@{dtype}"
    return name


def ingest_file(path) -> List[Dict[str, Any]]:
    """Parse one artifact into history records. Detects, in order: an obs
    JSONL stream (``cell`` events), a BENCH driver record (``parsed`` dict
    or the bare bench.py output dict), and a bench-grid ``--json`` cell
    array. Unverified cells are dropped — a FAILED cell's 0.0 seconds must
    never become a baseline."""
    text = open(os.fspath(path)).read()
    records: List[Dict[str, Any]] = []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and doc.get("type"):
        # A one-line obs stream parses as a plain dict; the "type" stamp
        # marks it an event, not a BENCH record — route to the JSONL path.
        doc = None
    if doc is None:  # JSONL: an obs event stream
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("type") == "cell" and ev.get("verified"):
                rec = _record(_cell_metric(ev), ev.get("seconds"), path,
                              "cell", run=ev.get("run"))
                if rec:
                    records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "serve_loadgen":
        # A gauss-serve --summary-json report: the serving layer's
        # throughput/latency enter the same history the solve benchmarks
        # gate on. Metric derivation lives with the loadgen (single
        # source); imported lazily so reading BENCH records never pulls
        # the serving stack (or jax) into this module.
        from gauss_tpu.serve.loadgen import history_records

        for metric, value in history_records(doc):
            rec = _record(metric, value, path, "serve")
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "slo_report":
        # A gauss-serve --slo-json report (or the nested "slo" dict of a
        # live-plane loadgen summary, exported standalone): violation rate,
        # worst burn rate, and alert count enter history so an SLO-health
        # regression — the service spending its error budget faster —
        # gates in CI exactly like a latency regression. Derivation lives
        # with the SLO module (single source); the import is jax-free.
        from gauss_tpu.obs.slo import history_records as slo_hist

        for metric, value, unit in slo_hist(doc):
            rec = _record(metric, value, path, "slo", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "fleet_solve":
        # A gauss-fleet --summary-json report: recovery depth (rung), resume
        # latency, and restart counts enter history so supervised-recovery
        # regressions gate like perf regressions. Derivation lives with the
        # fleet (single source); lazy import keeps jax out of this module.
        from gauss_tpu.resilience.fleet import history_records as fleet_hist

        for metric, value, unit in fleet_hist(doc):
            rec = _record(metric, value, path, "fleet", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "structured_solve":
        # A structure-check summary (python -m gauss_tpu.structure.check
        # --summary-json): per-class seconds-per-solve and FLOP ratio vs
        # dense LU enter history, so a class silently demoting back to
        # general LU gates exactly like a perf regression. Derivation
        # lives with the checker (single source); lazy import keeps jax
        # out of this module.
        from gauss_tpu.structure.check import history_records as struct_hist

        for metric, value, unit in struct_hist(doc):
            rec = _record(metric, value, path, "structure", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "sparse_solve":
        # A sparse-check summary (python -m gauss_tpu.sparse.check
        # --summary-json): per-method seconds-per-solve / iteration counts
        # and the no-densify giant leg's peak bytes enter history, so a
        # Krylov regression — slower convergence, a preconditioner losing
        # its bite, the O(nnz) path quietly densifying — gates in CI like
        # any perf regression. Derivation lives with the checker (single
        # source); lazy import keeps jax out of this module.
        from gauss_tpu.sparse.check import history_records as sparse_hist

        for metric, value, unit in sparse_hist(doc):
            rec = _record(metric, value, path, "sparse", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "mesh_serve":
        # A mesh-serve-check summary (python -m gauss_tpu.serve.meshcheck
        # --summary-json): the multi-lane serving plane's throughput /
        # tail latency and the continuous-batching-vs-fixed-drain ratio
        # enter history, so a lane-plane regression (slower lanes, a lost
        # batching win) gates in CI like any perf regression. Derivation
        # lives with the checker (single source); lazy import keeps jax
        # out of this module.
        from gauss_tpu.serve.meshcheck import history_records as mesh_hist

        for metric, value, unit in mesh_hist(doc):
            rec = _record(metric, value, path, "mesh_serve", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "throughput_bench":
        # A batched-throughput summary (python -m gauss_tpu.bench
        # .throughput): verified legs' seconds-per-solve enter history —
        # the THROUGHPUT record's epochs, gated (and ratcheted) exactly
        # like the latency headline's. Derivation lives with the bench
        # (single source); the import is jax-free at module level.
        from gauss_tpu.bench.throughput import history_records as tput_hist

        for metric, value, unit in tput_hist(doc):
            rec = _record(metric, value, path, "tput", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "tune_sweep":
        # A gauss-tune / tune-check summary: tuned seconds-per-solve and
        # the tuned/seed win ratio per swept point enter history, so a
        # sweep whose winner got slower — or whose tuning stopped paying —
        # gates exactly like a perf regression. Derivation lives with the
        # runner (single source); lazy import keeps jax out of this
        # module.
        from gauss_tpu.tune.runner import history_records as tune_hist

        for metric, value, unit in tune_hist(doc):
            rec = _record(metric, value, path, "tune", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "chaos_campaign":
        # A chaos-campaign summary (python -m gauss_tpu.resilience.chaos
        # --summary-json): recovery-depth and per-case cost enter history so
        # a RECOVERY-RATE regression (the ladder escalating deeper, or
        # failing where it used to recover) gates exactly like a perf
        # regression. Metric derivation lives with the campaign runner
        # (single source); lazy import so reading BENCH records never pulls
        # the solver stack into this module.
        from gauss_tpu.resilience.chaos import history_records as chaos_hist

        for metric, value, unit in chaos_hist(doc):
            rec = _record(metric, value, path, "chaos", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "abft_campaign":
        # An ABFT campaign summary (python -m gauss_tpu.resilience
        # .abftcheck --summary-json): detection-miss/escalation rates, per-
        # case cost, and the plain-path (abft OFF) seconds-per-solve enter
        # history — the last is the ZERO-OVERHEAD sentinel: the checksum
        # machinery creeping into the unprotected hot path gates exactly
        # like a perf regression. Metric derivation lives with the campaign
        # runner (single source); lazy import keeps the solver stack out of
        # this module.
        from gauss_tpu.resilience.abftcheck import history_records as \
            abft_hist

        for metric, value, unit in abft_hist(doc):
            rec = _record(metric, value, path, "abft", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "outofcore_bench":
        # An out-of-core gate summary (python -m gauss_tpu.outofcore.check
        # --summary-json): streamed seconds-per-solve, the stall fraction
        # (1 - transfer/compute overlap — the double-buffered pipeline
        # breaking shows as this jumping toward 1), and the measured peak
        # device fraction enter history, so the giant-system lane's
        # streaming efficiency is gated exactly like a perf regression.
        # Derivation lives with the checker (single source); lazy import
        # keeps jax out of this module.
        from gauss_tpu.outofcore.check import history_records as ooc_hist

        for metric, value, unit in ooc_hist(doc):
            rec = _record(metric, value, path, "outofcore", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "durable_campaign":
        # A kill-the-server campaign summary (python -m gauss_tpu.serve
        # .durablecheck --summary-json): per-case recovery cost and the
        # journal-on serving cost enter history — the journal getting more
        # expensive, or recovery getting slower, gates exactly like a perf
        # regression (the exactly-once INVARIANT itself is a hard exit-2,
        # not a band). Derivation lives with the campaign runner (single
        # source); lazy import keeps jax out of this module.
        from gauss_tpu.serve.durablecheck import history_records as \
            durable_hist

        for metric, value, unit in durable_hist(doc):
            rec = _record(metric, value, path, "durable", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "poison_campaign":
        # A poison-isolation campaign summary (python -m gauss_tpu.serve
        # .poisoncheck --summary-json): per-case isolation cost and the
        # bisection re-dispatch overhead enter history — poison isolation
        # getting more expensive gates exactly like a perf regression (the
        # innocents-verified / exactly-one-typed-terminal / no-crash-loop
        # INVARIANTS are hard exit-2s, not bands). Derivation lives with
        # the campaign runner (single source); lazy import keeps jax out
        # of this module.
        from gauss_tpu.serve.poisoncheck import history_records as \
            poison_hist

        for metric, value, unit in poison_hist(doc):
            rec = _record(metric, value, path, "poison", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "replica_campaign":
        # A kill-the-replica campaign summary (python -m gauss_tpu.serve
        # .replicacheck --summary-json): the 3-replica per-request serving
        # cost and the SIGKILL failover recovery latency enter history —
        # the network tier getting slower to serve or slower to fail over
        # gates exactly like a perf regression (the exactly-once ledger
        # INVARIANT itself is a hard exit-2, not a band). Derivation lives
        # with the campaign runner (single source); lazy import keeps jax
        # out of this module.
        from gauss_tpu.serve.replicacheck import history_records as \
            replica_hist

        for metric, value, unit in replica_hist(doc):
            rec = _record(metric, value, path, "replica", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "flight_check":
        # A flight-recorder gate summary (python -m gauss_tpu.obs
        # .flightcheck --summary-json): the measured ring-on overhead
        # ratio, ring-on seconds-per-solve, and the kill-to-bundle
        # campaign cost enter history — the always-on recorder getting
        # more expensive gates exactly like a perf regression (the
        # bundle/timeline INVARIANTS are hard exit-2s, not bands).
        # Derivation lives with the checker (single source); lazy import
        # keeps jax out of this module.
        from gauss_tpu.obs.flightcheck import history_records as \
            flight_hist

        for metric, value, unit in flight_hist(doc):
            rec = _record(metric, value, path, "flight", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "prof_check":
        # A profiler gate summary (python -m gauss_tpu.obs.profcheck
        # --summary-json): the attribution plane's per-request device cost
        # and serving overhead enter history — the always-on attribution
        # plane getting more expensive gates exactly like a perf
        # regression (the device-seconds RECONCILE and folded round-trip
        # are hard exit-2 invariants, not bands). Derivation lives with
        # the checker (single source); lazy import keeps jax out of this
        # module.
        from gauss_tpu.obs.profcheck import history_records as prof_hist

        for metric, value, unit in prof_hist(doc):
            rec = _record(metric, value, path, "prof", unit=unit)
            if rec:
                records.append(rec)
        return records
    if isinstance(doc, dict) and doc.get("kind") == "lint_report":
        # A gauss-lint --json summary: per-pass finding counts enter
        # history so the static gates ratchet like perf metrics — with
        # the committed epochs at 0, ANY finding is out-of-band here too.
        # Counts are built by the analysis package (single source, jax-
        # free) rather than _record, which by design drops the 0 values
        # that are this gate's healthy state.
        from gauss_tpu.analysis import history_records as lint_hist

        return lint_hist(doc, source=os.path.basename(os.fspath(path)))
    if isinstance(doc, list):  # bench-grid --json cells
        for cell in doc:
            if isinstance(cell, dict) and cell.get("verified"):
                rec = _record(_cell_metric(cell), cell.get("seconds"), path,
                              "cell", run=cell.get("run_id"))
                if rec:
                    records.append(rec)
        return records
    if isinstance(doc, dict):  # BENCH driver record or bare bench output
        parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        metric = parsed.get("metric")
        if metric:
            rec = _record(metric, parsed.get("value"), path, "bench",
                          unit=parsed.get("unit", "s"),
                          run=parsed.get("run_id"))
            if rec:
                records.append(rec)
            rec = _record(f"{metric}:refined", parsed.get("refined_value"),
                          path, "bench", unit=parsed.get("unit", "s"),
                          run=parsed.get("run_id"))
            if rec:
                records.append(rec)
    return records


def load_history(path) -> List[Dict[str, Any]]:
    if not os.path.exists(os.fspath(path)):
        return []
    out = []
    with open(os.fspath(path)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("metric"):
                out.append(rec)
    return out


def append_history(records: List[Dict[str, Any]], path) -> int:
    """Append records not already present (same metric+value+source ==
    the same measurement re-ingested; history is append-only, dedup keeps
    re-running ingest idempotent). Returns the number actually added."""
    existing = {(r.get("metric"), r.get("value"), r.get("source"))
                for r in load_history(path)}
    fresh = [r for r in records
             if (r["metric"], r["value"], r["source"]) not in existing]
    if not fresh:
        return 0
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a") as f:
        for r in fresh:
            f.write(json.dumps(r, sort_keys=True) + "\n")
    return len(fresh)


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def baseline(values: List[float], band: float = DEFAULT_BAND,
             ) -> Dict[str, float]:
    """Median + slow-side threshold for one metric's history. The band
    widens to 1 + 3*MAD/median when the recorded scatter exceeds the
    configured relative band — a metric whose own history is noisy gets a
    proportionally wider gate instead of false alarms."""
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    rel = max(band, 1.0 + (3.0 * mad / med if med > 0 else 0.0))
    return {"median": med, "mad": mad, "n": len(values),
            "rel_band": round(rel, 4), "threshold": med * rel}


def evaluate(metric: str, value: float, history: List[Dict[str, Any]],
             band: float = DEFAULT_BAND, min_samples: int = MIN_SAMPLES,
             ) -> Dict[str, Any]:
    """Classify one fresh measurement against the metric's history."""
    values = [r["value"] for r in history if r.get("metric") == metric
              and isinstance(r.get("value"), (int, float))]
    verdict: Dict[str, Any] = {"metric": metric, "value": value,
                               "samples": len(values)}
    if len(values) < min_samples:
        verdict.update(status="no-baseline",
                       note=f"only {len(values)} committed epoch(s) "
                            f"(need {min_samples}); informational only")
        return verdict
    base = baseline(values, band)
    ratio = value / base["median"] if base["median"] > 0 else float("inf")
    verdict.update(baseline=round(base["median"], 9),
                   threshold=round(base["threshold"], 9),
                   rel_band=base["rel_band"], ratio=round(ratio, 3))
    if value <= base["median"]:
        verdict.update(status="fast",
                       note="at or below the baseline median — a favorable "
                            "epoch is not a regression")
    elif value <= base["threshold"]:
        verdict.update(status="ok",
                       note=f"{ratio:.2f}x median, inside the "
                            f"{base['rel_band']:.2f}x noise band (epoch "
                            f"noise; docs/BENCH_STABILITY.md)")
    elif ratio <= EPOCH_DRIFT_CEILING:
        verdict.update(status="out-of-band",
                       note=f"{ratio:.2f}x median exceeds the "
                            f"{base['rel_band']:.2f}x band but sits inside "
                            f"the {EPOCH_DRIFT_CEILING}x epoch-drift "
                            f"ceiling — confirm with a same-epoch A/B "
                            f"before blaming code (BENCH_STABILITY.md)")
    else:
        verdict.update(status="out-of-band",
                       note=f"{ratio:.2f}x median, beyond the "
                            f"{EPOCH_DRIFT_CEILING}x epoch-drift ceiling — "
                            f"likely a code regression")
    return verdict


def evaluate_ratchet(metric: str, value: float) -> Optional[Dict[str, Any]]:
    """Classify a fresh measurement against the committed best-prior
    ratchet (None when the metric has no ratchet record). The returned
    verdict has the same shape :func:`evaluate` produces, so
    :func:`format_verdicts` and gate loops consume both uniformly."""
    best = RATCHET_BASELINES.get(metric)
    if best is None:
        return None
    ceiling = RATCHET_CEILINGS.get(metric, RATCHET_MAX_RATIO)
    ratio = value / best if best > 0 else float("inf")
    verdict: Dict[str, Any] = {
        "metric": f"{metric}:vs_best", "value": value, "samples": 1,
        "baseline": best, "threshold": round(best * ceiling, 9),
        "rel_band": ceiling, "ratio": round(ratio, 3)}
    if value <= best:
        verdict.update(status="fast",
                       note="at or below the committed best — ratchet the "
                            "record down (update RATCHET_BASELINES)")
    elif ratio <= ceiling:
        verdict.update(status="ok",
                       note=f"{ratio:.2f}x the committed best "
                            f"({best:.6g} s), inside the "
                            f"{ceiling}x ratchet ceiling")
    else:
        verdict.update(status="out-of-band",
                       note=f"{ratio:.2f}x the committed best "
                            f"({best:.6g} s) — past the "
                            f"{ceiling}x ratchet ceiling; the "
                            f"single-chip record only ratchets down "
                            f"(ROADMAP perf item)")
    return verdict


def attribute_phases(fresh: Dict[str, float], prior: Dict[str, float],
                     fresh_label: str = "fresh",
                     prior_label: str = "best-prior",
                     top: int = 3) -> Optional[str]:
    """Auto-attribution for a failed gate: diff a fresh record's flat
    ``{phase: seconds}`` map against the best committed prior epoch's and
    render the obs.doctor span-tree diff — the output NAMES the guilty
    phase ("biggest regression contributor: ..."), so a ratchet failure
    arrives pre-triaged instead of as a bare ratio. Returns None when
    either side has no phase accounting (old records predate phases_s)."""
    if not fresh or not prior:
        return None
    from gauss_tpu.obs import doctor

    a = doctor.profile_from_phases(prior, path=prior_label, tool="bench")
    b = doctor.profile_from_phases(fresh, path=fresh_label, tool="bench")
    diff = doctor.diff_profiles(a, b)
    return doctor.format_diff(diff, top or None)


def _doc_phases(doc: Any) -> Dict[str, float]:
    """Pull the flat phase map out of a bench-record-shaped artifact
    (``phases_s`` at top level or under ``parsed``); {} when absent."""
    if not isinstance(doc, dict):
        return {}
    for side in (doc, doc.get("parsed")):
        if isinstance(side, dict) and isinstance(side.get("phases_s"), dict):
            return side["phases_s"]
    return {}


def best_prior_phases() -> tuple:
    """(phases_s, label) of the best-headline committed BENCH_r*.json
    record that carries a phase breakdown — the prior side the check-path
    attribution diffs against. ({}, None) when no committed record has
    one (pre-attribution rounds)."""
    import glob

    root = os.path.dirname(os.path.dirname(default_history_path()))
    best_v, best = None, ({}, None)
    for p in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        side = parsed if isinstance(parsed, dict) else doc
        v = side.get("value") if isinstance(side, dict) else None
        phases = _doc_phases(doc)
        if phases and isinstance(v, (int, float)) and v > 0 and (
                best_v is None or v < best_v):
            best_v, best = v, (phases, os.path.basename(p))
    return best


def check_records(records: List[Dict[str, Any]],
                  history: List[Dict[str, Any]],
                  band: float = DEFAULT_BAND,
                  min_samples: int = MIN_SAMPLES) -> List[Dict[str, Any]]:
    return [evaluate(r["metric"], r["value"], history, band, min_samples)
            for r in records]


def format_verdicts(verdicts: List[Dict[str, Any]]) -> str:
    out = []
    for v in verdicts:
        head = f"[{v['status']:^12}] {v['metric']} = {v['value']:.6g}"
        if "baseline" in v:
            head += (f"  (baseline {v['baseline']:.6g} over "
                     f"{v['samples']} epochs)")
        out.append(head)
        out.append(f"               {v['note']}")
    bad = sum(1 for v in verdicts if v["status"] == "out-of-band")
    out.append(f"{len(verdicts)} metric(s) checked, {bad} out of band")
    return "\n".join(out)


def format_report(history: List[Dict[str, Any]],
                  band: float = DEFAULT_BAND) -> str:
    metrics: Dict[str, List[float]] = {}
    for r in history:
        if isinstance(r.get("value"), (int, float)):
            metrics.setdefault(r["metric"], []).append(r["value"])
    if not metrics:
        return "(empty history)"
    out = ["  epochs     median      threshold   metric"]
    for m in sorted(metrics):
        b = baseline(metrics[m], band)
        out.append(f"  {b['n']:6d}  {b['median']:10.6g}  "
                   f"{b['threshold']:10.6g}   {m}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.regress",
        description="Benchmark-regression sentinel over the append-only "
                    "history (reports/history.jsonl): median baselines "
                    "with an epoch-noise band, usable as a CI gate.")
    p.add_argument("command", choices=("ingest", "check", "report"),
                   help="ingest: append artifacts to history; check: "
                        "classify artifacts against history (exit 1 on "
                        "out-of-band); report: print per-metric baselines")
    p.add_argument("files", nargs="*",
                   help="artifacts: BENCH_*.json driver records, bench-grid "
                        "--json cell arrays, or obs JSONL streams with "
                        "cell events")
    p.add_argument("--history", default=None, metavar="PATH",
                   help=f"history file (default {default_history_path()})")
    p.add_argument("--band", type=float, default=DEFAULT_BAND,
                   help="slow-side relative noise band vs the median "
                        f"(default {DEFAULT_BAND})")
    p.add_argument("--min-samples", type=int, default=MIN_SAMPLES,
                   help="epochs required before a baseline gates "
                        f"(default {MIN_SAMPLES})")
    p.add_argument("--update", action="store_true",
                   help="check only: also append the checked records to "
                        "history when every verdict is in band (a green "
                        "gate grows the baseline)")
    p.add_argument("--ratchet", action="store_true",
                   help="check only: additionally gate every record that "
                        "has a RATCHET_BASELINES entry against the "
                        "committed best-ever value (the record-only-"
                        "ratchets-down contract; exit 1 past the per-"
                        "metric ceiling) — the CI leg of the gate "
                        "bench.py --regress applies to fresh headlines")
    args = p.parse_args(argv)
    history_path = args.history or default_history_path()

    if args.command == "report":
        print(f"history: {history_path}")
        print(format_report(load_history(history_path), args.band))
        return 0

    if not args.files:
        p.error(f"{args.command} needs at least one artifact file")
    records: List[Dict[str, Any]] = []
    for f in args.files:
        try:
            recs = ingest_file(f)
        except OSError as e:
            print(f"regress: cannot read '{f}': {e}", file=sys.stderr)
            return 2
        if not recs:
            print(f"regress: no ingestable measurements in '{f}'",
                  file=sys.stderr)
        records.extend(recs)
    if not records:
        print("regress: nothing to do (no measurements found)",
              file=sys.stderr)
        return 2

    if args.command == "ingest":
        added = append_history(records, history_path)
        print(f"regress: {added} new record(s) appended to {history_path} "
              f"({len(records) - added} already present)")
        return 0

    history = load_history(history_path)
    verdicts = check_records(records, history, args.band, args.min_samples)
    if args.ratchet:
        for r in records:
            rv = evaluate_ratchet(r["metric"], r["value"])
            if rv is not None:
                verdicts.append(rv)
    print(format_verdicts(verdicts))
    bad = any(v["status"] == "out-of-band" for v in verdicts)
    if bad:
        # Auto-attribution: when a checked artifact carries a phases_s
        # breakdown, diff it against the best committed prior epoch's and
        # name the guilty phase (obs.doctor) — a failed gate arrives
        # pre-triaged. Silent when neither side has phase accounting.
        prior, prior_label = best_prior_phases()
        for f in args.files:
            try:
                with open(os.fspath(f)) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue
            attribution = attribute_phases(
                _doc_phases(doc), prior,
                fresh_label=os.path.basename(os.fspath(f)),
                prior_label=prior_label or "best-prior")
            if attribution:
                print(f"regress: phase attribution for "
                      f"{os.path.basename(os.fspath(f))} vs {prior_label}:",
                      file=sys.stderr)
                print(attribution, file=sys.stderr)
    if args.update and not bad:
        added = append_history(records, history_path)
        print(f"regress: gate green; {added} record(s) appended to history")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
