"""End-to-end request tracing: one span tree per served request.

``python -m gauss_tpu.obs.requesttrace run.jsonl [--trace ID] [--json]``

Before this module, serving telemetry was BATCH-scoped: ``serve_batch_*``
spans carried no request identity, so "where did request 17's 40 ms go?"
was unanswerable from the stream. Now every request is minted a
``trace_id`` at ``submit()`` and the id rides the whole lifecycle:

- **admission** — the ``serve_admit`` event (queue depth at entry, bucket,
  deadline) and every synchronous rejection;
- **bucket/cache/dispatch** — batch-level spans and events
  (``serve_batch_pad`` / ``serve_batch_solve`` / ``serve_batch`` /
  ``serve_cache`` / ``serve_retry``) carry ``traces=[...]`` — the ids of
  every member request — plus ``requests=N``, so per-request numbers are
  computable from per-batch records (cost attribution: a batch span is
  shared by its members);
- **recovery / handoff** — the worker wraps per-request lanes in
  :func:`context`, so events emitted DEEP in library code with no trace
  parameter (``recovery`` rungs from recover.solve_resilient, ``route``
  from solve_handoff, fleet events) are stamped automatically via the
  thread-local in gauss_tpu.obs.spans;
- **terminal** — exactly one ``serve_request`` terminal event per request
  (the resolve-CAS guarantee), carrying the trace id.

:func:`request_traces` folds a recorded stream back into one tree per
trace id — root = the request, children = its stages in stream order,
batch spans shared by several requests appear in each member's tree.
The invariant the tests pin: every terminal status has EXACTLY ONE trace.
"""

from __future__ import annotations

import argparse
import json
import sys
import uuid
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import registry
# Re-exported: the thread-local context lives in spans (next to the emit
# hooks that consult it) so library emits need no import of this module.
from gauss_tpu.obs.spans import current_trace, trace_context  # noqa: F401

#: statuses that end a request (admission.py mirrors these; kept here so
#: the obs layer has no serve import)
TERMINAL_STATUSES = ("ok", "rejected", "expired", "failed", "cancelled")

#: event types that are per-request stages (single ``trace``) or shared
#: batch stages (``traces`` list) in a request tree
_STAGE_TYPES = ("serve_admit", "serve_request", "serve_batch", "serve_cache",
                "serve_retry", "serve_fallback", "serve_dedup", "span",
                "recovery", "route", "fault", "fleet", "health")


def mint() -> str:
    """A fresh trace id (hex, collision-safe across hosts)."""
    return uuid.uuid4().hex[:16]


def _trace_ids(ev: Dict[str, Any]) -> List[str]:
    tid = ev.get("trace")
    if tid:
        return [str(tid)]
    tids = ev.get("traces")
    if isinstance(tids, (list, tuple)):
        return [str(t) for t in tids]
    return []


def request_traces(events: List[Dict[str, Any]],
                   run_id: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Fold a stream into ``{trace_id: tree}``. A tree is::

        {"trace": id, "request_id", "n", "status", "lane", "latency_s",
         "terminal_count", "stages": [ {stage, t, ...fields} ... ]}

    Stages are in stream order (the recorder's ``seq``); a batch-shared
    stage (``traces`` list) appears in every member tree with
    ``shared=N`` so per-request cost attribution can divide by it."""
    if run_id is not None:
        events = [ev for ev in events if ev.get("run") == run_id]
    trees: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        typ = ev.get("type")
        if typ not in _STAGE_TYPES:
            continue
        tids = _trace_ids(ev)
        if not tids:
            continue
        shared = len(tids)
        for tid in tids:
            tree = trees.get(tid)
            if tree is None:
                tree = trees[tid] = {
                    "trace": tid, "request_id": None, "n": None,
                    "status": None, "lane": None, "latency_s": None,
                    "terminal_count": 0, "stages": []}
            stage = {"stage": (ev.get("name") if typ == "span" else typ),
                     "t": ev.get("t")}
            for k, v in ev.items():
                if k in ("type", "run", "seq", "t", "trace", "traces",
                         "name"):
                    continue
                stage[k] = v
            if shared > 1:
                stage["shared"] = shared
            tree["stages"].append(stage)
            if typ == "serve_admit":
                tree["request_id"] = ev.get("id")
                tree["n"] = ev.get("n")
            elif (typ == "serve_request"
                    and ev.get("status") in TERMINAL_STATUSES):
                tree["terminal_count"] += 1
                tree["status"] = ev.get("status")
                tree["lane"] = ev.get("lane") or tree["lane"]
                tree["request_id"] = ev.get("id", tree["request_id"])
                tree["n"] = ev.get("n", tree["n"])
                if ev.get("latency_s") is not None:
                    tree["latency_s"] = ev.get("latency_s")
    return trees


def _fold_key(ev: Dict[str, Any]) -> str:
    """Content identity of an event, ignoring sink-specific stamps: the
    recorder adds run/seq/t, the flight ring adds tu — the same emit seen
    through both sinks must collapse to one stage."""
    skip = ("run", "seq", "t", "tu")
    return json.dumps({k: v for k, v in ev.items() if k not in skip},
                      sort_keys=True, default=str)


def fold_ring_events(events: List[Dict[str, Any]],
                     ring_events: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Merge flight-ring records (:func:`gauss_tpu.obs.flight.scan`) into a
    recorded stream so a crash-spanning trace completes: the dead
    incarnation's ring carries the admit/batch stages the recorder lost
    with the process, the survivor's stream carries the terminal the
    journal resume produced. Ring events come first (they predate the
    surviving stream); duplicates — both sinks saw the same emit — fold to
    one stage. Ring ``tu`` doubles as the stage ``t`` when absent."""
    out: List[Dict[str, Any]] = []
    seen = set()
    for ev in ring_events:
        if ev.get("type") not in _STAGE_TYPES:
            continue
        key = _fold_key(ev)
        if key in seen:
            continue
        seen.add(key)
        ev = dict(ev)
        if "t" not in ev and "tu" in ev:
            ev["t"] = ev["tu"]
        out.append(ev)
    for ev in events:
        key = _fold_key(ev)
        if key in seen:
            continue
        seen.add(key)
        out.append(ev)
    return out


def check_traces(trees: Dict[str, Dict[str, Any]]) -> List[str]:
    """The exactly-one-trace-per-terminal invariant, as a problem list
    (empty = healthy). Used by tests and ``make live-check``."""
    problems = []
    for tid, tree in trees.items():
        if tree["terminal_count"] == 0:
            problems.append(f"trace {tid}: no terminal status recorded")
        elif tree["terminal_count"] > 1:
            problems.append(f"trace {tid}: {tree['terminal_count']} "
                            f"terminal statuses (must be exactly 1)")
    return problems


def format_tree(tree: Dict[str, Any]) -> str:
    head = (f"trace {tree['trace']}  request={tree['request_id']} "
            f"n={tree['n']} status={tree['status']}")
    if tree.get("lane"):
        head += f" lane={tree['lane']}"
    if isinstance(tree.get("latency_s"), (int, float)):
        head += f" latency={tree['latency_s'] * 1e3:.3f} ms"
    lines = [head]
    for st in tree["stages"]:
        kv = " ".join(
            f"{k}={v}" for k, v in st.items()
            if k not in ("stage", "t") and v is not None)
        t = st.get("t")
        ts = f"{t:9.6f}" if isinstance(t, (int, float)) else "        ?"
        lines.append(f"  {ts}  {st['stage']}" + (f"  {kv}" if kv else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.requesttrace",
        description="Reconstruct per-request span trees from a recorded "
                    "serving stream (one tree per trace_id, admission "
                    "through terminal status).")
    p.add_argument("path", help="JSONL events file (--metrics-out output)")
    p.add_argument("--run", default=None, help="restrict to this run ID")
    p.add_argument("--trace", default=None, help="print only this trace id")
    p.add_argument("--json", action="store_true",
                   help="emit the trees as JSON keyed by trace id")
    p.add_argument("--check", action="store_true",
                   help="verify every trace has exactly one terminal "
                        "status (exit 1 otherwise)")
    args = p.parse_args(argv)
    try:
        events = registry.read_events(args.path)
    except OSError as e:
        print(f"requesttrace: cannot read '{args.path}': {e}",
              file=sys.stderr)
        return 2
    trees = request_traces(events, args.run)
    if args.trace:
        if args.trace not in trees:
            print(f"requesttrace: trace '{args.trace}' not found "
                  f"({len(trees)} trace(s) in stream)", file=sys.stderr)
            return 2
        trees = {args.trace: trees[args.trace]}
    if args.json:
        print(json.dumps(trees, indent=1, sort_keys=True))
    else:
        print("\n\n".join(format_tree(trees[t]) for t in sorted(trees))
              or "(no traces found)")
    if args.check:
        problems = check_traces(trees)
        for prob in problems:
            print(f"requesttrace: {prob}", file=sys.stderr)
        print(f"requesttrace: {len(trees)} trace(s), "
              f"{len(problems)} problem(s)", file=sys.stderr)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
