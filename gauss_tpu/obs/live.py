"""Live telemetry aggregator: rolling-window views the process can serve.

PRs 1–2 made telemetry *post-hoc*: a per-run JSONL stream summarized after
the process exits. Long-lived processes (``SolverServer``, the
``gauss-fleet`` supervisor) need the complementary half — numbers you can
read WHILE the system runs. This module is that half:

- :class:`RollingWindow` — a fixed-capacity ring buffer of ``(t, value)``
  samples with an optional time horizon, plus numpy-compatible quantiles
  over the surviving window (the "latency sketch": p50/p95/p99 over the
  last N observations, exact within the window — asserted against
  ``np.quantile`` in tests).
- :class:`LiveAggregator` — the live sink the obs hooks forward into
  (:func:`gauss_tpu.obs.spans.set_live_sink`): monotonic counter totals,
  last-write gauges, one rolling window per histogram/span series, plus
  per-counter increment windows so windowed RATES (requests/s over the
  last minute) come from the same stream. It also hosts the SLO monitors
  (:mod:`gauss_tpu.obs.slo`) — terminal ``serve_request`` events feed the
  burn-rate windows in-band — and the on-demand trace capture the
  ``/trace`` endpoint uses.

Everything is lock-cheap: one mutex around plain dict/ring updates —
no allocation beyond ring slots, no sorting until a reader asks. With no
sink installed the obs hooks stay the zero-cost no-ops they were (two
module-global reads).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from gauss_tpu.obs import registry as _registry
from gauss_tpu.obs import spans as _spans

DEFAULT_WINDOW = 1024          # ring capacity per series
DEFAULT_HORIZON_S = 600.0      # samples older than this leave the window


def quantile(sorted_vals: Sequence[float], q: float) -> Optional[float]:
    """Linear-interpolation quantile over an ascending sequence — the same
    definition ``np.quantile`` defaults to, so window quantiles are exact
    (within the window), not an approximation."""
    n = len(sorted_vals)
    if n == 0:
        return None
    if n == 1:
        return float(sorted_vals[0])
    pos = min(max(q, 0.0), 1.0) * (n - 1)
    lo = math.floor(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


class RollingWindow:
    """Fixed-capacity ring of ``(t, value)`` samples with a time horizon.

    ``add`` is O(1); readers pay the sort. NOT internally locked — the
    owning aggregator serializes access (one lock for the whole sink is
    cheaper than one per series).
    """

    __slots__ = ("capacity", "horizon_s", "_buf", "_next", "count", "total")

    def __init__(self, capacity: int = DEFAULT_WINDOW,
                 horizon_s: Optional[float] = DEFAULT_HORIZON_S):
        if capacity < 1:
            raise ValueError(f"window capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.horizon_s = horizon_s
        self._buf: List[Tuple[float, float]] = []
        self._next = 0          # ring write index once the buffer is full
        self.count = 0          # all-time observation count
        self.total = 0.0        # all-time sum

    def add(self, value: float, t: Optional[float] = None) -> None:
        t = time.monotonic() if t is None else t
        item = (t, float(value))
        if len(self._buf) < self.capacity:
            self._buf.append(item)
        else:
            self._buf[self._next] = item
            self._next = (self._next + 1) % self.capacity
        self.count += 1
        self.total += float(value)

    def items(self, now: Optional[float] = None,
              horizon_s: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples still inside the horizon (unordered by time is fine for
        quantiles; rate readers filter by t anyway)."""
        horizon = self.horizon_s if horizon_s is None else horizon_s
        if horizon is None:
            return list(self._buf)
        now = time.monotonic() if now is None else now
        cutoff = now - horizon
        return [it for it in self._buf if it[0] >= cutoff]

    def values(self, now: Optional[float] = None,
               horizon_s: Optional[float] = None) -> List[float]:
        return [v for _, v in self.items(now, horizon_s)]

    def quantiles(self, qs: Sequence[float], now: Optional[float] = None,
                  ) -> Dict[str, Optional[float]]:
        vals = sorted(self.values(now))
        return {f"p{int(q * 100)}": quantile(vals, q) for q in qs}


class LiveAggregator:
    """The process's live metrics plane (install via :func:`install`).

    Counters accumulate monotonically (Prometheus counter semantics) and
    additionally record each increment into a rolling window, so
    :meth:`window_rate` answers "requests/s over the last minute" from the
    same stream. Histogram observations (including every ``span.<name>.s``)
    land in per-series rolling windows read back as p50/p95/p99.
    """

    def __init__(self, window: int = DEFAULT_WINDOW,
                 horizon_s: float = DEFAULT_HORIZON_S,
                 slos: Sequence = ()):
        self._lock = threading.Lock()
        self.t0 = time.monotonic()
        self.t0_unix = time.time()
        self.window = window
        self.horizon_s = horizon_s
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.windows: Dict[str, RollingWindow] = {}
        self._increments: Dict[str, RollingWindow] = {}
        from gauss_tpu.obs import slo as _slo

        self.slos = [s if isinstance(s, _slo.SLOMonitor) else _slo.SLOMonitor(s)
                     for s in slos]
        # on-demand trace capture (the /trace endpoint): a real Recorder the
        # hooks tee into while armed, completed after N serve_batch events.
        self._capture: Optional[_registry.Recorder] = None
        self._capture_left = 0
        self._capture_done = threading.Event()

    # -- sink interface (called by gauss_tpu.obs.spans hooks) --------------

    def on_counter(self, name: str, inc: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc
            win = self._increments.get(name)
            if win is None:
                win = self._increments[name] = RollingWindow(
                    self.window, self.horizon_s)
            win.add(inc)

    def on_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def on_histogram(self, name: str, value: float) -> None:
        with self._lock:
            win = self.windows.get(name)
            if win is None:
                win = self.windows[name] = RollingWindow(
                    self.window, self.horizon_s)
            win.add(float(value))

    def on_span(self, name: str, dur_s: float, parent: Optional[str],
                depth: int, attrs: Dict[str, Any]) -> None:
        self.on_histogram(f"span.{name}.s", dur_s)
        cap = self._capture
        if cap is not None:
            cap.emit("span", name=name, dur_s=round(dur_s, 6), parent=parent,
                     depth=depth, **attrs)

    def on_event(self, type_: str, fields: Dict[str, Any]) -> None:
        cap = self._capture
        if cap is not None and type_ != "alert":
            cap.emit(type_, **fields)
            if type_ == "serve_batch":
                with self._lock:
                    if self._capture_left > 0:
                        self._capture_left -= 1
                        if self._capture_left == 0:
                            self._capture_done.set()
        if type_ == "health":
            # numerical-health monitors become live gauges (last value
            # wins): min pivot, growth, residuals — scraped next to the
            # serving counters so a numerically sick lane is visible
            # BEFORE the post-hoc summary.
            with self._lock:
                for k, v in fields.items():
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        self.gauges[f"health.{k}"] = float(v)
            return
        if type_ == "serve_request" and self.slos:
            status = fields.get("status")
            if status is not None:
                self.observe_slo(str(status))

    # -- SLO plumbing ------------------------------------------------------

    def observe_slo(self, status: str, now: Optional[float] = None) -> None:
        """Feed one terminal request status to every SLO monitor; emit
        ``alert`` obs events for state transitions (outside the lock —
        the emit re-enters this sink through on_event)."""
        transitions = []
        with self._lock:
            for mon in self.slos:
                tr = mon.observe(status, now=now)
                if tr is not None:
                    transitions.append(tr)
        for tr in transitions:
            _spans.counter("slo.alerts" if tr["state"] == "firing"
                           else "slo.clears")
            _spans.emit("alert", **tr)
            if tr["state"] == "firing":
                # In-process crash detection: a firing burn-rate alert
                # freezes the flight ring into a post-mortem bundle while
                # the degradation is still observable. The trigger is a
                # no-op unless the server armed it (flight_dir set) and is
                # throttled there — a flapping alert cannot bundle-storm.
                try:
                    from gauss_tpu.obs import postmortem as _postmortem

                    _postmortem.trigger("slo_alert", slo=tr.get("slo"),
                                        burn_short=tr.get("burn_short"),
                                        burn_long=tr.get("burn_long"))
                except Exception:  # pragma: no cover — capture is best-effort
                    pass

    def slo_firing(self) -> bool:
        """Is any SLO alert currently firing? (The shed-wiring consult:
        one lock + list scan, cheap enough for the admission path.)"""
        with self._lock:
            return any(mon.firing for mon in self.slos)

    def slo_status(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        with self._lock:
            return [mon.status(now=now) for mon in self.slos]

    # -- readers -----------------------------------------------------------

    def window_rate(self, counter: str, horizon_s: float = 60.0,
                    now: Optional[float] = None) -> float:
        """Increments/s of ``counter`` over the trailing ``horizon_s``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            win = self._increments.get(counter)
            if win is None:
                return 0.0
            total = sum(v for t, v in win.items(now, horizon_s))
        return total / horizon_s if horizon_s > 0 else 0.0

    def snapshot(self, quantiles=(0.5, 0.95, 0.99),
                 now: Optional[float] = None) -> Dict[str, Any]:
        """One coherent read of the whole plane (the /metrics payload)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            windows = {
                name: {"count": win.count, "sum": win.total,
                       **win.quantiles(quantiles, now=now)}
                for name, win in self.windows.items()}
            slos = [mon.status(now=now) for mon in self.slos]
        return {"uptime_s": now - self.t0, "time_unix": time.time(),
                "counters": counters, "gauges": gauges, "windows": windows,
                "slo": slos}

    # -- on-demand trace capture (the /trace endpoint) ---------------------

    def start_capture(self, batches: int = 1, **meta) -> str:
        """Arm a capture of the next ``batches`` served batches; returns
        the capture run id. One capture at a time (409 at the endpoint)."""
        if batches < 1:
            raise ValueError(f"batches must be >= 1, got {batches}")
        with self._lock:
            if self._capture is not None:
                raise RuntimeError("a trace capture is already running")
            self._capture_done.clear()
            self._capture_left = batches
            self._capture = _registry.Recorder(
                meta={"tool": "live_trace_capture", "batches": batches,
                      **meta})
        return self._capture.run_id

    def wait_capture(self, timeout: Optional[float] = None) -> bool:
        """Block until the armed capture saw its N batches (False on
        timeout — the partial capture is still collectable)."""
        return self._capture_done.wait(timeout)

    def finish_capture(self) -> List[Dict[str, Any]]:
        """Disarm the capture and return its events (run_end stamped)."""
        with self._lock:
            cap, self._capture = self._capture, None
            self._capture_left = 0
        if cap is None:
            raise RuntimeError("no trace capture is running")
        cap.close()
        return cap.events + cap._registry_events()


def install(aggregator: LiveAggregator):
    """Install ``aggregator`` as the process live sink; returns the
    previous sink (restore it with :func:`uninstall`)."""
    return _spans.set_live_sink(aggregator)


def uninstall(previous=None) -> None:
    """Remove the live sink (restoring ``previous`` when given)."""
    _spans.set_live_sink(previous)
