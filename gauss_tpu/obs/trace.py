"""Chrome-trace export: the recorded spans as a visual timeline.

``python -m gauss_tpu.obs.trace run.jsonl [-o trace.json] [--run ID]``

Converts a telemetry events file — a single-process stream or an
``obs.aggregate`` merge — into the Chrome Trace Event JSON format, loadable
in ``chrome://tracing``, Perfetto (ui.perfetto.dev), or ``about:tracing``.
The reference's gprof tables flatten time; this is the same data as a
timeline: every span becomes a complete ("X") event, nested spans stack by
containment, and each PROCESS of a merged multihost run gets its own lane
(pid), clock-aligned by the merge's ``t_aligned`` stamps — stragglers are
visible as ragged lane edges instead of a number in a table.

Mapping:

- ``span``  -> phase "X": ts = end − duration, dur = dur_s (µs). Chrome
  infers nesting from containment within a lane, which matches the
  recorder's stack discipline (a parent opens before and closes after its
  children on one thread). Multi-threaded producers (bench worker threads)
  share a lane; overlap renders stacked, not wrong.
- ``health`` / ``collective`` / ``vmem_estimate`` / ``compile`` -> instant
  ("i") markers with the event's fields as args, so numerical incidents
  and comms budgets sit on the same timeline as the phases.
- ``run_start`` -> process_name/process_sort_index metadata, labeling each
  lane "process N @ host".

Span timestamps are host wall-clock (the recorder's contract); device work
is bounded by completion fetches, so lanes reflect what each host waited
for — exactly the straggler question.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import registry

_US = 1e6
_INSTANT_TYPES = ("health", "collective", "vmem_estimate", "compile",
                  "reported_time")
_SKIP_ARGS = {"type", "run", "seq", "t", "t_aligned", "proc", "name",
              "dur_s", "parent", "depth"}


def _ev_time(ev: Dict[str, Any]) -> float:
    """Event time in seconds on the merged clock (t_aligned when the stream
    went through obs.aggregate, per-run t otherwise)."""
    t = ev.get("t_aligned")
    return float(t if t is not None else ev.get("t", 0.0))


def _args_of(ev: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in ev.items() if k not in _SKIP_ARGS}


def to_chrome_trace(events: List[Dict[str, Any]],
                    run_id: Optional[str] = None) -> Dict[str, Any]:
    """Build the Chrome trace dict for one run of an events list."""
    runs = []
    for ev in events:
        rid = ev.get("run")
        if rid and rid not in runs:
            runs.append(rid)
    if not runs:
        raise ValueError("no runs found in the events")
    rid = run_id or runs[0]
    if rid not in runs:
        raise ValueError(f"run '{rid}' not found; runs: {', '.join(runs)}")
    evs = [ev for ev in events if ev.get("run") == rid]

    trace: List[Dict[str, Any]] = []
    lanes: Dict[int, Dict[str, Any]] = {}
    for ev in evs:
        proc = int(ev.get("proc", 0))
        if ev.get("type") == "run_start":
            lanes[proc] = ev
    for proc in sorted({int(ev.get("proc", 0)) for ev in evs}):
        start = lanes.get(proc, {})
        host = start.get("host")
        name = f"process {proc}" + (f" @ {host}" if host else "")
        trace.append({"ph": "M", "name": "process_name", "pid": proc,
                      "args": {"name": name}})
        trace.append({"ph": "M", "name": "process_sort_index", "pid": proc,
                      "args": {"sort_index": proc}})

    for ev in evs:
        proc = int(ev.get("proc", 0))
        typ = ev.get("type")
        if typ == "span":
            dur = float(ev.get("dur_s", 0.0))
            end = _ev_time(ev)
            trace.append({
                "ph": "X", "name": str(ev.get("name")), "cat": "span",
                "pid": proc, "tid": 0,
                "ts": round(max(0.0, end - dur) * _US, 3),
                "dur": round(dur * _US, 3),
                "args": _args_of(ev),
            })
        elif typ in _INSTANT_TYPES:
            trace.append({
                "ph": "i", "name": str(ev.get("name") or typ), "cat": typ,
                "pid": proc, "tid": 0, "s": "p",
                "ts": round(_ev_time(ev) * _US, 3),
                "args": _args_of(ev),
            })
    meta = lanes.get(min(lanes), {}) if lanes else {}
    return {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {"run": rid,
                      "processes": sorted(lanes) or [0],
                      "tool": meta.get("tool"),
                      "source": "gauss_tpu.obs.trace"},
    }


def write_trace(trace: Dict[str, Any], path) -> None:
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.trace",
        description="Export a telemetry JSONL file (single-process or an "
                    "obs.aggregate merge) as Chrome-trace/Perfetto JSON — "
                    "one timeline lane per process.")
    p.add_argument("path", help="JSONL events file")
    p.add_argument("--run", default=None,
                   help="run ID to export (default: first run in the file)")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="output trace JSON (default: <input>.trace.json)")
    args = p.parse_args(argv)
    try:
        events = registry.read_events(args.path)
    except OSError as e:
        print(f"trace: cannot read '{args.path}': {e}", file=sys.stderr)
        return 1
    try:
        trace = to_chrome_trace(events, args.run)
    except ValueError as e:
        print(f"trace: {e}", file=sys.stderr)
        return 1
    out = args.out or (os.fspath(args.path) + ".trace.json")
    write_trace(trace, out)
    spans = sum(1 for ev in trace["traceEvents"] if ev.get("ph") == "X")
    print(f"trace: run {trace['otherData']['run']}: {spans} spans across "
          f"{len(trace['otherData']['processes'])} lane(s) -> {out}\n"
          f"open in chrome://tracing or https://ui.perfetto.dev",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
