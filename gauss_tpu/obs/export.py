"""/metrics exposition + the embedded live-telemetry HTTP plane.

Pull-based exposition, the production-serving shape: the process embeds one
stdlib ``http.server`` thread (no new dependencies) and anything — a
Prometheus scraper, ``curl``, ``gauss-top`` — reads the live aggregator's
rolling windows while the system runs. Endpoints:

====================  =====================================================
``/metrics``          Prometheus text exposition (v0.0.4): every counter as
                      ``gauss_*_total``, gauges plain, rolling windows as
                      summary quantiles + ``_count``/``_sum``, SLO burn
                      rates/alert states with ``{slo=...}`` labels.
``/healthz``          liveness JSON: uptime, counts, firing-alert count.
``/slo``              full SLO monitor states as JSON.
``/snapshot``         the raw aggregator snapshot as JSON (gauss-top's
                      fallback; /metrics is the stable surface).
``/trace?batches=N``  arm an on-demand capture, block until the running
                      server has served N more batches (or ``timeout=S``),
                      return the Chrome-trace JSON — the PR-2 exporter
                      (obs.trace) pointed at a LIVE process instead of a
                      flushed file. 409 when a capture is already armed.
====================  =====================================================

Metric name mangling is mechanical and stable: ``serve.cache.hits`` ->
``gauss_serve_cache_hits_total``; the window ``span.serve_batch_solve.s``
-> ``gauss_span_serve_batch_solve_s{quantile="0.5"}``. A scrape totals-
match with the loadgen's final report is asserted by ``make live-check``.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from gauss_tpu.obs.live import LiveAggregator

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_QUANTILES = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}


def flight_status() -> Dict[str, Any]:
    """The flight-recorder section of ``/snapshot``: whether this process
    is recording, its ring position, and the LAST post-mortem bundle
    pointer (cause/time parsed from the filename — no body read per
    scrape). ``{"recording": False}`` when no sink is installed."""
    from gauss_tpu.obs import spans

    sink = spans.flight_sink()
    out: Dict[str, Any] = {"recording": sink is not None}
    if sink is None:
        return out
    try:
        out["flight_dir"] = sink.flight_dir
        out["ring"] = sink.position()
    except Exception:  # pragma: no cover — a scrape never takes serving down
        return out
    try:
        from gauss_tpu.obs import postmortem

        last = postmortem.latest_bundle(
            postmortem.default_bundles_dir(sink.flight_dir))
        if last:
            out["last_bundle"] = postmortem.bundle_info(last)
    except Exception:  # pragma: no cover
        pass
    return out


def attr_status() -> Dict[str, Any]:
    """The attribution-plane section of ``/snapshot``: whether a matrix is
    installed and its live snapshot (peaks, roofline, capacity, top
    cells). ``{"recording": False}`` when the plane is off — gauss-prof
    ``--url`` reads this to say so instead of printing empty tables."""
    from gauss_tpu.obs import attr as _attr

    try:
        return _attr.status()
    except Exception:  # pragma: no cover — a scrape never takes serving down
        return {"recording": False}


def metric_name(name: str, prefix: str = "gauss") -> str:
    """Flatten a dotted obs name into a legal Prometheus metric name."""
    flat = _NAME_RE.sub("_", name.strip("."))
    if flat and flat[0].isdigit():
        flat = "_" + flat
    return f"{prefix}_{flat}" if prefix else flat


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshot: Dict[str, Any], prefix: str = "gauss",
                      flight: Optional[Dict[str, Any]] = None) -> str:
    """Render an aggregator snapshot as the Prometheus text format.

    Deterministic (sorted by metric name) so the format has a golden test;
    one ``# TYPE`` line per family, counters suffixed ``_total``, windows
    rendered as summaries (quantile labels + _count/_sum). ``flight`` (the
    :func:`flight_status` dict) adds the flight-ring position gauges and —
    because Prometheus values are numeric-only — the last post-mortem's
    CAUSE as a label on its age gauge:
    ``gauss_postmortem_last_age_s{cause="..."}``."""
    lines = []

    def family(name: str, typ: str, help_: Optional[str] = None):
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {typ}")

    up = metric_name("live.uptime_s", prefix)
    family(up, "gauge", "seconds since the live aggregator started")
    lines.append(f"{up} {_fmt_value(snapshot.get('uptime_s', 0.0))}")

    for name in sorted(snapshot.get("counters", {})):
        m = metric_name(name, prefix) + "_total"
        family(m, "counter")
        lines.append(f"{m} {_fmt_value(snapshot['counters'][name])}")

    for name in sorted(snapshot.get("gauges", {})):
        m = metric_name(name, prefix)
        family(m, "gauge")
        lines.append(f"{m} {_fmt_value(snapshot['gauges'][name])}")

    for name in sorted(snapshot.get("windows", {})):
        win = snapshot["windows"][name]
        m = metric_name(name, prefix)
        family(m, "summary")
        for key, q in _QUANTILES.items():
            if win.get(key) is not None:
                lines.append(f'{m}{{quantile="{q}"}} '
                             f"{_fmt_value(win[key])}")
        lines.append(f"{m}_count {_fmt_value(win.get('count', 0))}")
        lines.append(f"{m}_sum {_fmt_value(win.get('sum', 0.0))}")

    slos = snapshot.get("slo") or []
    if slos:
        burn = metric_name("slo.burn_rate", prefix)
        family(burn, "gauge", "error-budget burn rate per SLO window")
        firing = metric_name("slo.firing", prefix)
        alerts = metric_name("slo.alerts", prefix) + "_total"
        objective = metric_name("slo.objective", prefix)
        viol = metric_name("slo.violation_rate", prefix)
        for s in sorted(slos, key=lambda s: s.get("name", "")):
            lines.append(f'{burn}{{slo="{s["name"]}",window="short"}} '
                         f"{_fmt_value(s['burn_short'])}")
            lines.append(f'{burn}{{slo="{s["name"]}",window="long"}} '
                         f"{_fmt_value(s['burn_long'])}")
        family(firing, "gauge", "1 while the SLO alert is firing")
        for s in sorted(slos, key=lambda s: s.get("name", "")):
            lines.append(f'{firing}{{slo="{s["name"]}"}} '
                         f"{1 if s.get('firing') else 0}")
        family(alerts, "counter")
        for s in sorted(slos, key=lambda s: s.get("name", "")):
            lines.append(f'{alerts}{{slo="{s["name"]}"}} '
                         f"{_fmt_value(s.get('alerts', 0))}")
        family(objective, "gauge")
        for s in sorted(slos, key=lambda s: s.get("name", "")):
            lines.append(f'{objective}{{slo="{s["name"]}"}} '
                         f"{_fmt_value(s.get('objective', 0.0))}")
        family(viol, "gauge")
        for s in sorted(slos, key=lambda s: s.get("name", "")):
            lines.append(f'{viol}{{slo="{s["name"]}"}} '
                         f"{_fmt_value(s.get('violation_rate', 0.0))}")

    if flight and flight.get("recording"):
        rec = metric_name("flight.recording", prefix)
        family(rec, "gauge", "1 while the flight recorder ring is on")
        lines.append(f"{rec} 1")
        ring = flight.get("ring") or {}
        for key in ("wpos", "seq", "capacity", "dropped_oversize"):
            if key in ring:
                m = metric_name(f"flight.ring_{key}", prefix)
                family(m, "gauge")
                lines.append(f"{m} {_fmt_value(ring[key])}")
        last = flight.get("last_bundle")
        if last and isinstance(last.get("time_unix"), (int, float)):
            m = metric_name("postmortem.last_age_s", prefix)
            family(m, "gauge",
                   "seconds since the last post-mortem bundle was captured")
            cause = str(last.get("cause") or "unknown").replace('"', "'")
            age = max(0.0, time.time() - float(last["time_unix"]))
            lines.append(f'{m}{{cause="{cause}"}} {_fmt_value(age)}')
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "gauss-live/1"
    agg: LiveAggregator = None  # type: ignore[assignment] # set per server

    def log_message(self, fmt, *args):  # quiet: obs, not stdout noise
        pass

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):  # client went away
            pass

    def _json(self, code: int, payload) -> None:
        self._reply(code, json.dumps(payload, sort_keys=True) + "\n",
                    "application/json")

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        agg = self.agg
        if url.path == "/metrics":
            agg.on_counter("live.scrapes")
            self._reply(200,
                        render_prometheus(agg.snapshot(),
                                          flight=flight_status()),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif url.path == "/healthz":
            snap = agg.snapshot()
            self._json(200, {
                "status": "ok", "uptime_s": round(snap["uptime_s"], 3),
                "counters": len(snap["counters"]),
                "windows": len(snap["windows"]),
                "slo_firing": sum(1 for s in snap["slo"]
                                  if s.get("firing"))})
        elif url.path == "/slo":
            self._json(200, {"slo": agg.slo_status()})
        elif url.path == "/snapshot":
            snap = agg.snapshot()
            snap["flight"] = flight_status()
            snap["attr"] = attr_status()
            self._json(200, snap)
        elif url.path == "/trace":
            self._trace(parse_qs(url.query))
        else:
            self._json(404, {"error": f"unknown endpoint {url.path!r}",
                             "endpoints": ["/metrics", "/healthz", "/slo",
                                           "/snapshot", "/trace"]})

    def _trace(self, q) -> None:
        from gauss_tpu.obs import trace as _trace

        try:
            batches = int(q.get("batches", ["1"])[0])
            timeout = float(q.get("timeout", ["30"])[0])
        except ValueError as e:
            self._json(400, {"error": f"bad query: {e}"})
            return
        try:
            self.agg.start_capture(batches=batches)
        except RuntimeError as e:
            self._json(409, {"error": str(e)})
            return
        except ValueError as e:
            self._json(400, {"error": str(e)})
            return
        complete = self.agg.wait_capture(timeout)
        events = self.agg.finish_capture()
        try:
            doc = _trace.to_chrome_trace(events)
        except ValueError as e:  # no spans arrived at all
            self._json(408, {"error": f"capture timed out empty: {e}"})
            return
        doc["otherData"]["complete"] = complete
        self._json(200 if complete else 206, doc)


class LiveServer:
    """The embedded telemetry endpoint: one daemon thread serving the
    aggregator. ``port=0`` binds an ephemeral port (tests); read the bound
    address back from :attr:`port` / :attr:`url`."""

    def __init__(self, aggregator: LiveAggregator, port: int = 0,
                 host: str = "127.0.0.1"):
        self.agg = aggregator
        handler = type("BoundHandler", (_Handler,), {"agg": aggregator})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "LiveServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="gauss-live",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "LiveServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
