"""Compile and memory accounting for jit/pallas entry points.

The repo's recurring blind spots are compile-side (ROADMAP history: the
49% r3->r4 headline swing, tunneled remote-compiles that never finished,
VMEM probe-table surprises). These helpers make that side data:

- :func:`compile_span` — wall-clock of a warmup/compile region as a
  ``compile`` event (+ span), so JIT cost is attributed instead of leaking
  into whatever phase runs next;
- :func:`record_cost` — XLA's own ``cost_analysis()`` FLOPs/bytes and
  ``memory_analysis()`` sizes for a jitted callable at concrete operands,
  via the AOT API (lowering only when the backend compile is unavailable);
- :func:`record_vmem_estimate` — the analytic VMEM/HBM working-set numbers
  the kernel-sizing code already computes internally (core.blocked,
  kernels.matmul_pallas), recorded at resolution time so probe-table gaps
  are visible data instead of only compile crashes.

Everything no-ops without an active recorder and never raises: accounting
must not take down a solve.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional

from gauss_tpu.obs import spans as _spans


@contextlib.contextmanager
def compile_span(label: str, **attrs):
    """Record a compile/warmup region: emits both a span (so the flat
    profile accounts the time) and a ``compile`` event keyed by label."""
    rec = _spans.active()
    if rec is None:
        yield
        return
    t0 = time.perf_counter()
    with _spans.span(f"compile:{label}", **attrs):
        yield
    _spans.emit("compile", label=label,
                compile_wall_s=round(time.perf_counter() - t0, 6), **attrs)


def _first_dict(cost) -> Dict[str, Any]:
    """``cost_analysis()`` returns a dict on new jax, a list of per-module
    dicts on older releases."""
    if isinstance(cost, dict):
        return cost
    if isinstance(cost, (list, tuple)) and cost and isinstance(cost[0], dict):
        return cost[0]
    return {}


def _lowerable(fn, args, kwargs):
    """Resolve decorator/partial wrappings to a jit object with ``.lower``
    (folding a functools.partial's bound arguments back into the call)."""
    import functools

    while True:
        if isinstance(fn, functools.partial):
            args = fn.args + args
            kwargs = {**fn.keywords, **kwargs}
            fn = fn.func
            continue
        lower = getattr(fn, "lower", None)
        if callable(lower):
            return fn, args, kwargs
        wrapped = getattr(fn, "__wrapped__", None)
        if wrapped is None:
            return None, args, kwargs
        fn = wrapped


def cost_summary(jitted_fn, *args, allow_compile: bool = True,
                 **kwargs) -> Optional[Dict[str, Any]]:
    """FLOPs/bytes/memory estimates for ``jitted_fn(*args, **kwargs)``.

    With ``allow_compile`` the AOT path compiles for the full
    ``cost_analysis`` + ``memory_analysis`` numbers — only do that where a
    (re)compile is affordable; ``allow_compile=False`` stops at the
    lowering-level HLO estimate, which costs one trace. Never raises."""
    fn, args, kwargs = _lowerable(jitted_fn, args, kwargs)
    if fn is None:
        return None
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception:
        return None
    out: Dict[str, Any] = {}
    if allow_compile:
        try:
            compiled = lowered.compile()
            cost = _first_dict(compiled.cost_analysis())
            out["flops"] = cost.get("flops")
            out["bytes_accessed"] = cost.get("bytes accessed")
            try:
                mem = compiled.memory_analysis()
                for attr in ("argument_size_in_bytes",
                             "output_size_in_bytes", "temp_size_in_bytes",
                             "generated_code_size_in_bytes"):
                    val = getattr(mem, attr, None)
                    if val is not None:
                        out[attr] = int(val)
            except Exception:
                pass
        except Exception:
            pass
    if "flops" not in out or out.get("flops") is None:
        try:
            cost = _first_dict(lowered.cost_analysis())
            out["flops"] = cost.get("flops")
            out.setdefault("bytes_accessed", cost.get("bytes accessed"))
        except Exception:
            pass
    return {k: v for k, v in out.items() if v is not None} or None


def record_cost(label: str, jitted_fn, *args, allow_compile: bool = True,
                **kwargs) -> Optional[Dict[str, Any]]:
    """Emit a ``cost`` event with :func:`cost_summary`'s numbers (no-op and
    zero work when no recorder is active)."""
    if _spans.active() is None:
        return None
    t0 = time.perf_counter()
    summary = cost_summary(jitted_fn, *args, allow_compile=allow_compile,
                           **kwargs)
    if summary is None:
        return None
    _spans.emit("cost", label=label,
                analysis_wall_s=round(time.perf_counter() - t0, 6), **summary)
    return summary


def record_vmem_estimate(label: str, **fields) -> None:
    """Record an analytic working-set estimate (bytes vs budget, fits flag,
    clamped tile dims, ...) computed by kernel-sizing code. Call sites run
    at trace/resolution time, never inside compiled code."""
    _spans.emit("vmem_estimate", label=label, **fields)


# -- XLA persistent-compile-cache accounting --------------------------------

#: jax monitoring event suffix -> obs counter. A "cache miss" IS an actual
#: backend compile (the executable was not in the persistent cache); a
#: "cache hit" is a compile avoided — the pair is exactly the
#: fewer-compiles evidence the tune-check warm-start gate asserts on.
_XLA_CACHE_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "xla.cache_hits",
    "/jax/compilation_cache/cache_misses": "xla.cache_misses",
    "/jax/compilation_cache/compile_requests_use_cache":
        "xla.compile_requests",
}

_xla_listener_registered = False


def _xla_cache_listener(event: str, **kwargs) -> None:
    name = _XLA_CACHE_COUNTERS.get(event)
    if name is not None:
        _spans.counter(name)


def track_xla_cache() -> bool:
    """Register a jax monitoring listener that folds persistent-compile-
    cache hit/miss events into obs counters (``xla.cache_hits`` /
    ``xla.cache_misses`` / ``xla.compile_requests``). Idempotent; returns
    whether the listener is installed. Counters no-op without an active
    recorder, so registration is safe process-wide. Uses jax's private
    monitoring module — guarded, because accounting must never take down
    a solve (and the events simply go uncounted on a jax that moved it)."""
    global _xla_listener_registered
    if _xla_listener_registered:
        return True
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_xla_cache_listener)
    except Exception:
        return False
    _xla_listener_registered = True
    return True
