"""``make prof-check`` — the attribution plane's end-to-end CI gate.

``python -m gauss_tpu.obs.profcheck [--summary-json PATH]``

Three legs, all CPU, exit 2 on any invariant failure:

1. **Reconcile**: a warm attr-on loadgen run (``ServeConfig(attr=True)``);
   the per-request device-seconds the clients saw (``ServeResult.device_s``
   summed over served + warmup requests) must reconcile with the
   attribution matrix's own serve total (``capacity()["serve_device_s"]``)
   within the stated tolerance — ``|Σ request - matrix| <=
   max(RECONCILE_ABS_S, RECONCILE_REL * matrix)``. The same leg asserts
   the roofline has a row for every engine the run exercised, each with an
   achieved-flops rate, and that per-sig capacity accounting is populated.
2. **Folds round-trip**: the leg-1 recorded stream's folded stacks must
   survive ``fold_lines -> parse_folded -> fold_lines`` byte-identically,
   and ``top_executables`` must surface the attr cells.
3. **Attribution on a forced ratchet failure**: a synthetic headline past
   the committed ratchet ceiling must come back ``out-of-band`` from
   :func:`gauss_tpu.obs.regress.evaluate_ratchet`, and
   :func:`gauss_tpu.obs.regress.attribute_phases` over an inflated phase
   map must NAME the regressed phase in its "biggest regression
   contributor" line — the pre-triage contract ``bench.py --regress`` and
   ``regress check`` print on failure.

The summary is regress-ingestable (``kind: prof_check``). Exit 2 on an
invariant failure, 1 when ``--regress-check`` finds an out-of-band
metric, 0 otherwise. ``make prof-check`` runs the CI configuration; like
the other timing-gated gates it must not run concurrently with them
(Makefile serial-ordering note).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Tuple

from gauss_tpu.utils.env import honor_jax_platforms

#: the reconcile tolerance, stated: per-request costs are rounded to
#: microseconds at the ServeResult boundary, so the identity is exact up
#: to rounding on a healthy run; the relative term absorbs the rare
#: cancel/verify-failure divergence (a request the matrix timed but whose
#: result never reached a client).
RECONCILE_ABS_S = 0.001
RECONCILE_REL = 0.01

#: the forced-failure phase map for leg 3 — the slope phase is inflated,
#: everything else held; attribution must name it.
_PRIOR_PHASES = {"prepare_inputs": 0.11, "headline_slope": 1.05,
                 "verify": 0.21}
_INFLATED_PHASES = {"prepare_inputs": 0.11, "headline_slope": 2.37,
                    "verify": 0.21}


# -- leg 1: request-cost vs matrix reconcile -------------------------------

def run_reconcile_leg(seed: int, gate: float, cache=None, log=print) -> Dict:
    """One warm attr-on loadgen pass; reconcile the client-visible cost
    accounting against the matrix's totals and check the roofline rows."""
    from gauss_tpu import obs
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    cfg = ServeConfig(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
                      verify_gate=gate, max_queue=256, attr=True)
    warm = LoadgenConfig(mix="random:24*2,random:30", requests=24, warmup=4,
                         mode="closed", concurrency=4, seed=seed,
                         verify_gate=gate, serve=cfg)
    leg: Dict = {"leg": "reconcile"}
    t0 = time.perf_counter()
    # Warm pass: compiles land here so the measured pass is steady-state
    # (and the reconcile is not dominated by one giant compile share).
    with obs.span("prof_reconcile_warm"):
        with SolverServer(cfg, cache=cache) as srv:
            run_load(srv, warm)
    with obs.span("prof_reconcile_measured"):
        with SolverServer(cfg, cache=cache) as srv:
            summary = run_load(srv, warm)
            roofline = srv.attr.roofline()
            engines_seen = sorted(srv.attr.engine_names())
    cost = summary.get("cost") or {}
    req_s = ((cost.get("request_device_s") or 0.0)
             + (cost.get("warmup_device_s") or 0.0))
    matrix_s = cost.get("serve_device_s") or 0.0
    tol = max(RECONCILE_ABS_S, RECONCILE_REL * matrix_s)
    leg.update(
        request_device_s=round(req_s, 6),
        matrix_device_s=round(matrix_s, 6),
        tolerance_s=round(tol, 6),
        reconciled=abs(req_s - matrix_s) <= tol,
        incorrect=summary.get("incorrect"),
        throughput_rps=summary.get("throughput_rps"),
        device_s_per_request=cost.get("device_s_per_request"),
        engines=engines_seen,
        roofline=roofline,
        sigs=sorted((cost.get("sigs") or {})),
    )
    problems = []
    if not leg["reconciled"]:
        problems.append(
            f"request cost {req_s:.6f} s vs matrix {matrix_s:.6f} s "
            f"diverges past the {tol:.6f} s tolerance")
    if summary.get("incorrect"):
        problems.append(f"{summary['incorrect']} INCORRECT solution(s)")
    if matrix_s <= 0:
        problems.append("matrix attributed no serve device-seconds")
    for eng in engines_seen:
        row = roofline.get(eng) or {}
        if not isinstance(row.get("achieved_flops_per_s"), (int, float)):
            problems.append(f"roofline row for engine '{eng}' has no "
                            f"achieved-flops rate")
    if not leg["sigs"]:
        problems.append("capacity model has no per-sig accounting")
    leg["outcome"] = "violation" if problems else "ok"
    if problems:
        leg["error"] = "; ".join(problems)
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    log(f"  reconcile leg: {leg['outcome']} (requests {req_s:.6f} s vs "
        f"matrix {matrix_s:.6f} s, tol {tol:.6f} s; engines "
        f"{','.join(engines_seen) or '-'})")
    return leg


# -- leg 2: folded stacks round-trip ---------------------------------------

def run_folds_leg(stream: str, log=print) -> Dict:
    """The leg-1 stream's folded stacks must round-trip through the
    serialized form byte-identically, and the top table must see cells."""
    from gauss_tpu.obs import prof, registry

    leg: Dict = {"leg": "folds", "stream": stream}
    events = registry.read_events(stream)
    folds = prof.folded_stacks(events)
    lines = prof.fold_lines(folds)
    round_trip = prof.fold_lines(prof.parse_folded(lines))
    top = prof.top_executables(events, 5)
    leg.update(stacks=len(lines), round_trip_ok=round_trip == lines,
               top_rows=len(top),
               attr_cells=sum(1 for ev in events
                              if ev.get("type") == "attr"))
    problems = []
    if not lines:
        problems.append("no folded stacks recovered from the stream")
    if not leg["round_trip_ok"]:
        problems.append("fold_lines(parse_folded(lines)) != lines")
    if not top:
        problems.append("top_executables saw no cells")
    if not leg["attr_cells"]:
        problems.append("stream has no attr events")
    leg["outcome"] = "violation" if problems else "ok"
    if problems:
        leg["error"] = "; ".join(problems)
    log(f"  folds leg: {leg['outcome']} ({leg['stacks']} stack(s), "
        f"{leg['attr_cells']} attr cell(s), round-trip "
        f"{'ok' if leg['round_trip_ok'] else 'BROKEN'})")
    return leg


# -- leg 3: forced ratchet failure -> named phase --------------------------

def run_attribution_leg(log=print) -> Dict:
    """A headline past the ratchet ceiling must gate out-of-band, and the
    phase attribution over an inflated phase map must name the phase."""
    from gauss_tpu.obs import regress

    leg: Dict = {"leg": "attribution"}
    metric = "gauss_n2048_wallclock"
    forced = regress.RATCHET_BASELINES[metric] * (
        regress.RATCHET_CEILINGS.get(metric, regress.RATCHET_MAX_RATIO)
        + 0.5)
    verdict = regress.evaluate_ratchet(metric, forced)
    leg["forced_value"] = round(forced, 6)
    leg["ratchet_status"] = verdict["status"] if verdict else None
    text = regress.attribute_phases(_INFLATED_PHASES, _PRIOR_PHASES,
                                    fresh_label="forced",
                                    prior_label="prior")
    leg["attribution"] = text
    named = bool(text) and ("biggest regression contributor: "
                            "headline_slope" in text)
    leg["named_phase"] = "headline_slope" if named else None
    problems = []
    if leg["ratchet_status"] != "out-of-band":
        problems.append(f"forced {forced:.6f} s gated "
                        f"'{leg['ratchet_status']}', expected out-of-band")
    if not named:
        problems.append("attribution did not name the inflated phase")
    leg["outcome"] = "violation" if problems else "ok"
    if problems:
        leg["error"] = "; ".join(problems)
    log(f"  attribution leg: {leg['outcome']} (forced headline "
        f"{forced:.6f} s -> {leg['ratchet_status']}, named phase: "
        f"{leg['named_phase']})")
    return leg


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a prof-check run contributes to
    history. The attr-on serving cost gates (the attribution plane getting
    more expensive is a perf regression); the per-request attributed
    device cost gates the accounting itself drifting (a sudden jump means
    the matrix started double-counting or the solve path slowed)."""
    out: List[Tuple[str, float, str]] = []
    rec = summary.get("reconcile") or {}
    tput = rec.get("throughput_rps")
    if isinstance(tput, (int, float)) and tput > 0:
        out.append(("prof:attr_s_per_request", round(1.0 / tput, 6), "s"))
    dev = rec.get("device_s_per_request")
    if isinstance(dev, (int, float)) and dev > 0:
        out.append(("prof:device_s_per_request", round(dev, 6), "s"))
    return out


# -- gate main --------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.profcheck",
        description="Attribution-plane gate: per-request cost vs matrix "
                    "reconcile, folded-stack round-trip, and the forced-"
                    "ratchet-failure phase-attribution contract.")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--tmpdir", default="/tmp/gauss_prof",
                   help="stream scratch directory")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="record the gate's own stream here (default "
                        "<tmpdir>/profcheck.jsonl — the folds leg reads "
                        "it back)")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append gate records to the regression history "
                        "(default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve.cache import ExecutableCache

    os.makedirs(args.tmpdir, exist_ok=True)
    stream = args.metrics_out or os.path.join(args.tmpdir,
                                              "profcheck.jsonl")
    if stream != args.metrics_out and os.path.exists(stream):
        os.remove(stream)  # default scratch stream: one run per file
    t0 = time.perf_counter()
    with obs.run(metrics_out=stream, tool="prof_check", seed=args.seed):
        with obs.span("prof_check"):
            reconcile = run_reconcile_leg(args.seed, args.gate,
                                          cache=ExecutableCache(64))
            attribution = run_attribution_leg()
    # The folds leg reads the CLOSED stream back — the round-trip is over
    # what actually landed on disk, not the in-memory event list.
    folds = run_folds_leg(stream)
    wall = round(time.perf_counter() - t0, 3)
    legs = [reconcile, folds, attribution]
    violations = sum(1 for leg in legs if leg.get("outcome") == "violation")
    summary = {"kind": "prof_check", "seed": args.seed, "gate": args.gate,
               "reconcile": reconcile, "folds": folds,
               "attribution": attribution, "wall_s": wall,
               "invariant_ok": violations == 0}
    print(f"prof-check: {len(legs)} leg(s), "
          f"{'invariant HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "profcheck",
                "kind": "prof"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        for r in records:
            rv = regress.evaluate_ratchet(r["metric"], r["value"])
            if rv is not None:
                verdicts.append(rv)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 \
            and violations == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        for leg in legs:
            if leg.get("outcome") == "violation":
                print(f"profcheck: leg[{leg.get('leg')}] VIOLATION: "
                      f"{leg.get('error')}", file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
