"""Render a recorded run: gprof-style flat profile + health + accounting.

``python -m gauss_tpu.obs.summarize run.jsonl [--run ID] [--json]`` — the
offline consumer of the JSONL event stream (the gprof step of the reference's
workflow, SURVEY §5, replayed from persistent data instead of a one-shot
stdout table).

The flat profile aggregates LEAF spans (spans that are never some other
span's parent), so nested regions are not double-counted, and reports the
leaf total against the run's wall-clock — the coverage line is the honesty
check that the spans actually tile the run instead of sampling it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import registry


def _runs(events: List[Dict[str, Any]]) -> List[str]:
    seen = []
    for ev in events:
        rid = ev.get("run")
        if rid and rid not in seen:
            seen.append(rid)
    return seen


def _fmt(v) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or abs(v) < 1e-4:
            return f"{v:.3e}"
        return f"{v:.6g}"
    return str(v)


def flat_profile(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate span events into {name: {seconds, calls}} over leaves, plus
    totals. Returns a dict so tests and JSON output share the numbers."""
    spans = [ev for ev in events if ev.get("type") == "span"]
    parents = {ev.get("parent") for ev in spans if ev.get("parent")}
    leaves = [ev for ev in spans if ev["name"] not in parents]
    agg: Dict[str, Dict[str, float]] = {}
    for ev in leaves:
        a = agg.setdefault(ev["name"], {"seconds": 0.0, "calls": 0})
        a["seconds"] += float(ev.get("dur_s", 0.0))
        a["calls"] += 1
    total = sum(a["seconds"] for a in agg.values())
    wall = None
    for ev in events:
        if ev.get("type") == "run_end" and ev.get("wall_s") is not None:
            wall = float(ev["wall_s"])
    return {"phases": agg, "span_total_s": total, "wall_s": wall}


def _profile_lines(prof: Dict[str, Any]) -> List[str]:
    agg, total = prof["phases"], prof["span_total_s"]
    lines = ["  %time    seconds   calls  phase"]
    denom = total or 1.0
    for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["seconds"]):
        lines.append(f"  {100.0 * a['seconds'] / denom:5.1f}  "
                     f"{a['seconds']:9.6f}  {a['calls']:6d}  {name}")
    lines.append(f"  span total {total:.6f} s")
    if prof["wall_s"]:
        cov = 100.0 * total / prof["wall_s"]
        lines.append(f"  run wall-clock {prof['wall_s']:.6f} s "
                     f"({cov:.1f}% covered by leaf spans)")
    return lines


_SKIP_FIELDS = {"type", "run", "seq", "t"}


def _event_kv(ev: Dict[str, Any], skip=()) -> str:
    return " ".join(f"{k}={_fmt(v)}" for k, v in ev.items()
                    if k not in _SKIP_FIELDS and k not in skip
                    and v is not None)


def summarize_run(events: List[Dict[str, Any]], run_id: str) -> str:
    evs = [ev for ev in events if ev.get("run") == run_id]
    out = []
    start = next((ev for ev in evs if ev.get("type") == "run_start"), {})
    meta = _event_kv(start, skip=("time_unix", "schema"))
    out.append(f"run {run_id}" + (f"  [{meta}]" if meta else ""))

    reported = [ev for ev in evs if ev.get("type") == "reported_time"]
    for ev in reported:
        out.append(f"  reported: {ev.get('name')} = "
                   f"{_fmt(ev.get('seconds'))} s")

    prof = flat_profile(evs)
    if prof["phases"]:
        out.append("")
        out.append("flat profile (leaf spans):")
        out.extend(_profile_lines(prof))

    health = [ev for ev in evs if ev.get("type") == "health"]
    if health:
        out.append("")
        out.append("numerical health:")
        for ev in health:
            out.append("  " + _event_kv(ev))

    compiles = [ev for ev in evs if ev.get("type") in ("compile", "cost")]
    if compiles:
        out.append("")
        out.append("compile / cost accounting:")
        for ev in compiles:
            out.append("  " + _event_kv(ev))

    vmem = [ev for ev in evs if ev.get("type") == "vmem_estimate"]
    if vmem:
        out.append("")
        out.append("VMEM/HBM working-set estimates:")
        for ev in vmem:
            out.append("  " + _event_kv(ev))

    metrics = [ev for ev in evs if ev.get("type") == "metric"
               and not str(ev.get("name", "")).startswith("span.")]
    if metrics:
        out.append("")
        out.append("metrics:")
        for ev in metrics:
            out.append(f"  {ev.get('kind')} " + _event_kv(ev, skip=("kind",)))
    return "\n".join(out)


def summarize_events(events: List[Dict[str, Any]],
                     run_id: Optional[str] = None) -> str:
    run_ids = [run_id] if run_id else _runs(events)
    if not run_ids:
        return "(no runs found)"
    return "\n\n".join(summarize_run(events, rid) for rid in run_ids)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.summarize",
        description="Render a metrics JSONL file (gprof-style flat profile, "
                    "numerical health, compile/memory accounting).")
    p.add_argument("path", help="JSONL events file (--metrics-out output)")
    p.add_argument("--run", default=None, help="summarize only this run ID")
    p.add_argument("--json", action="store_true",
                   help="emit the flat profile(s) as JSON instead of text")
    args = p.parse_args(argv)
    try:
        events = registry.read_events(args.path)
    except OSError as e:
        print(f"summarize: cannot read '{args.path}': {e}", file=sys.stderr)
        return 1
    if args.run and args.run not in _runs(events):
        print(f"summarize: run '{args.run}' not found; runs: "
              f"{', '.join(_runs(events)) or '(none)'}", file=sys.stderr)
        return 1
    if args.json:
        run_ids = [args.run] if args.run else _runs(events)
        payload = {rid: flat_profile(
            [ev for ev in events if ev.get("run") == rid]) for rid in run_ids}
        print(json.dumps(payload, indent=1, sort_keys=True))
        return 0
    print(summarize_events(events, args.run))
    return 0


if __name__ == "__main__":
    sys.exit(main())
