"""Render a recorded run: gprof-style flat profile + health + accounting.

``python -m gauss_tpu.obs.summarize run.jsonl [--run ID] [--json]`` — the
offline consumer of the JSONL event stream (the gprof step of the reference's
workflow, SURVEY §5, replayed from persistent data instead of a one-shot
stdout table).

The flat profile aggregates LEAF spans (spans that are never some other
span's parent), so nested regions are not double-counted, and reports the
leaf total against the run's wall-clock — the coverage line is the honesty
check that the spans actually tile the run instead of sampling it. On a
MERGED multihost stream (``obs.aggregate`` stamps ``proc`` on every event)
coverage is computed PER PROCESS and reported per lane: each process has its
own wall-clock, and a single-stream formula dividing the summed span time of
P processes by one process's wall would read ~P00%.

``--json`` emits the complete summary (profile, lanes, health, collective
traffic, compile/cost, vmem, metrics) as machine-readable JSON keyed by run
— the form CI and the regression sentinel consume.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import registry


def _runs(events: List[Dict[str, Any]]) -> List[str]:
    seen = []
    for ev in events:
        rid = ev.get("run")
        if rid and rid not in seen:
            seen.append(rid)
    return seen


def _fmt(v) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or abs(v) < 1e-4:
            return f"{v:.3e}"
        return f"{v:.6g}"
    return str(v)


def _procs(events: List[Dict[str, Any]]) -> List[int]:
    return sorted({int(ev.get("proc", 0)) for ev in events})


def flat_profile(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate span events into {name: {seconds, calls}} over leaves, plus
    totals. Returns a dict so tests and JSON output share the numbers.

    ``lanes`` holds the per-process split of a merged multihost stream
    (span total, wall-clock and coverage PER process); on a single-process
    stream it has one lane and matches the totals. The top-level ``wall_s``
    is the max lane wall — the run's duration, not the sum of P clocks.
    """
    spans = [ev for ev in events if ev.get("type") == "span"]
    parents = {ev.get("parent") for ev in spans if ev.get("parent")}
    leaves = [ev for ev in spans if ev["name"] not in parents]
    agg: Dict[str, Dict[str, float]] = {}
    for ev in leaves:
        a = agg.setdefault(ev["name"], {"seconds": 0.0, "calls": 0})
        a["seconds"] += float(ev.get("dur_s", 0.0))
        a["calls"] += 1
    total = sum(a["seconds"] for a in agg.values())

    lanes: Dict[int, Dict[str, Any]] = {}
    for proc in _procs(events):
        lane_total = sum(float(ev.get("dur_s", 0.0)) for ev in leaves
                         if int(ev.get("proc", 0)) == proc)
        wall = None
        for ev in events:
            if (ev.get("type") == "run_end" and int(ev.get("proc", 0)) == proc
                    and ev.get("wall_s") is not None):
                wall = float(ev["wall_s"])
        lanes[proc] = {"span_total_s": lane_total, "wall_s": wall,
                       "coverage": (lane_total / wall if wall else None)}
    walls = [l["wall_s"] for l in lanes.values() if l["wall_s"]]
    return {"phases": agg, "span_total_s": total,
            "wall_s": max(walls) if walls else None, "lanes": lanes}


def _profile_lines(prof: Dict[str, Any]) -> List[str]:
    agg, total = prof["phases"], prof["span_total_s"]
    lines = ["  %time    seconds   calls  phase"]
    denom = total or 1.0
    for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["seconds"]):
        lines.append(f"  {100.0 * a['seconds'] / denom:5.1f}  "
                     f"{a['seconds']:9.6f}  {a['calls']:6d}  {name}")
    lines.append(f"  span total {total:.6f} s")
    lanes = prof.get("lanes") or {}
    if len(lanes) > 1:
        # Merged multihost stream: one coverage line PER process lane —
        # each process has its own wall-clock (the single-stream formula
        # against one wall would report ~P00% and nonsense skew).
        for proc in sorted(lanes):
            lane = lanes[proc]
            if lane["wall_s"]:
                lines.append(
                    f"  process {proc}: wall-clock {lane['wall_s']:.6f} s "
                    f"({100.0 * lane['coverage']:.1f}% covered by its leaf "
                    f"spans)")
            else:
                lines.append(f"  process {proc}: (no run_end recorded)")
    elif prof["wall_s"]:
        cov = 100.0 * total / prof["wall_s"]
        lines.append(f"  run wall-clock {prof['wall_s']:.6f} s "
                     f"({cov:.1f}% covered by leaf spans)")
    return lines


def comms_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold ``collective`` events into per-engine traffic totals:
    ``{label: {"ops": {op: {count, bytes}}, "count", "bytes"}}``."""
    out: Dict[str, Any] = {}
    for ev in events:
        if ev.get("type") != "collective":
            continue
        label = str(ev.get("label", "?"))
        eng = out.setdefault(label, {"ops": {}, "count": 0, "bytes": 0})
        op = eng["ops"].setdefault(str(ev.get("op", "?")),
                                   {"count": 0, "bytes": 0})
        c = int(ev.get("count", 0) or 0)
        b = int(ev.get("bytes", 0) or 0)
        op["count"] += c
        op["bytes"] += b
        eng["count"] += c
        eng["bytes"] += b
    return out


def serving_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the serving layer's events (``serve_request`` / ``serve_batch``
    / ``serve_cache`` / ``serve_retry`` / ``serve_fallback`` / handoff
    ``route``) into one report: request counts by status and lane, latency
    percentiles, batch occupancy, and cache hit/miss — the summarizer-side
    mirror of the loadgen report, but computed from ANY recorded stream
    (a production server's run, not just a load test). Empty dict when the
    run did no serving."""
    reqs = [ev for ev in events if ev.get("type") == "serve_request"]
    batches = [ev for ev in events if ev.get("type") == "serve_batch"]
    caches = [ev for ev in events if ev.get("type") == "serve_cache"]
    retries = [ev for ev in events if ev.get("type") == "serve_retry"]
    fallbacks = [ev for ev in events if ev.get("type") == "serve_fallback"]
    steals = [ev for ev in events if ev.get("type") == "lane_steal"]
    scales = [ev for ev in events if ev.get("type") == "lane_scale"]
    routes = [ev for ev in events if ev.get("type") == "route"
              and ev.get("tool") == "solve_handoff"]
    if not (reqs or batches or caches):
        return {}
    by_status: Dict[str, int] = {}
    by_lane: Dict[str, int] = {}
    lat: List[float] = []
    for ev in reqs:
        st = str(ev.get("status", "?"))
        by_status[st] = by_status.get(st, 0) + 1
        lane = ev.get("lane")
        if lane:
            by_lane[str(lane)] = by_lane.get(str(lane), 0) + 1
        if st == "ok" and isinstance(ev.get("latency_s"), (int, float)):
            lat.append(float(ev["latency_s"]))
    lat.sort()

    def _pct(q: float):
        return lat[min(len(lat) - 1, int(q * len(lat)))] if lat else None

    occ = [float(ev["occupancy"]) for ev in batches
           if isinstance(ev.get("occupancy"), (int, float))]
    cache_counts = {"hit": 0, "miss": 0, "evict": 0}
    for ev in caches:
        k = str(ev.get("event", "?"))
        cache_counts[k] = cache_counts.get(k, 0) + 1
    lookups = cache_counts["hit"] + cache_counts["miss"]
    route_lanes: Dict[str, int] = {}
    for ev in routes:
        lane = str(ev.get("lane", "?"))
        route_lanes[lane] = route_lanes.get(lane, 0) + 1
    # Mesh-plane fold: serve_batch events carry ``lane`` when a LaneSet
    # dispatched them; steal/scale events exist only on the mesh plane.
    mesh_batches: Dict[str, int] = {}
    for ev in batches:
        if ev.get("lane") is not None:
            k = str(ev["lane"])
            mesh_batches[k] = mesh_batches.get(k, 0) + 1
    mesh = {}
    if mesh_batches or steals or scales:
        mesh = {"lane_batches": mesh_batches, "steals": len(steals),
                "stolen_requests": sum(int(ev.get("requests", 0) or 0)
                                       for ev in steals),
                "scale_events": len(scales)}
    return {
        "requests": by_status,
        "lanes": by_lane,
        "mesh": mesh,
        "retries": len(retries),
        "fallbacks": len(fallbacks),
        "latency_s": {"count": len(lat),
                      "mean": sum(lat) / len(lat) if lat else None,
                      "p50": _pct(0.50), "p95": _pct(0.95),
                      "p99": _pct(0.99)},
        "batches": {"count": len(batches),
                    "occupancy_mean": sum(occ) / len(occ) if occ else None},
        "cache": {**cache_counts,
                  "hit_rate": (cache_counts["hit"] / lookups
                               if lookups else None)},
        "handoff_routes": route_lanes,
    }


def _serving_lines(sv: Dict[str, Any]) -> List[str]:
    def _f(v):
        return "-" if v is None else _fmt(round(v, 6) if isinstance(v, float)
                                          else v)

    lines = []
    req = ", ".join(f"{k}={v}" for k, v in sorted(sv["requests"].items()))
    lane = ", ".join(f"{k}={v}" for k, v in sorted(sv["lanes"].items()))
    lines.append(f"  requests: {req or '-'}" + (f"  lanes: {lane}" if lane
                                                else ""))
    lat = sv["latency_s"]
    lines.append(f"  latency s: p50 {_f(lat['p50'])}  p95 {_f(lat['p95'])}  "
                 f"p99 {_f(lat['p99'])}  (n={lat['count']})")
    b = sv["batches"]
    c = sv["cache"]
    lines.append(f"  batches: {b['count']}, mean occupancy "
                 f"{_f(b['occupancy_mean'])}; cache: {c['hit']} hits / "
                 f"{c['miss']} misses (hit-rate {_f(c['hit_rate'])}), "
                 f"{c['evict']} evictions")
    mesh = sv.get("mesh")
    if mesh:
        per = ", ".join(f"L{k}={v}" for k, v in
                        sorted(mesh["lane_batches"].items(),
                               key=lambda kv: int(kv[0])))
        lines.append(f"  mesh: batches by lane: {per or '-'}; "
                     f"{mesh['steals']} steal(s) "
                     f"({mesh['stolen_requests']} request(s)), "
                     f"{mesh['scale_events']} autoscale event(s)")
    if sv["retries"] or sv["fallbacks"]:
        lines.append(f"  degradation: {sv['retries']} retried batch "
                     f"attempt(s), {sv['fallbacks']} fallback-lane trip(s)")
    if sv["handoff_routes"]:
        routes = ", ".join(f"{k} x{v}"
                           for k, v in sorted(sv["handoff_routes"].items()))
        lines.append(f"  solve_handoff routing: {routes}")
    return lines


def durability_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the durable-admission events (``journal`` lifecycle from
    gauss_tpu.serve.durable, ``serve_resume`` recovery reports,
    ``serve_dedup`` idempotent-resubmission hits, ``serve_supervisor``
    watchdog transitions) into one report. Empty dict when the run used no
    journal — journal-off runs carry no durability noise."""
    journal = [ev for ev in events if ev.get("type") == "journal"]
    resumes = [ev for ev in events if ev.get("type") == "serve_resume"]
    # Idempotent dedupe shows up two ways: a journaled-terminal hit emits
    # its terminal ``serve_request`` with deduped=True; an in-flight hit
    # (key already pending) emits a ``serve_dedup`` attach event.
    dedups = ([ev for ev in events if ev.get("type") == "serve_dedup"]
              + [ev for ev in events if ev.get("type") == "serve_request"
                 and ev.get("deduped")])
    sup = [ev for ev in events if ev.get("type") == "serve_supervisor"]
    if not (journal or resumes):
        return {}
    jevents: Dict[str, int] = {}
    torn = 0
    for ev in journal:
        k = str(ev.get("event", "?"))
        jevents[k] = jevents.get(k, 0) + 1
        if k == "torn_tail":
            torn += int(ev.get("dropped", 0) or 0)
    sup_events: Dict[str, int] = {}
    for ev in sup:
        k = str(ev.get("event", "?"))
        sup_events[k] = sup_events.get(k, 0) + 1
    return {
        "journal_events": jevents,
        "torn_dropped": torn,
        "resumes": {"count": len(resumes),
                    "replayed": sum(int(ev.get("replayed", 0) or 0)
                                    for ev in resumes),
                    "expired": sum(int(ev.get("expired", 0) or 0)
                                   for ev in resumes),
                    "clean": sum(1 for ev in resumes if ev.get("clean"))},
        "deduped": len(dedups),
        "supervisor": sup_events,
    }


def _durability_lines(du: Dict[str, Any]) -> List[str]:
    lines = []
    je = ", ".join(f"{k} x{v}"
                   for k, v in sorted(du["journal_events"].items()))
    lines.append(f"  journal: {je or '-'}"
                 + (f"; {du['torn_dropped']} torn record(s) dropped"
                    if du["torn_dropped"] else ""))
    r = du["resumes"]
    lines.append(f"  resumes: {r['count']} ({r['replayed']} replayed, "
                 f"{r['expired']} expired-in-recovery, {r['clean']} clean); "
                 f"{du['deduped']} idempotent dedupe(s)")
    if du["supervisor"]:
        sv = ", ".join(f"{k} x{v}"
                       for k, v in sorted(du["supervisor"].items()))
        lines.append(f"  supervisor: {sv}")
    return lines


def slo_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the live plane's ``alert`` transitions (obs.slo burn-rate
    alerts) into per-SLO fire/clear counts with the last observed burn
    rates. Empty dict when the run raised none — healthy runs carry no
    alert noise."""
    alerts = [ev for ev in events if ev.get("type") == "alert"]
    if not alerts:
        return {}
    per: Dict[str, Dict[str, Any]] = {}
    for ev in alerts:
        name = str(ev.get("slo", "?"))
        s = per.setdefault(name, {"fired": 0, "cleared": 0,
                                  "last_state": None, "worst_burn": 0.0})
        state = str(ev.get("state", "?"))
        if state == "firing":
            s["fired"] += 1
        elif state == "clear":
            s["cleared"] += 1
        s["last_state"] = state
        if isinstance(ev.get("burn_short"), (int, float)):
            s["worst_burn"] = max(s["worst_burn"], float(ev["burn_short"]))
    return {"slos": per,
            "alerts": sum(s["fired"] for s in per.values()),
            "unresolved": sum(1 for s in per.values()
                              if s["last_state"] == "firing")}


def _slo_lines(sl: Dict[str, Any]) -> List[str]:
    lines = [f"  {sl['alerts']} alert(s) fired, "
             f"{sl['unresolved']} still firing at run end"]
    for name, s in sorted(sl["slos"].items()):
        lines.append(f"  {name}: fired x{s['fired']}, cleared "
                     f"x{s['cleared']}, worst short-window burn "
                     f"{_fmt(s['worst_burn'])}x, last state "
                     f"{s['last_state']}")
    return lines


def resilience_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the resilience layer's events (``fault`` injections from
    gauss_tpu.resilience.inject, ``recovery`` ladder steps from
    recover.solve_resilient, ``checkpoint`` save/resume from the
    checkpointed factorization) into one report: injections by site and
    kind, recoveries by rung, escalation/unrecoverable counts, checkpoint
    activity. Empty dict when the run saw none of it — healthy runs carry
    no resilience noise."""
    faults = [ev for ev in events if ev.get("type") == "fault"]
    recov = [ev for ev in events if ev.get("type") == "recovery"]
    ckpts = [ev for ev in events if ev.get("type") == "checkpoint"]
    if not (faults or recov or ckpts):
        return {}
    by_site: Dict[str, int] = {}
    by_kind: Dict[str, int] = {}
    for ev in faults:
        site = str(ev.get("site", "?"))
        kind = str(ev.get("kind", "?"))
        by_site[site] = by_site.get(site, 0) + 1
        by_kind[kind] = by_kind.get(kind, 0) + 1
    recovered_by_rung: Dict[str, int] = {}
    escalations = 0
    unrecoverable = 0
    for ev in recov:
        outcome = ev.get("outcome")
        if outcome == "recovered":
            rung = str(ev.get("rung", "?"))
            recovered_by_rung[rung] = recovered_by_rung.get(rung, 0) + 1
        elif outcome == "escalate":
            escalations += 1
        elif outcome == "unrecoverable":
            unrecoverable += 1
    ckpt_counts: Dict[str, int] = {}
    for ev in ckpts:
        k = str(ev.get("event", "?"))
        ckpt_counts[k] = ckpt_counts.get(k, 0) + 1
    return {
        "injections": {"total": len(faults), "by_site": by_site,
                       "by_kind": by_kind},
        "recoveries": {"total": sum(recovered_by_rung.values()),
                       "by_rung": recovered_by_rung},
        "escalations": escalations,
        "unrecoverable": unrecoverable,
        "checkpoints": ckpt_counts,
    }


def _resilience_lines(rs: Dict[str, Any]) -> List[str]:
    inj = rs["injections"]
    rec = rs["recoveries"]
    lines = []
    sites = ", ".join(f"{k} x{v}" for k, v in sorted(inj["by_site"].items()))
    kinds = ", ".join(f"{k} x{v}" for k, v in sorted(inj["by_kind"].items()))
    lines.append(f"  injected faults: {inj['total']}"
                 + (f"  ({sites})" if sites else ""))
    if kinds:
        lines.append(f"  by kind: {kinds}")
    rungs = ", ".join(f"{k} x{v}" for k, v in sorted(rec["by_rung"].items()))
    lines.append(f"  recoveries: {rec['total']}"
                 + (f"  (by rung: {rungs})" if rungs else "")
                 + f"; {rs['escalations']} escalation step(s), "
                 f"{rs['unrecoverable']} unrecoverable")
    if rs["checkpoints"]:
        ck = ", ".join(f"{k} x{v}"
                       for k, v in sorted(rs["checkpoints"].items()))
        lines.append(f"  checkpoints: {ck}")
    return lines


def sdc_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the ABFT layer's events (``sdc`` checksum-mismatch detections
    from gauss_tpu.resilience.abft, ``sdc_inject`` on-device corruption
    injections) into one report: detections by engine and action
    (replay / escalate / correct / recompute), injected on-device faults,
    worst mismatch magnitude, and detection-latency stats. Empty dict when
    the run saw none of it — healthy runs carry no SDC noise."""
    dets = [ev for ev in events if ev.get("type") == "sdc"]
    injs = [ev for ev in events if ev.get("type") == "sdc_inject"]
    if not (dets or injs):
        return {}
    by_engine: Dict[str, int] = {}
    by_action: Dict[str, int] = {}
    lat = []
    max_mag = 0.0
    for ev in dets:
        eng = str(ev.get("engine", "?"))
        act = str(ev.get("action", "?"))
        by_engine[eng] = by_engine.get(eng, 0) + 1
        by_action[act] = by_action.get(act, 0) + 1
        if isinstance(ev.get("latency_s"), (int, float)):
            lat.append(float(ev["latency_s"]))
        mag = ev.get("magnitude")
        if isinstance(mag, (int, float)) and mag == mag:
            max_mag = max(max_mag, float(mag))
    inj_by_site: Dict[str, int] = {}
    for ev in injs:
        site = str(ev.get("site", "?"))
        inj_by_site[site] = inj_by_site.get(site, 0) + 1
    out = {
        "detections": {"total": len(dets), "by_engine": by_engine,
                       "by_action": by_action},
        "injected": {"total": len(injs), "by_site": inj_by_site},
        "max_magnitude": max_mag,
    }
    if lat:
        out["detect_latency_s"] = {
            "mean": round(sum(lat) / len(lat), 6),
            "max": round(max(lat), 6),
        }
    return out


def _sdc_lines(sd: Dict[str, Any]) -> List[str]:
    det = sd["detections"]
    inj = sd["injected"]
    engines = ", ".join(f"{k} x{v}"
                        for k, v in sorted(det["by_engine"].items()))
    actions = ", ".join(f"{k} x{v}"
                        for k, v in sorted(det["by_action"].items()))
    lines = [f"  detections: {det['total']}"
             + (f"  ({engines})" if engines else "")
             + (f"; actions: {actions}" if actions else "")]
    if inj["total"]:
        sites = ", ".join(f"{k} x{v}"
                          for k, v in sorted(inj["by_site"].items()))
        lines.append(f"  injected on-device faults: {inj['total']}"
                     + (f"  ({sites})" if sites else ""))
    lines.append(f"  worst |mismatch|: {_fmt(sd['max_magnitude'])}")
    if "detect_latency_s" in sd:
        ls = sd["detect_latency_s"]
        lines.append(f"  detect latency: mean {_fmt(ls['mean'])} s, "
                     f"max {_fmt(ls['max'])} s")
    return lines


def structure_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the structure router's events (``structure`` detections /
    routing tags, ``structure_solve`` engine outcomes) into per-structure
    lanes: what was detected, what was routed, which engine actually
    served, and how often a route demoted to general LU. Empty dict when
    the run routed nothing."""
    dets = [ev for ev in events if ev.get("type") == "structure"]
    solves = [ev for ev in events if ev.get("type") == "structure_solve"]
    if not (dets or solves):
        return {}
    detected: Dict[str, int] = {}
    routed: Dict[str, int] = {}
    for ev in dets:
        d = str(ev.get("detected", "?"))
        t = str(ev.get("tag", "?"))
        detected[d] = detected.get(d, 0) + 1
        routed[t] = routed.get(t, 0) + 1
    engines: Dict[str, int] = {}
    demotions = 0
    rels: List[float] = []
    for ev in solves:
        eng = str(ev.get("engine", "?"))
        engines[eng] = engines.get(eng, 0) + 1
        if ev.get("demoted"):
            demotions += 1
        if isinstance(ev.get("rel_residual"), (int, float)):
            rels.append(float(ev["rel_residual"]))
    return {
        "detected": detected, "routed": routed, "engines": engines,
        "solves": len(solves), "demotions": demotions,
        "worst_rel_residual": max(rels) if rels else None,
    }


def _structure_lines(st: Dict[str, Any]) -> List[str]:
    lines = []
    det = ", ".join(f"{k} x{v}" for k, v in sorted(st["detected"].items()))
    lines.append(f"  detected: {det or '-'}")
    eng = ", ".join(f"{k} x{v}" for k, v in sorted(st["engines"].items()))
    lines.append(f"  lanes: {eng or '-'}  ({st['solves']} solve(s), "
                 f"{st['demotions']} demotion(s) to general LU)")
    if st["worst_rel_residual"] is not None:
        lines.append(f"  worst rel residual: "
                     f"{_fmt(st['worst_rel_residual'])}")
    return lines


def sparse_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the Krylov plane's ``sparse_solve`` attempts
    (gauss_tpu.sparse.solve) into per-method lanes: attempts, converged
    count, iteration totals, worst verified residual, and how many
    attempts ran on a Gershgorin-certified operand. Empty dict when the
    run never touched the sparse plane."""
    solves = [ev for ev in events if ev.get("type") == "sparse_solve"]
    if not solves:
        return {}
    methods: Dict[str, Dict[str, Any]] = {}
    certified = 0
    rels: List[float] = []
    for ev in solves:
        m = methods.setdefault(str(ev.get("method", "?")), {
            "attempts": 0, "converged": 0, "iterations": 0,
            "wall_s": 0.0, "preconds": {}})
        m["attempts"] += 1
        if ev.get("converged"):
            m["converged"] += 1
        m["iterations"] += int(ev.get("iterations", 0) or 0)
        m["wall_s"] += float(ev.get("wall_s", 0.0) or 0.0)
        pk = str(ev.get("precond", "none"))
        m["preconds"][pk] = m["preconds"].get(pk, 0) + 1
        if ev.get("certified_spd"):
            certified += 1
        if ev.get("converged") and isinstance(ev.get("rel_residual"),
                                              (int, float)):
            rels.append(float(ev["rel_residual"]))
    return {
        "methods": methods, "attempts": len(solves),
        "certified_spd": certified,
        "max_n": max(int(ev.get("n", 0) or 0) for ev in solves),
        "max_nnz": max(int(ev.get("nnz", 0) or 0) for ev in solves),
        "worst_rel_residual": max(rels) if rels else None,
    }


def _sparse_lines(sp: Dict[str, Any]) -> List[str]:
    lines = []
    for name, m in sorted(sp["methods"].items()):
        pre = ", ".join(f"{k} x{v}"
                        for k, v in sorted(m["preconds"].items()))
        lines.append(f"  {name}: {m['converged']}/{m['attempts']} converged, "
                     f"{m['iterations']} iter(s), "
                     f"{_fmt(m['wall_s'])} s  [{pre}]")
    lines.append(f"  certified SPD: {sp['certified_spd']}/{sp['attempts']} "
                 f"attempt(s); largest n {sp['max_n']} "
                 f"({sp['max_nnz']} nnz)")
    if sp["worst_rel_residual"] is not None:
        lines.append(f"  worst converged rel residual: "
                     f"{_fmt(sp['worst_rel_residual'])}")
    return lines


def utilization_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the attribution plane's ``attr`` cell observations
    (gauss_tpu.obs.attr) into one report: device-seconds by phase and by
    engine, achieved-vs-peak roofline fractions per engine (against the
    peaks the run's ``attr_plane`` event recorded), seconds-weighted stall
    fractions, and amortized compile-seconds. Empty dict when the run had
    no attribution plane — attr-off streams carry no utilization noise."""
    cells = [ev for ev in events if ev.get("type") == "attr"]
    if not cells:
        return {}
    plane = next((ev for ev in events if ev.get("type") == "attr_plane"), {})
    peak_f = plane.get("flops_per_s")
    peak_b = plane.get("bytes_per_s")
    by_phase: Dict[str, Dict[str, float]] = {}
    engines: Dict[str, Dict[str, float]] = {}
    compile_s = 0.0
    for ev in cells:
        s = float(ev.get("seconds", 0.0) or 0.0)
        ph = by_phase.setdefault(str(ev.get("phase", "?")),
                                 {"seconds": 0.0, "calls": 0, "requests": 0})
        ph["seconds"] += s
        ph["calls"] += 1
        ph["requests"] += int(ev.get("requests", 0) or 0)
        eng = engines.setdefault(str(ev.get("engine", "?")),
                                 {"seconds": 0.0, "flops": 0.0,
                                  "bytes": 0.0, "stall_s": 0.0,
                                  "stall_w": 0.0})
        eng["seconds"] += s
        if isinstance(ev.get("flops"), (int, float)):
            eng["flops"] += float(ev["flops"])
        if isinstance(ev.get("bytes"), (int, float)):
            eng["bytes"] += float(ev["bytes"])
        if isinstance(ev.get("stall_frac"), (int, float)):
            eng["stall_s"] += float(ev["stall_frac"]) * s
            eng["stall_w"] += s
        if isinstance(ev.get("compile_s"), (int, float)):
            compile_s += float(ev["compile_s"])
    roofline: Dict[str, Dict[str, Any]] = {}
    for name, e in engines.items():
        row: Dict[str, Any] = {"device_s": round(e["seconds"], 6)}
        if e["seconds"] > 0 and e["flops"]:
            row["achieved_flops_per_s"] = round(e["flops"] / e["seconds"], 3)
            if isinstance(peak_f, (int, float)) and peak_f > 0:
                row["flops_frac"] = round(
                    row["achieved_flops_per_s"] / peak_f, 6)
        if e["seconds"] > 0 and e["bytes"]:
            row["achieved_bytes_per_s"] = round(e["bytes"] / e["seconds"], 3)
            if isinstance(peak_b, (int, float)) and peak_b > 0:
                row["bytes_frac"] = round(
                    row["achieved_bytes_per_s"] / peak_b, 6)
        if e["stall_w"] > 0:
            row["stall_frac"] = round(e["stall_s"] / e["stall_w"], 4)
        roofline[name] = row
    return {
        "observes": len(cells),
        "device_s_total": round(sum(e["seconds"]
                                    for e in engines.values()), 6),
        "compile_s": round(compile_s, 6),
        "by_phase": {k: {"seconds": round(v["seconds"], 6),
                         "calls": int(v["calls"]),
                         "requests": int(v["requests"])}
                     for k, v in by_phase.items()},
        "roofline": roofline,
        "peaks": ({"flops_per_s": peak_f, "bytes_per_s": peak_b,
                   "source": plane.get("source")} if plane else None),
    }


def _utilization_lines(ut: Dict[str, Any]) -> List[str]:
    lines = [f"  {ut['observes']} observation(s), "
             f"{_fmt(ut['device_s_total'])} device-s attributed, "
             f"{_fmt(ut['compile_s'])} s amortized compile"]
    for ph, d in sorted(ut["by_phase"].items(),
                        key=lambda kv: -kv[1]["seconds"]):
        lines.append(f"  {ph}: {d['seconds']:.6f} s over {d['calls']} "
                     f"call(s), {d['requests']} request(s)")
    for eng, row in sorted(ut["roofline"].items()):
        bits = [f"device_s {_fmt(row['device_s'])}"]
        if "achieved_flops_per_s" in row:
            bits.append(f"{_fmt(row['achieved_flops_per_s'])} flop/s")
        if "flops_frac" in row:
            bits.append(f"{100 * row['flops_frac']:.2f}% of peak flops")
        if "bytes_frac" in row:
            bits.append(f"{100 * row['bytes_frac']:.2f}% of peak bytes")
        if "stall_frac" in row:
            bits.append(f"stall {_fmt(row['stall_frac'])}")
        lines.append(f"  engine {eng}: " + ", ".join(bits))
    if ut.get("peaks"):
        p = ut["peaks"]
        lines.append(f"  peaks ({p.get('source', '?')}): "
                     f"{_fmt(p.get('flops_per_s'))} flop/s, "
                     f"{_fmt(p.get('bytes_per_s'))} B/s — CPU-proxy "
                     f"calibration, not chip datasheet numbers")
    return lines


def postmortem_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold ``postmortem`` capture events (gauss_tpu.obs.postmortem) and
    ``flight`` recorder lifecycle events into one report: bundles captured
    by cause, open-trace / in-flight counts at capture, and the last
    bundle's path — the pointer ``gauss-debug`` starts from. Empty dict
    when the run captured nothing — healthy runs carry no crash noise."""
    caps = [ev for ev in events if ev.get("type") == "postmortem"]
    fl = [ev for ev in events if ev.get("type") == "flight"]
    if not caps:
        return {}
    by_cause: Dict[str, int] = {}
    for ev in caps:
        cause = str(ev.get("cause", "?"))
        by_cause[cause] = by_cause.get(cause, 0) + 1
    last = caps[-1]
    return {
        "bundles": len(caps),
        "by_cause": by_cause,
        "open_traces": sum(int(ev.get("open_traces", 0) or 0)
                           for ev in caps),
        "in_flight": sum(int(ev.get("in_flight", 0) or 0) for ev in caps),
        "last_bundle": last.get("bundle"),
        "last_cause": last.get("cause"),
        "recording": bool(fl),
    }


def _postmortem_lines(pm: Dict[str, Any]) -> List[str]:
    causes = ", ".join(f"{k} x{v}"
                       for k, v in sorted(pm["by_cause"].items()))
    lines = [f"  {pm['bundles']} bundle(s) captured"
             + (f"  ({causes})" if causes else "")
             + f"; {pm['in_flight']} request(s) in flight, "
             f"{pm['open_traces']} open trace(s) at capture"]
    if pm["last_bundle"]:
        lines.append(f"  last: {pm['last_bundle']} "
                     f"(cause={pm['last_cause']}; inspect with gauss-debug)")
    return lines


def fleet_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the fleet supervisor's events (``fleet``: launch / worker_dead /
    worker_stalled / restart / shrink / local_finish / done, plus worker-side
    peer_lost) and ``watchdog`` deadline trips into one report: failure
    detections by cause, restarts and shrinks, the elastic rung reached,
    and resume latency. Empty dict when the run supervised nothing."""
    fl = [ev for ev in events if ev.get("type") == "fleet"]
    wd = [ev for ev in events if ev.get("type") == "watchdog"]
    if not (fl or wd):
        return {}
    by_event: Dict[str, int] = {}
    deaths_by_cause: Dict[str, int] = {}
    for ev in fl:
        k = str(ev.get("event", "?"))
        by_event[k] = by_event.get(k, 0) + 1
        if k == "worker_dead":
            cause = str(ev.get("cause", "?"))
            deaths_by_cause[cause] = deaths_by_cause.get(cause, 0) + 1
    dones = [ev for ev in fl if ev.get("event") == "done"]
    last = dones[-1] if dones else {}
    return {
        "events": by_event,
        "deaths": {"total": by_event.get("worker_dead", 0),
                   "by_cause": deaths_by_cause},
        "stalls": by_event.get("worker_stalled", 0),
        "restarts": by_event.get("restart", 0),
        "shrinks": by_event.get("shrink", 0),
        "local_finishes": by_event.get("local_finish", 0),
        "watchdog_timeouts": len(wd),
        "solves": len(dones),
        "rung": last.get("rung"),
        "resume_latency_s": last.get("resume_latency_s"),
    }


def _fleet_lines(fs: Dict[str, Any]) -> List[str]:
    lines = []
    causes = ", ".join(f"{k} x{v}"
                       for k, v in sorted(fs["deaths"]["by_cause"].items()))
    lines.append(f"  worker deaths: {fs['deaths']['total']}"
                 + (f"  ({causes})" if causes else "")
                 + f"; {fs['stalls']} stall detection(s), "
                 f"{fs['watchdog_timeouts']} watchdog timeout(s)")
    lines.append(f"  recovery: {fs['restarts']} restart(s), "
                 f"{fs['shrinks']} shrink(s), "
                 f"{fs['local_finishes']} local finish(es)")
    if fs["solves"]:
        tail = f"  supervised solves: {fs['solves']}"
        if fs["rung"]:
            tail += f", last rung {fs['rung']}"
        if isinstance(fs["resume_latency_s"], (int, float)):
            tail += f", resume latency {_fmt(fs['resume_latency_s'])} s"
        lines.append(tail)
    return lines


def replica_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the network replica tier's events (``router`` lifecycle from
    gauss_tpu.serve.router, per-replica ``replica`` spawn/listen/drain,
    ``replica_adopt`` journal adoptions, ``replica_failover`` handoff
    reports, and ``replica_campaign`` chaos-audit verdicts) into one
    report. Empty dict when the run served no replica fleet."""
    router = [ev for ev in events if ev.get("type") == "router"]
    replicas = [ev for ev in events if ev.get("type") == "replica"]
    adopts = [ev for ev in events if ev.get("type") == "replica_adopt"]
    fails = [ev for ev in events if ev.get("type") == "replica_failover"]
    camps = [ev for ev in events if ev.get("type") == "replica_campaign"]
    if not (router or replicas or fails or camps):
        return {}
    revents: Dict[str, int] = {}
    for ev in router:
        k = str(ev.get("event", "?"))
        revents[k] = revents.get(k, 0) + 1
    fail_causes: Dict[str, int] = {}
    recoveries = []
    for ev in fails:
        cause = str(ev.get("cause", "?"))
        fail_causes[cause] = fail_causes.get(cause, 0) + 1
        if isinstance(ev.get("recovery_s"), (int, float)):
            recoveries.append(float(ev["recovery_s"]))
    out: Dict[str, Any] = {
        "router_events": revents,
        "replica_events": len(replicas),
        "failovers": {
            "count": len(fails),
            "by_cause": fail_causes,
            "pins_moved": sum(int(ev.get("pins_moved", 0) or 0)
                              for ev in fails),
            "replayed": sum(int(ev.get("replayed", 0) or 0)
                            for ev in fails),
            "imported": sum(int(ev.get("imported", 0) or 0)
                            for ev in fails),
            "expired": sum(int(ev.get("expired", 0) or 0) for ev in fails),
            "max_recovery_s": max(recoveries) if recoveries else None,
        },
        "adoptions": len(adopts),
    }
    if camps:
        last = camps[-1]
        out["campaign"] = {k: last.get(k)
                           for k in ("cases", "admitted", "case_violations",
                                     "replayed_on_peer",
                                     "expired_in_failover",
                                     "invariant_ok")
                           if last.get(k) is not None}
        cv = out["campaign"].get("case_violations")
        if isinstance(cv, list):
            # The campaign event carries the violating cases themselves;
            # the summary only needs how many there were.
            out["campaign"]["case_violations"] = len(cv)
    return out


def _replica_lines(rp: Dict[str, Any]) -> List[str]:
    lines = []
    re_ = ", ".join(f"{k} x{v}"
                    for k, v in sorted(rp["router_events"].items()))
    lines.append(f"  router: {re_ or '-'}; "
                 f"{rp['replica_events']} replica event(s)")
    fo = rp["failovers"]
    if fo["count"]:
        causes = ", ".join(f"{k} x{v}"
                           for k, v in sorted(fo["by_cause"].items()))
        tail = (f"  failovers: {fo['count']}  ({causes}); "
                f"{fo['pins_moved']} pin(s) moved, "
                f"{fo['replayed']} replayed, {fo['imported']} imported, "
                f"{fo['expired']} expired-in-failover")
        if isinstance(fo["max_recovery_s"], (int, float)):
            tail += f"; worst recovery {_fmt(fo['max_recovery_s'])} s"
        lines.append(tail)
    if rp["adoptions"]:
        lines.append(f"  adoptions: {rp['adoptions']} journal(s) adopted")
    camp = rp.get("campaign")
    if camp:
        kv = " ".join(f"{k}={_fmt(v)}" for k, v in camp.items())
        lines.append(f"  campaign: {kv}")
    return lines


def poison_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the poison-isolation plane's events into one report: typed
    poison terminals (``serve.poisoned``), batch bisections
    (``serve_bisect``), quarantine decisions (``quarantine`` events /
    ``serve.quarantined``), non-finite batch-member rescues, uncharged
    quarantined deaths, and the latest ``poison_campaign`` verdict.
    Empty dict when the run saw no poison activity."""
    counters = {ev.get("name"): ev.get("value") for ev in events
                if ev.get("type") == "metric"
                and ev.get("kind") == "counter"}
    quar = [ev for ev in events if ev.get("type") == "quarantine"]
    bisects = [ev for ev in events if ev.get("type") == "serve_bisect"]
    camps = [ev for ev in events if ev.get("type") == "poison_campaign"]

    def _c(name: str) -> int:
        return int(counters.get(name, 0) or 0)

    poisoned = _c("serve.poisoned")
    quarantined = _c("serve.quarantined")
    rescues = _c("serve.nonfinite_rescues")
    free_deaths = (_c("serve.quarantined_respawns")
                   + _c("router.quarantined_deaths"))
    if not (poisoned or quarantined or rescues or free_deaths
            or quar or bisects or camps):
        return {}
    out: Dict[str, Any] = {
        "poisoned": poisoned,
        "quarantined": quarantined,
        "bisections": {
            "count": len(bisects),
            "requests": sum(int(ev.get("requests", 0) or 0)
                            for ev in bisects),
        },
        "nonfinite_rescues": rescues,
        "quarantined_deaths_uncharged": free_deaths,
        "quarantine_events": [
            {k: ev.get(k) for k in ("id", "rid", "trace", "deaths",
                                    "action", "adopted")
             if ev.get(k) is not None}
            for ev in quar],
    }
    if camps:
        last = camps[-1]
        out["campaign"] = {k: last.get(k)
                           for k in ("cases", "innocents_verified",
                                     "culprits_typed", "violations",
                                     "crash_loops", "invariant_ok")
                           if last.get(k) is not None}
    return out


def _poison_lines(po: Dict[str, Any]) -> List[str]:
    bi = po["bisections"]
    lines = [
        f"  typed rejects: {po['poisoned']} poisoned, "
        f"{po['quarantined']} quarantined, "
        f"{po['nonfinite_rescues']} non-finite batch-member rescue(s)",
        f"  bisections: {bi['count']} split(s) over "
        f"{bi['requests']} batched request(s)",
    ]
    if po["quarantined_deaths_uncharged"]:
        lines.append(f"  deaths reclassified quarantined (budget "
                     f"uncharged): {po['quarantined_deaths_uncharged']}")
    for ev in po["quarantine_events"]:
        lines.append("  quarantine: " + _event_kv(ev))
    camp = po.get("campaign")
    if camp:
        kv = " ".join(f"{k}={_fmt(v)}" for k, v in camp.items())
        lines.append(f"  campaign: {kv}")
    return lines


def tuning_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the autotuner's events into one report: store consults with
    their provenance (``tune`` events: source=store|seed, reason on
    degraded fallbacks), sweep activity (``tune_sweep`` points/prunes),
    and the persistent-XLA-cache counters (``xla.cache_*`` — a miss is a
    real backend compile, a hit is a compile avoided). Empty dict when
    the run touched none of it."""
    tune = [ev for ev in events if ev.get("type") == "tune"]
    sweep = [ev for ev in events if ev.get("type") == "tune_sweep"]
    counters = {ev.get("name"): ev.get("value") for ev in events
                if ev.get("type") == "metric"
                and ev.get("kind") == "counter"}
    hits = counters.get("tune.store_hits", 0)
    misses = counters.get("tune.store_misses", 0)
    xla = {k.split(".", 1)[1]: int(v) for k, v in counters.items()
           if k and str(k).startswith("xla.")}
    if not (tune or sweep or xla):
        return {}
    consults = [{k: ev.get(k) for k in ("key", "source", "params",
                                        "reason", "sweep_run", "dir")
                 if ev.get(k) is not None}
                for ev in tune]
    points = [ev for ev in sweep if ev.get("event") == "point"]
    out: Dict[str, Any] = {
        "store": {"hits": int(hits), "misses": int(misses)},
        "consults": consults,
    }
    if xla:
        out["xla_cache"] = xla
    if sweep:
        out["sweep"] = {
            "points": len(points),
            "pruned": sum(1 for ev in sweep if ev.get("event") == "pruned"),
            "keys": [ev.get("key") for ev in points],
        }
    return out


def _tuning_lines(tn: Dict[str, Any]) -> List[str]:
    st = tn["store"]
    lines = [f"  store: {st['hits']} hit(s) / {st['misses']} miss(es)"]
    for c in tn["consults"]:
        kv = " ".join(f"{k}={_fmt(v)}" for k, v in c.items() if k != "key")
        lines.append(f"    {c.get('key', '?')}: {kv}")
    xla = tn.get("xla_cache")
    if xla:
        lines.append(f"  xla compile cache: "
                     f"{xla.get('cache_hits', 0)} hit(s) / "
                     f"{xla.get('cache_misses', 0)} compile(s)")
    sw = tn.get("sweep")
    if sw:
        lines.append(f"  sweep: {sw['points']} point(s) "
                     f"({', '.join(str(k) for k in sw['keys'] if k)}), "
                     f"{sw['pruned']} candidate(s) pruned early")
    return lines


def _human_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"


def _comms_lines(comms: Dict[str, Any]) -> List[str]:
    lines = []
    for label in sorted(comms):
        eng = comms[label]
        ops = ", ".join(
            f"{op} x{d['count']} ({_human_bytes(d['bytes'])})"
            for op, d in sorted(eng["ops"].items()))
        lines.append(f"  {label}: {ops}")
        lines.append(f"    total {eng['count']} collectives, "
                     f"{_human_bytes(eng['bytes'])} payload")
    return lines


_SKIP_FIELDS = {"type", "run", "seq", "t", "t_aligned", "proc"}


def _event_kv(ev: Dict[str, Any], skip=()) -> str:
    return " ".join(f"{k}={_fmt(v)}" for k, v in ev.items()
                    if k not in _SKIP_FIELDS and k not in skip
                    and v is not None)


def _strip(ev: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in ev.items()
            if k not in ("run", "seq") and v is not None}


def run_summary(events: List[Dict[str, Any]], run_id: str) -> Dict[str, Any]:
    """The complete machine-readable summary of one run — the ``--json``
    payload, and the single source the text renderer draws from."""
    evs = [ev for ev in events if ev.get("run") == run_id]
    start = next((ev for ev in evs if ev.get("type") == "run_start"), {})
    env = {k: start[k] for k in registry.ENV_FINGERPRINT_KEYS if k in start}
    meta = {k: v for k, v in start.items()
            if k not in _SKIP_FIELDS and k not in env
            and k not in ("time_unix", "schema")}
    return {
        "run": run_id,
        "meta": meta,
        "environment": env,
        "processes": _procs(evs),
        "reported": [_strip(ev) for ev in evs
                     if ev.get("type") == "reported_time"],
        "profile": flat_profile(evs),
        "health": [_strip(ev) for ev in evs if ev.get("type") == "health"],
        "serving": serving_summary(evs),
        "durability": durability_summary(evs),
        "poison": poison_summary(evs),
        "slo": slo_summary(evs),
        "structure": structure_summary(evs),
        "sparse": sparse_summary(evs),
        "utilization": utilization_summary(evs),
        "resilience": resilience_summary(evs),
        "sdc": sdc_summary(evs),
        "postmortems": postmortem_summary(evs),
        "fleet": fleet_summary(evs),
        "replica": replica_summary(evs),
        "tuning": tuning_summary(evs),
        "comms": comms_summary(evs),
        "compile": [_strip(ev) for ev in evs
                    if ev.get("type") in ("compile", "cost")],
        "vmem": [_strip(ev) for ev in evs
                 if ev.get("type") == "vmem_estimate"],
        "cells": [_strip(ev) for ev in evs if ev.get("type") == "cell"],
        "metrics": [_strip(ev) for ev in evs if ev.get("type") == "metric"
                    and not str(ev.get("name", "")).startswith("span.")],
    }


def summarize_run(events: List[Dict[str, Any]], run_id: str) -> str:
    evs = [ev for ev in events if ev.get("run") == run_id]
    out = []
    start = next((ev for ev in evs if ev.get("type") == "run_start"), {})
    env_skip = tuple(registry.ENV_FINGERPRINT_KEYS)
    meta = _event_kv(start, skip=("time_unix", "schema") + env_skip)
    out.append(f"run {run_id}" + (f"  [{meta}]" if meta else ""))
    env = {k: start[k] for k in registry.ENV_FINGERPRINT_KEYS if k in start}
    if env:
        out.append("  environment: "
                   + " ".join(f"{k}={_fmt(v)}" for k, v in env.items()))
    procs = _procs(evs)
    if len(procs) > 1:
        out.append(f"  merged multihost stream: {len(procs)} processes "
                   f"{procs}")

    reported = [ev for ev in evs if ev.get("type") == "reported_time"]
    for ev in reported:
        out.append(f"  reported: {ev.get('name')} = "
                   f"{_fmt(ev.get('seconds'))} s")

    prof = flat_profile(evs)
    if prof["phases"]:
        out.append("")
        out.append("flat profile (leaf spans):")
        out.extend(_profile_lines(prof))

    health = [ev for ev in evs if ev.get("type") == "health"]
    if health:
        out.append("")
        out.append("numerical health:")
        for ev in health:
            out.append("  " + _event_kv(ev))

    serving = serving_summary(evs)
    if serving:
        out.append("")
        out.append("serving:")
        out.extend(_serving_lines(serving))

    durability = durability_summary(evs)
    if durability:
        out.append("")
        out.append("durability (request journal):")
        out.extend(_durability_lines(durability))

    poison = poison_summary(evs)
    if poison:
        out.append("")
        out.append("poison isolation:")
        out.extend(_poison_lines(poison))

    slo = slo_summary(evs)
    if slo:
        out.append("")
        out.append("slo burn-rate alerts:")
        out.extend(_slo_lines(slo))

    structure = structure_summary(evs)
    if structure:
        out.append("")
        out.append("structure lanes:")
        out.extend(_structure_lines(structure))

    sparse = sparse_summary(evs)
    if sparse:
        out.append("")
        out.append("sparse (Krylov) solves:")
        out.extend(_sparse_lines(sparse))

    util = utilization_summary(evs)
    if util:
        out.append("")
        out.append("utilization (device-time attribution):")
        out.extend(_utilization_lines(util))

    resilience = resilience_summary(evs)
    if resilience:
        out.append("")
        out.append("resilience:")
        out.extend(_resilience_lines(resilience))

    sdc = sdc_summary(evs)
    if sdc:
        out.append("")
        out.append("sdc (abft checksum detections):")
        out.extend(_sdc_lines(sdc))

    pm = postmortem_summary(evs)
    if pm:
        out.append("")
        out.append("post-mortems:")
        out.extend(_postmortem_lines(pm))

    fleet = fleet_summary(evs)
    if fleet:
        out.append("")
        out.append("fleet:")
        out.extend(_fleet_lines(fleet))

    replica = replica_summary(evs)
    if replica:
        out.append("")
        out.append("replica tier (network serving):")
        out.extend(_replica_lines(replica))

    tuning = tuning_summary(evs)
    if tuning:
        out.append("")
        out.append("tuning:")
        out.extend(_tuning_lines(tuning))

    comms = comms_summary(evs)
    if comms:
        out.append("")
        out.append("collective traffic (per-execution budget):")
        out.extend(_comms_lines(comms))

    compiles = [ev for ev in evs if ev.get("type") in ("compile", "cost")]
    if compiles:
        out.append("")
        out.append("compile / cost accounting:")
        for ev in compiles:
            out.append("  " + _event_kv(ev))

    vmem = [ev for ev in evs if ev.get("type") == "vmem_estimate"]
    if vmem:
        out.append("")
        out.append("VMEM/HBM working-set estimates:")
        for ev in vmem:
            out.append("  " + _event_kv(ev))

    metrics = [ev for ev in evs if ev.get("type") == "metric"
               and not str(ev.get("name", "")).startswith("span.")]
    if metrics:
        out.append("")
        out.append("metrics:")
        for ev in metrics:
            out.append(f"  {ev.get('kind')} " + _event_kv(ev, skip=("kind",)))
    return "\n".join(out)


def summarize_events(events: List[Dict[str, Any]],
                     run_id: Optional[str] = None) -> str:
    run_ids = [run_id] if run_id else _runs(events)
    if not run_ids:
        return "(no runs found)"
    return "\n\n".join(summarize_run(events, rid) for rid in run_ids)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.summarize",
        description="Render a metrics JSONL file (gprof-style flat profile, "
                    "numerical health, collective traffic, compile/memory "
                    "accounting).")
    p.add_argument("path", help="JSONL events file (--metrics-out output)")
    p.add_argument("--run", default=None, help="summarize only this run ID")
    p.add_argument("--json", action="store_true",
                   help="emit the full summary (profile, per-process lanes, "
                        "health, comms, compile, metrics) as JSON keyed by "
                        "run — the machine-readable form CI and obs.regress "
                        "consume")
    args = p.parse_args(argv)
    try:
        events = registry.read_events(args.path)
    except OSError as e:
        print(f"summarize: cannot read '{args.path}': {e}", file=sys.stderr)
        return 1
    if args.run and args.run not in _runs(events):
        print(f"summarize: run '{args.run}' not found; runs: "
              f"{', '.join(_runs(events)) or '(none)'}", file=sys.stderr)
        return 1
    if args.json:
        run_ids = [args.run] if args.run else _runs(events)
        payload = {rid: run_summary(events, rid) for rid in run_ids}
        print(json.dumps(payload, indent=1, sort_keys=True))
        return 0
    print(summarize_events(events, args.run))
    return 0


if __name__ == "__main__":
    sys.exit(main())
