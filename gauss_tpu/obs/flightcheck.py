"""``make flight-check`` — the flight recorder's end-to-end CI gate.

``python -m gauss_tpu.obs.flightcheck [--summary-json PATH]``

Three legs, all CPU, exit 2 on any invariant failure:

1. **Kill mid-load** (skipped by ``--no-subprocess``): a journaled,
   flight-recording server child (``--drive``) is killed with a REAL
   ``SIGKILL`` (kill -9, not ``os._exit``) once its ring shows enough
   dispatched batches; the resume run's ``unclean_resume`` capture must
   leave a bundle from which ``gauss-debug``/:func:`reconstruct` recovers
   the final >= :data:`MIN_BATCHES` batches whose trace ids all
   cross-check against the journal's own records, and whose in-flight
   request set equals the journal's unterminated admits EXACTLY (judged
   against an independent scan taken before the resume run could replay
   them).
2. **Torn tail at every offset**: a ring is written, then for EVERY byte
   offset of its data region the file is truncated-at-offset (zeros
   after — the state a kill mid-write leaves) and re-scanned; the scan
   must never raise and must recover exactly the records fully written
   before the offset — the reader-owns-integrity contract, exhaustively.
3. **Overhead** (``--no-overhead`` to skip): one loadgen plan run
   flight-off then flight-on (same seed, shared executable cache, warm
   pass first); the flight-on seconds-per-request enters history
   (``flight:ring_s_per_request``) and is regress/ratchet-gated like any
   perf metric — the always-on ring getting more expensive gates in CI.
   The off run's timing stays covered by serve-check's band.

The summary is regress-ingestable (``kind: flight_check``). Exit 2 on an
invariant failure, 1 when ``--regress-check`` finds an out-of-band
metric, 0 otherwise. ``make flight-check`` runs the CI configuration;
like the other timing-gated gates it must not run concurrently with them
(Makefile serial-ordering note).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the acceptance bar: the bundle must reconstruct at least this many of
#: the dead process's final batches, with trace ids intact
MIN_BATCHES = 5
#: batches that must be visible in the ring before the SIGKILL lands —
#: comfortably past MIN_BATCHES so the reconstruction bar has margin
KILL_AFTER_BATCHES = MIN_BATCHES + 2


def _system(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


# -- leg 1: SIGKILL mid-load -> bundle -> timeline -------------------------

def _drive_argv(journal: str, flight: str, requests: int,
                seed: int) -> List[str]:
    return [sys.executable, "-m", "gauss_tpu.obs.flightcheck", "--drive",
            "--journal", journal, "--flight", flight,
            "--requests", str(requests), "--seed", str(seed)]


def _ring_batches(flight_dir: str) -> int:
    """serve_batch events currently recoverable from the dir's rings."""
    from gauss_tpu.obs import flight

    return sum(1 for r in flight.scan_dir(flight_dir)
               for ev in r["events"] if ev.get("type") == "serve_batch")


def run_kill_leg(seed: int, gate: float, tmpdir: str,
                 requests: int = 80, attempts: int = 3,
                 log=print) -> Dict:
    """SIGKILL a flight-recording server mid-load; the resume run's
    ``unclean_resume`` bundle must reconstruct the death. Retries when the
    kill raced the drain (the child finished first) — the leg proves a
    MID-LOAD kill, not a lucky clean exit."""
    from gauss_tpu.obs import debug as _gdebug
    from gauss_tpu.obs import postmortem as _postmortem
    from gauss_tpu.serve import durable

    env = {k: v for k, v in os.environ.items() if k != "GAUSS_FAULTS"}
    env.setdefault("JAX_PLATFORMS", "cpu")
    leg: Dict = {"leg": "kill", "attempts": 0}
    t0 = time.perf_counter()
    for attempt in range(attempts):
        leg["attempts"] = attempt + 1
        jd = os.path.join(tmpdir, f"kill-{attempt}.journal")
        fdir = os.path.join(tmpdir, f"kill-{attempt}.flight")
        # A previous run's ring/journal here would satisfy the kill
        # condition instantly and hand the leg a stale bundle — every
        # attempt starts from a clean scene.
        shutil.rmtree(jd, ignore_errors=True)
        shutil.rmtree(fdir, ignore_errors=True)
        proc = subprocess.Popen(
            _drive_argv(jd, fdir, requests, seed + attempt),
            env=env, cwd=_REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        # Kill -9 the moment the ring shows the batch budget: the child
        # queued its whole plan up front, so a healthy run still has most
        # of the backlog in flight here.
        deadline = time.monotonic() + 240.0
        killed = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            if _ring_batches(fdir) >= KILL_AFTER_BATCHES:
                proc.send_signal(signal.SIGKILL)
                killed = True
                break
            time.sleep(0.003)
        try:
            _, err = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:  # pragma: no cover
            proc.kill()
            _, err = proc.communicate()
        leg["child_rc"] = proc.returncode
        if not killed or proc.returncode != -signal.SIGKILL:
            leg["note"] = (f"attempt {attempt}: child exited rc="
                           f"{proc.returncode} before the kill landed")
            if proc.returncode not in (0, -signal.SIGKILL):
                leg["stderr"] = (err or "")[-1500:]
            continue
        # The journal's view of the death, taken BEFORE the resume run can
        # replay the backlog — the independent record the bundle's
        # in-flight set must match exactly.
        st = durable.scan(jd)
        want_in_flight = sorted(a["id"] for a in st.live_admits())
        known_traces = {str(d.get("trace"))
                        for d in list(st.admits.values())
                        + list(st.terminals.values()) if d.get("trace")}
        leg["in_flight_at_death"] = len(want_in_flight)
        # Resume run (no new requests): its start() finds the unterminated
        # admits and captures the 'unclean_resume' bundle under fdir.
        p2 = subprocess.run(_drive_argv(jd, fdir, 0, seed + attempt),
                            env=env, cwd=_REPO, timeout=300,
                            capture_output=True, text=True)
        leg["resume_rc"] = p2.returncode
        if p2.returncode != 0:
            leg["stderr2"] = p2.stderr[-1500:]
        bundle = _postmortem.latest_bundle(
            _postmortem.default_bundles_dir(fdir))
        leg["bundle"] = bundle
        leg["bundle_check_rc"] = (_gdebug.main([bundle, "--check"])
                                  if bundle else None)
        if bundle is None:
            leg["outcome"] = "violation"
            leg["error"] = "no post-mortem bundle captured at resume"
            break
        doc = _postmortem.read_bundle(bundle)
        rec = _gdebug.reconstruct(doc, batches=MIN_BATCHES)
        leg["cause"] = rec.get("cause")
        leg["batches_reconstructed"] = len(rec["last_batches"])
        batch_traces = [str(t) for ev in rec["last_batches"]
                        for t in (ev.get("traces") or ())]
        leg["trace_ids_ok"] = (bool(batch_traces)
                               and all(t in known_traces
                                       for t in batch_traces))
        got_in_flight = sorted(a.get("id") for a in rec["in_flight"])
        leg["in_flight_match"] = got_in_flight == want_in_flight
        problems = []
        if rec.get("cause") != "unclean_resume":
            problems.append(f"cause {rec.get('cause')!r}")
        if leg["bundle_check_rc"] != 0:
            problems.append("gauss-debug --check failed")
        if leg["batches_reconstructed"] < MIN_BATCHES:
            problems.append(f"only {leg['batches_reconstructed']} "
                            f"batch(es) reconstructed (need {MIN_BATCHES})")
        if not leg["trace_ids_ok"]:
            problems.append("batch trace ids do not cross-check against "
                            "the journal")
        if not leg["in_flight_match"]:
            problems.append(f"in-flight set {got_in_flight} != journal "
                            f"unterminated admits {want_in_flight}")
        if p2.returncode != 0:
            problems.append(f"resume run rc={p2.returncode}")
        leg["outcome"] = "violation" if problems else "ok"
        if problems:
            leg["error"] = "; ".join(problems)
        break
    else:
        leg["outcome"] = "violation"
        leg["error"] = (f"kill never landed mid-load in "
                        f"{attempts} attempt(s)")
    leg["wall_s"] = round(time.perf_counter() - t0, 3)
    log(f"  kill leg: {leg['outcome']} "
        f"(attempt {leg['attempts']}, "
        f"{leg.get('batches_reconstructed', 0)} batch(es) reconstructed, "
        f"{leg.get('in_flight_at_death', 0)} in flight at death)")
    return leg


# -- leg 2: torn tail at every offset --------------------------------------

def run_torn_tail_leg(seed: int, tmpdir: str, log=print) -> Dict:
    """The exhaustive torn-tail property: for EVERY offset of the data
    region, a ring cut at that offset (zeros after — what a kill mid-write
    leaves on a fresh ring) must scan without raising to exactly the
    records fully written before the cut. Plus a wrapped-ring damage
    sweep: corruption windows anywhere must never raise and never fake a
    record that was not written."""
    from gauss_tpu.obs import flight

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0xF117)))
    path = os.path.join(tmpdir, "torn.ring")
    if os.path.exists(path):
        os.remove(path)
    ring = flight.FlightRing(path, capacity=flight.MIN_RING_BYTES)
    docs = [{"type": "event", "i": i, "payload": "x" * int(rng.integers(8, 40))}
            for i in range(40)]
    ends: List[Tuple[int, int]] = []  # (end offset in data region, doc idx)
    for i, doc in enumerate(docs):
        assert ring.append(json.dumps(doc, separators=(",", ":")).encode())
        ends.append((ring.wpos, i))
    assert ring.wpos <= ring.capacity, "leg must not wrap — prefix oracle"
    ring.flush()
    blob = open(path, "rb").read()
    hs, wpos = flight.HEADER_SIZE, ring.wpos
    ring.close()
    mismatches: List[str] = []
    checked = 0
    for cut in range(wpos + 1):
        torn = bytearray(blob)
        torn[hs + cut:] = b"\0" * (len(torn) - hs - cut)
        tpath = os.path.join(tmpdir, "torn.cut.ring")
        with open(tpath, "wb") as f:
            f.write(torn)
        events, stats = flight.scan(tpath)  # must never raise
        want = [docs[i] for end, i in ends if end <= cut]
        checked += 1
        if events != want:
            mismatches.append(
                f"cut@{cut}: recovered {len(events)} != expected "
                f"{len(want)} record(s)")
            if len(mismatches) >= 5:
                break
    # Wrapped ring + arbitrary damage windows: recovered events must be a
    # subset of what was written (no fabrication), scan never raises.
    wpath = os.path.join(tmpdir, "torn.wrap.ring")
    if os.path.exists(wpath):
        os.remove(wpath)
    wring = flight.FlightRing(wpath, capacity=flight.MIN_RING_BYTES)
    wdocs = [{"type": "event", "i": i, "p": "y" * int(rng.integers(8, 120))}
             for i in range(200)]
    for doc in wdocs:
        wring.append(json.dumps(doc, separators=(",", ":")).encode())
    assert wring.wpos > wring.capacity, "wrap sweep must actually wrap"
    wring.flush()
    wblob = bytearray(open(wpath, "rb").read())
    wring.close()
    written = {json.dumps(d, sort_keys=True) for d in wdocs}
    for _ in range(64):
        dmg = bytearray(wblob)
        start = hs + int(rng.integers(0, flight.MIN_RING_BYTES - 64))
        width = int(rng.integers(1, 64))
        dmg[start:start + width] = rng.integers(
            0, 256, width, dtype=np.uint8).tobytes()
        with open(wpath + ".dmg", "wb") as f:
            f.write(dmg)
        devents, dstats = flight.scan(wpath + ".dmg")
        checked += 1
        fabricated = [e for e in devents
                      if json.dumps(e, sort_keys=True) not in written]
        if fabricated:
            mismatches.append(f"damage@{start}+{width}: scan fabricated "
                              f"{len(fabricated)} record(s)")
    out = {"leg": "torn_tail", "offsets_checked": checked,
           "records": len(docs), "wrap_records": len(wdocs),
           "mismatches": mismatches,
           "outcome": "violation" if mismatches else "ok"}
    if mismatches:
        out["error"] = "; ".join(mismatches[:3])
    log(f"  torn-tail leg: {out['outcome']} ({checked} cut/damage "
        f"case(s), {len(mismatches)} mismatch(es))")
    return out


# -- leg 3: the ring's measured overhead -----------------------------------

def run_overhead_leg(seed: int, gate: float, tmpdir: str,
                     cache=None, log=print) -> Dict:
    """The recorder's cost, measured: one loadgen plan run flight-off then
    flight-on (same seed, shared executable cache, unmeasured warm pass so
    neither run pays compiles). The flight-on seconds-per-request enters
    history and is regress/ratchet-gated."""
    from gauss_tpu import obs
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.loadgen import LoadgenConfig, run_load
    from gauss_tpu.serve.server import SolverServer

    def _cfg(flight_dir):
        return ServeConfig(ladder=(32,), max_batch=4, panel=16,
                           refine_steps=1, verify_gate=gate,
                           max_queue=256, flight_dir=flight_dir)

    results: Dict = {"leg": "overhead"}
    warm = LoadgenConfig(mix="random:24*2,random:30", requests=24,
                         warmup=4, mode="closed", concurrency=4,
                         seed=seed, verify_gate=gate, serve=_cfg(None))
    with obs.span("flight_overhead_warm"):
        with SolverServer(warm.serve, cache=cache) as srv:
            run_load(srv, warm)
    for label, fdir in (("off", None),
                        ("on", os.path.join(tmpdir, "overhead.flight"))):
        cfg = LoadgenConfig(mix="random:24*2,random:30", requests=24,
                            warmup=4, mode="closed", concurrency=4,
                            seed=seed, verify_gate=gate, serve=_cfg(fdir))
        # Best-of-2 per arm: a straggler batch-size executable the warm
        # pass happened not to form compiles in ONE pass; the best pass is
        # the fully-warm cost the ratchet gates, not the compile spike.
        summary = None
        incorrect = 0
        for _ in range(2):
            with obs.span(f"flight_overhead_{label}"):
                with SolverServer(cfg.serve, cache=cache) as srv:
                    s = run_load(srv, cfg)
            incorrect += s["incorrect"]
            if summary is None or (s["throughput_rps"] or 0) > (
                    summary["throughput_rps"] or 0):
                summary = s
        results[label] = {
            "throughput_rps": summary["throughput_rps"],
            "s_per_request": (round(1.0 / summary["throughput_rps"], 6)
                              if summary["throughput_rps"] else None),
            "p50_s": summary["latency_s"]["p50"],
            "incorrect": incorrect,
        }
    off = results["off"]["s_per_request"]
    on = results["on"]["s_per_request"]
    results["overhead_ratio"] = round(on / off, 4) if off and on else None
    results["outcome"] = ("violation"
                          if results["off"]["incorrect"]
                          or results["on"]["incorrect"] else "ok")
    log(f"  overhead leg: flight-off {off} s/req -> flight-on {on} s/req "
        f"(ratio {results['overhead_ratio']})")
    return results


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a flight-check run contributes to
    history. The flight-on absolute cost gates (the on/off RATIO rides in
    the summary only — its sub-ms denominator jitters between epochs,
    which would flake the band, while the numerator is stable); the kill
    campaign's wall-clock gates recovery-tooling cost."""
    out: List[Tuple[str, float, str]] = []
    on = ((summary.get("overhead") or {}).get("on") or {}).get(
        "s_per_request")
    if isinstance(on, (int, float)) and on > 0:
        out.append(("flight:ring_s_per_request", on, "s"))
    wall = (summary.get("kill") or {}).get("wall_s")
    if isinstance(wall, (int, float)) and wall > 0:
        out.append(("flight:kill_to_timeline_s", round(wall, 3), "s"))
    return out


# -- the self-driving server child (--drive) -------------------------------

def drive_main(args) -> int:
    """Subprocess worker: a journaled, flight-recording server fed its
    whole seeded plan up front (a deep backlog, so a SIGKILL anywhere
    mid-run leaves requests in flight). ``--requests 0`` is the resume
    form: replay the dead predecessor's backlog and drain — its start()
    captures the ``unclean_resume`` bundle."""
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.server import SolverServer

    honor_jax_platforms()
    rng = np.random.default_rng(np.random.SeedSequence(
        (args.seed, 0xF117D)))
    cfg = ServeConfig(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
                      verify_gate=args.gate, journal_dir=args.journal,
                      journal_fsync_batch=1, max_queue=256,
                      flight_dir=args.flight)
    srv = SolverServer(cfg)
    srv.start()
    handles = []
    for j in range(args.requests):
        n = 16 + int(rng.integers(0, 13))
        a, b = _system(rng, n)
        handles.append(srv.submit(a, b, request_id=f"f{args.seed}-{j}"))
    for h in handles:
        if h.result(timeout=240.0).status is None:  # pragma: no cover
            return 3
    srv.stop(drain=True, timeout=240.0)
    return 0


# -- gate main --------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.flightcheck",
        description="Flight-recorder gate: SIGKILL a recording server "
                    "mid-load and reconstruct its death from the "
                    "post-mortem bundle; torn-tail-at-every-offset ring "
                    "property; measured ring overhead (regress-gated).")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--tmpdir", default="/tmp/gauss_flight",
                   help="ring/journal scratch directory")
    p.add_argument("--no-subprocess", action="store_true",
                   help="skip the SIGKILL-mid-load leg")
    p.add_argument("--no-overhead", action="store_true",
                   help="skip the flight-off vs flight-on measurement")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append gate records to the regression history "
                        "(default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    # -- the subprocess worker mode ---------------------------------------
    p.add_argument("--drive", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--journal", default=None, help=argparse.SUPPRESS)
    p.add_argument("--flight", default=None, help=argparse.SUPPRESS)
    p.add_argument("--requests", type=int, default=80,
                   help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.drive:
        if not args.journal or not args.flight:
            print("flightcheck --drive needs --journal and --flight",
                  file=sys.stderr)
            return 2
        return drive_main(args)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress
    from gauss_tpu.serve.cache import ExecutableCache

    os.makedirs(args.tmpdir, exist_ok=True)
    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="flight_check",
                 seed=args.seed):
        with obs.span("flight_check"):
            kill = ({} if args.no_subprocess
                    else run_kill_leg(args.seed, args.gate, args.tmpdir,
                                      requests=args.requests))
            torn = run_torn_tail_leg(args.seed, args.tmpdir)
            overhead = ({} if args.no_overhead
                        else run_overhead_leg(args.seed, args.gate,
                                              args.tmpdir,
                                              cache=ExecutableCache(64)))
    wall = round(time.perf_counter() - t0, 3)
    legs = [leg for leg in (kill, torn, overhead) if leg]
    violations = sum(1 for leg in legs if leg.get("outcome") == "violation")
    summary = {"kind": "flight_check", "seed": args.seed,
               "gate": args.gate, "kill": kill, "torn_tail": torn,
               "overhead": overhead, "wall_s": wall,
               "invariant_ok": violations == 0}
    print(f"flight-check: {len(legs)} leg(s), "
          f"{'invariant HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "flightcheck",
                "kind": "flight"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        for r in records:
            rv = regress.evaluate_ratchet(r["metric"], r["value"])
            if rv is not None:
                verdicts.append(rv)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 \
            and violations == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        for leg in legs:
            if leg.get("outcome") == "violation":
                print(f"flightcheck: leg[{leg.get('leg')}] VIOLATION: "
                      f"{leg.get('error')}", file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
