"""Crash-surviving flight recorder: an mmap-backed ring of recent events.

The recorder (:mod:`gauss_tpu.obs.registry`) and the live aggregator
(:mod:`gauss_tpu.obs.live`) both hold their state in process memory, so a
``kill -9`` — the exact fault the durable/fleet chaos campaigns inject on
purpose — destroys every byte of telemetry describing the final seconds.
This module is the third sink next to recorder+live (installed via
:func:`gauss_tpu.obs.spans.set_flight_sink`): every span/event/counter-delta
the hooks already emit is ALSO appended to a fixed-size ring buffer in an
mmap'd file, where it survives the process. A surviving process (the
durable/fleet supervisor, the post-restart server, ``gauss-debug``) harvests
the ring with :func:`scan` and folds the dead process's last seconds into a
post-mortem bundle (:mod:`gauss_tpu.obs.postmortem`).

Ring file layout (all integers little-endian)::

    header (64 bytes)
      [0:8)    magic  b"GAUSFLT1"
      [8:12)   u32    format version (1)
      [16:24)  u64    capacity — data-region bytes
      [24:32)  u64    wpos — logical bytes written (data offset = wpos % cap)
      [32:40)  u64    seq  — records written (monotonic)
      [40:48)  u64    writer pid
      [48:56)  f64    writer start time (unix)
    data (capacity bytes)
      record := marker(4) | u32 len | u64 seq | u32 crc | payload[len]
      marker  = b"\\xf1\\x9a\\x7e\\x01" (non-ASCII, cannot occur in the
                JSON payload — the resync anchor)
      crc     = crc32(seq_le_bytes + payload)

Same torn-tail discipline as the PR-12 request journal: the writer never
trusts its own death to be clean, so the READER carries the integrity
invariant — :func:`scan` walks the data region, accepts only records whose
marker, length, and CRC all check out, resynchronizes on the marker after
any damage, and orders the survivors by embedded ``seq``. A record torn at
the kill offset (or half-overwritten by a later lap of the ring) fails its
CRC and is dropped, counted in ``stats["torn_dropped"]``. Records never
straddle the ring end (the tail is zero-padded instead), so a record's
bytes are always contiguous.

Alongside the ring, a **sidecar** JSON file carries the per-process state a
post-mortem needs but events don't repeat: the environment fingerprint,
the set of trace ids admitted but not yet terminal, the latest gauges
(queue depth, lane occupancy), and a last-alive timestamp. It is rewritten
atomically and throttled (default 0.5 s), so its mtime doubles as a
heartbeat.

Cost contract: with no sink installed (``flight_dir=None`` everywhere) the
hot path is one module-global ``is None`` read — byte-identical pre-flight
behavior. With the sink on, the only hot-path cost is one compact-JSON
encode plus a locked memcpy into the mmap; the flight-check gate measures
this and the serve latency ratchet bounds it end to end.

Stdlib + existing obs machinery only; never imports jax.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

HEADER_MAGIC = b"GAUSFLT1"
HEADER_SIZE = 64
FORMAT_VERSION = 1
RECORD_MARKER = b"\xf1\x9a\x7e\x01"
RECORD_HEADER = struct.Struct("<4sIQI")  # marker, len, seq, crc
DEFAULT_RING_BYTES = 1 << 20
MIN_RING_BYTES = 1 << 12
#: records larger than capacity // 4 are dropped (a single runaway event
#: must not evict the whole recent history it exists to explain)
OVERSIZE_DIVISOR = 4

#: terminal serve_request statuses — a trace leaves the sidecar's
#: "active" set when its request reaches one (mirrors requesttrace).
_TERMINAL_STATUSES = ("ok", "rejected", "expired", "failed", "cancelled")
_MAX_ACTIVE_TRACES = 1024
SIDECAR_WRITE_EVERY_S = 0.5


def ring_path(flight_dir: str, pid: Optional[int] = None) -> str:
    return os.path.join(os.fspath(flight_dir),
                        f"flight.{pid or os.getpid()}.ring")


def sidecar_path(flight_dir: str, pid: Optional[int] = None) -> str:
    return os.path.join(os.fspath(flight_dir),
                        f"flight.{pid or os.getpid()}.state.json")


class FlightRing:
    """The mmap-backed ring. Thread-safe appends; one writer process."""

    def __init__(self, path, capacity: int = DEFAULT_RING_BYTES):
        if capacity < MIN_RING_BYTES:
            raise ValueError(
                f"flight ring capacity must be >= {MIN_RING_BYTES}, "
                f"got {capacity}")
        self.path = os.fspath(path)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        size = HEADER_SIZE + self.capacity
        # O_CREAT without truncation: attaching to an existing ring (a
        # restarted pid reusing its path) keeps the old lap's records.
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if os.fstat(fd).st_size != size:
                os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        if self._mm[:8] != HEADER_MAGIC:
            self._mm[:HEADER_SIZE] = b"\0" * HEADER_SIZE
            self._mm[:8] = HEADER_MAGIC
            struct.pack_into("<I", self._mm, 8, FORMAT_VERSION)
            struct.pack_into("<Q", self._mm, 16, self.capacity)
            struct.pack_into("<Qd", self._mm, 40, os.getpid(), time.time())
        else:
            cap = struct.unpack_from("<Q", self._mm, 16)[0]
            if cap != self.capacity:
                raise ValueError(
                    f"flight ring {self.path} has capacity {cap}, "
                    f"asked for {self.capacity}")
        self.wpos = struct.unpack_from("<Q", self._mm, 24)[0]
        self.seq = struct.unpack_from("<Q", self._mm, 32)[0]
        self.dropped_oversize = 0

    # -- writing ----------------------------------------------------------
    def append(self, payload: bytes) -> bool:
        """Append one record; returns False when dropped as oversize."""
        total = RECORD_HEADER.size + len(payload)
        if total > self.capacity // OVERSIZE_DIVISOR:
            with self._lock:
                self.dropped_oversize += 1
            return False
        with self._lock:
            seq = self.seq
            self.seq += 1
            pos = self.wpos % self.capacity
            if pos + total > self.capacity:
                # Records never straddle the ring end: zero the tail (so a
                # scan resyncs straight past it) and wrap to offset 0.
                pad = self.capacity - pos
                self._mm[HEADER_SIZE + pos:HEADER_SIZE + self.capacity] = (
                    b"\0" * pad)
                self.wpos += pad
                pos = 0
            crc = zlib.crc32(struct.pack("<Q", seq) + payload) & 0xFFFFFFFF
            rec = RECORD_HEADER.pack(RECORD_MARKER, len(payload), seq, crc)
            self._mm[HEADER_SIZE + pos:HEADER_SIZE + pos + total] = (
                rec + payload)
            self.wpos += total
            # Header update LAST: a kill between the data write and here
            # leaves wpos short of the new record, whose CRC still admits
            # it at scan — the reader, not this pointer, owns integrity.
            struct.pack_into("<QQ", self._mm, 24, self.wpos, self.seq)
        return True

    def position(self) -> Dict[str, int]:
        """Where the ring is: logical write offset, records written, size."""
        with self._lock:
            return {"wpos": self.wpos, "seq": self.seq,
                    "capacity": self.capacity,
                    "dropped_oversize": self.dropped_oversize}

    def flush(self) -> None:
        self._mm.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._mm.close()
            except ValueError:  # pragma: no cover — already closed
                pass


def scan(path) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Read every intact record out of a ring file, oldest first.

    Never raises on damage: a torn/overwritten/garbage region is skipped by
    resynchronizing on the record marker, and every marker whose record
    fails its length or CRC check counts as ``torn_dropped``. Returns
    ``(events, stats)`` where events are the decoded JSON payloads sorted
    by their embedded write sequence (the ring's physical order is a lap,
    not a timeline).
    """
    stats: Dict[str, Any] = {"records": 0, "torn_dropped": 0,
                             "wpos": 0, "seq": 0, "capacity": 0, "pid": None}
    try:
        with open(os.fspath(path), "rb") as f:
            blob = f.read()
    except OSError:
        return [], stats
    if len(blob) < HEADER_SIZE or blob[:8] != HEADER_MAGIC:
        return [], stats
    cap = struct.unpack_from("<Q", blob, 16)[0]
    stats["capacity"] = cap
    stats["wpos"] = struct.unpack_from("<Q", blob, 24)[0]
    stats["seq"] = struct.unpack_from("<Q", blob, 32)[0]
    stats["pid"] = struct.unpack_from("<Q", blob, 40)[0]
    data = blob[HEADER_SIZE:HEADER_SIZE + cap]
    found: List[Tuple[int, Dict[str, Any]]] = []
    pos = 0
    end = len(data)
    hsz = RECORD_HEADER.size
    while pos + hsz <= end:
        if data[pos:pos + 4] != RECORD_MARKER:
            nxt = data.find(RECORD_MARKER, pos + 1)
            if nxt < 0:
                break
            pos = nxt
            continue
        _, length, seq, crc = RECORD_HEADER.unpack_from(data, pos)
        body = data[pos + hsz:pos + hsz + length]
        ok = (length <= cap // OVERSIZE_DIVISOR
              and len(body) == length
              and zlib.crc32(struct.pack("<Q", seq) + body) & 0xFFFFFFFF
              == crc)
        doc = None
        if ok:
            try:
                doc = json.loads(body)
            except ValueError:
                doc = None
        if doc is None:
            stats["torn_dropped"] += 1
            pos += 1  # resync: the marker may have been payload of damage
            continue
        found.append((seq, doc))
        pos += hsz + length
    found.sort(key=lambda sd: sd[0])
    stats["records"] = len(found)
    return [doc for _, doc in found], stats


def read_sidecar(path) -> Optional[Dict[str, Any]]:
    """Parse a sidecar state file; None when absent/corrupt (a kill can
    land mid-rename only on exotic filesystems, but never crash a reader
    over it)."""
    try:
        with open(os.fspath(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def scan_dir(flight_dir) -> List[Dict[str, Any]]:
    """Harvest every ring in a flight dir: one entry per ring file with its
    events, scan stats, and sidecar (when present), newest writer last."""
    flight_dir = os.fspath(flight_dir)
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(flight_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("flight.") and name.endswith(".ring")):
            continue
        path = os.path.join(flight_dir, name)
        events, stats = scan(path)
        pid = stats.get("pid")
        entry = {"path": path, "pid": pid, "events": events, "stats": stats,
                 "sidecar": read_sidecar(sidecar_path(flight_dir, pid))
                 if pid else None}
        out.append(entry)
    out.sort(key=lambda e: ((e.get("sidecar") or {}).get("time_unix", 0.0),
                            e["path"]))
    return out


class FlightSink:
    """The third telemetry sink: forwards every hook into the ring.

    Duck-typed like the live sink (``on_counter``/``on_gauge``/
    ``on_histogram``/``on_span``/``on_event``) so
    :func:`gauss_tpu.obs.spans.set_flight_sink` can install it with the
    identical zero-cost-when-absent contract. Counter deltas are recorded
    as written (``inc``), not as totals — the scanner sums them.
    """

    def __init__(self, flight_dir, ring_bytes: int = DEFAULT_RING_BYTES,
                 sidecar_every_s: float = SIDECAR_WRITE_EVERY_S):
        self.flight_dir = os.fspath(flight_dir)
        os.makedirs(self.flight_dir, exist_ok=True)
        self.ring = FlightRing(ring_path(self.flight_dir),
                               capacity=ring_bytes)
        self._sidecar_path = sidecar_path(self.flight_dir)
        self._sidecar_every_s = float(sidecar_every_s)
        self._lock = threading.Lock()
        self._active_traces: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._last_heartbeat: Optional[float] = None
        self._started_unix = time.time()
        self._last_sidecar_write = 0.0
        from gauss_tpu.obs import registry as _registry

        self._env = _registry.environment_fingerprint()
        self._write_sidecar(force=True)

    # -- ring records -----------------------------------------------------
    def _put(self, doc: Dict[str, Any]) -> None:
        try:
            payload = json.dumps(doc, separators=(",", ":"),
                                 default=str).encode()
        except (TypeError, ValueError):  # pragma: no cover — _jsonable'd
            return
        self.ring.append(payload)

    def on_event(self, type_: str, fields: Dict[str, Any]) -> None:
        doc = {"type": type_, "tu": round(time.time(), 3)}
        doc.update(fields)
        self._put(doc)
        self._track(type_, fields)

    def on_counter(self, name: str, inc: float) -> None:
        self._put({"type": "counter", "name": name, "inc": inc,
                   "tu": round(time.time(), 3)})

    def on_gauge(self, name: str, value: float) -> None:
        self._put({"type": "gauge", "name": name, "value": value,
                   "tu": round(time.time(), 3)})
        with self._lock:
            self._gauges[name] = float(value)
        self._maybe_write_sidecar()

    def on_histogram(self, name: str, value: float) -> None:
        self._put({"type": "hist", "name": name, "value": value,
                   "tu": round(time.time(), 3)})

    def on_span(self, name: str, dur_s: float, parent: Optional[str],
                depth: int, attrs: Dict[str, Any]) -> None:
        doc = {"type": "span", "name": name, "dur_s": round(dur_s, 6),
               "parent": parent, "depth": depth, "tu": round(time.time(), 3)}
        doc.update(attrs)
        self._put(doc)

    # -- sidecar ----------------------------------------------------------
    def _track(self, type_: str, fields: Dict[str, Any]) -> None:
        """Maintain the active-trace set and heartbeat from the event flow
        (admit opens a trace; its request's terminal status closes it)."""
        now = time.time()
        if type_ == "serve_admit":
            tid = fields.get("trace")
            if tid and len(self._active_traces) < _MAX_ACTIVE_TRACES:
                with self._lock:
                    self._active_traces[str(tid)] = now
        elif type_ == "serve_request":
            if fields.get("status") in _TERMINAL_STATUSES:
                tid = fields.get("trace")
                if tid:
                    with self._lock:
                        self._active_traces.pop(str(tid), None)
        elif type_ == "serve_batch":
            self._last_heartbeat = now
        self._maybe_write_sidecar()

    def _maybe_write_sidecar(self) -> None:
        now = time.time()
        if now - self._last_sidecar_write < self._sidecar_every_s:
            return
        self._write_sidecar()

    def _write_sidecar(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            if not force and (now - self._last_sidecar_write
                              < self._sidecar_every_s):
                return
            self._last_sidecar_write = now
            doc = {"pid": os.getpid(), "time_unix": round(now, 3),
                   "started_unix": round(self._started_unix, 3),
                   "env": dict(self._env),
                   "active_traces": sorted(self._active_traces),
                   "gauges": dict(self._gauges),
                   "last_heartbeat_unix":
                       round(self._last_heartbeat, 3)
                       if self._last_heartbeat else None,
                   "ring": self.ring.position()}
        tmp = self._sidecar_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, self._sidecar_path)
        except OSError:  # pragma: no cover — telemetry never takes a run down
            pass

    # -- lifecycle --------------------------------------------------------
    def position(self) -> Dict[str, Any]:
        """Ring position + sidecar path (the /snapshot payload)."""
        pos = self.ring.position()
        pos["path"] = self.ring.path
        return pos

    def close(self) -> None:
        self._write_sidecar(force=True)
        self.ring.close()


def install(flight_dir, ring_bytes: int = DEFAULT_RING_BYTES) -> FlightSink:
    """Create a :class:`FlightSink` over ``flight_dir`` and install it as
    the process's flight sink; returns it. One per process — installing
    over an existing sink closes the old one."""
    from gauss_tpu.obs import spans

    sink = FlightSink(flight_dir, ring_bytes=ring_bytes)
    prev = spans.set_flight_sink(sink)
    if prev is not None:
        try:
            prev.close()
        except Exception:  # pragma: no cover
            pass
    return sink


def uninstall() -> None:
    """Remove and close the installed flight sink (no-op when absent)."""
    from gauss_tpu.obs import spans

    prev = spans.set_flight_sink(None)
    if prev is not None:
        prev.close()


#: env channel a supervisor uses to hand its children a flight dir
#: (durable.supervise, the fleet supervisor). Consumed explicitly by
#: :func:`install_from_env` at worker startup — NOT at import, unlike
#: GAUSS_FAULTS: recording is a process decision, not ambient state.
ENV_VAR = "GAUSS_FLIGHT_DIR"


def install_from_env(environ=None) -> Optional[FlightSink]:
    """Install a flight sink when the ``GAUSS_FLIGHT_DIR`` env channel
    names a directory; returns it (None when the channel is unset or the
    install fails — a worker never dies over its telemetry)."""
    environ = os.environ if environ is None else environ
    flight_dir = environ.get(ENV_VAR)
    if not flight_dir:
        return None
    try:
        return install(flight_dir)
    except Exception:  # pragma: no cover — best-effort by design
        return None
