"""Device-time attribution: the per-(phase, executable, lane) cost matrix.

The bench harness has always timed device work honestly (block-until-ready
spans); serving and streaming paid the same discipline but nothing ever
AGGREGATED those measurements by *what executable* ran *where*, or joined
them against the compile-time FLOP/byte budgets ``obs.compile`` computes.
This module is that join: an :class:`AttributionMatrix` installed as a
process-global (the same one-``is None``-read contract the recorder / live
/ flight sinks follow — see ``obs.spans``), fed by the dispatch call sites
(``serve.server``, ``outofcore.stream``), maintaining

- **cells** keyed ``(phase, executable, lane)`` — device-seconds, calls,
  requests, and the executable's FLOP/byte budget;
- **roofline rows** per engine — achieved FLOP/s and bytes/s against the
  calibrated :class:`Peaks`, plus the stall fraction (measured where the
  engine has a ledger — out-of-core — and derived as idle fraction where
  it does not);
- a **capacity model** per compat-sig (``serve.lanes.compat_sig``'s
  bucket/dtype/structure identity): device-seconds per request and the
  estimated sustainable requests/s, which is what the serving tier needs
  to route and autoscale on something better than drain-rate EWMAs.

Every ``observe`` also emits an ``attr`` obs event plus ``util.*`` gauges
and windows through the normal hooks, so the live aggregator / Prometheus
exposition (``gauss_util_*``), the flight ring, and recorded streams all
carry the same series with no second instrumentation path.

**Honest-measurement caveats** (docs/OBSERVABILITY.md "Attribution &
roofline"): spans measure host wall-clock around blocked device work, so
attribution includes dispatch overhead; :func:`calibrate_peaks` measures a
CPU-proxy ceiling (a small matmul / memcopy) unless GAUSS_PEAK_FLOPS /
GAUSS_PEAK_BYTES override it with datasheet numbers — utilization
fractions are honest relative to the *measured* ceiling of this host, not
a TPU roofline, until run on real hardware.

Everything no-ops (one module-global ``is None`` read) when no matrix is
installed, and never raises: attribution must not take down a solve.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, Optional

from gauss_tpu.obs import spans as _spans

#: process-global matrix (same handover discipline as the live/flight
#: sinks: swap under a lock, call sites do one unlocked read).
_state_lock = threading.Lock()
_active: Optional["AttributionMatrix"] = None


def active() -> Optional["AttributionMatrix"]:
    """The installed attribution matrix (None -> attribution no-ops)."""
    return _active


def install(matrix: Optional["AttributionMatrix"]):
    """Install ``matrix`` as the process attribution matrix; returns the
    previous one so callers can restore it (the server's start/stop
    pair). ``None`` uninstalls."""
    global _active
    with _state_lock:
        prev = _active
        _active = matrix
    return prev


def uninstall(previous: Optional["AttributionMatrix"] = None) -> None:
    """Restore ``previous`` (default: uninstall entirely)."""
    install(previous)


# -- hardware ceiling -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Peaks:
    """The roofline ceiling utilization is measured against.

    ``source`` records where the numbers came from: ``"env"`` (the
    GAUSS_PEAK_FLOPS / GAUSS_PEAK_BYTES overrides — use these to pin
    datasheet numbers on real hardware) or ``"measured"`` (the CPU-proxy
    microbenchmark below)."""

    flops_per_s: float
    bytes_per_s: float
    source: str = "measured"

    def to_dict(self) -> Dict[str, Any]:
        return {"flops_per_s": round(self.flops_per_s, 3),
                "bytes_per_s": round(self.bytes_per_s, 3),
                "source": self.source}


_peaks_cache: Optional[Peaks] = None
_peaks_lock = threading.Lock()


def calibrate_peaks(n: int = 192, repeats: int = 3,
                    refresh: bool = False) -> Peaks:
    """Measure (once per process) the ceiling the roofline divides by.

    Env overrides win: GAUSS_PEAK_FLOPS / GAUSS_PEAK_BYTES (floats,
    units FLOP/s and bytes/s). Otherwise a small f32 matmul (BLAS — the
    densest compute this host exposes to numpy) and a buffer copy give a
    measured, honest-for-this-host proxy; on a TPU runtime the overrides
    are how datasheet peaks are pinned. Never raises — a calibration
    failure degrades to a 1.0 ceiling (utilization then reads as raw
    achieved FLOP/s, still monotonic and comparable run-to-run)."""
    global _peaks_cache
    env_f = os.environ.get("GAUSS_PEAK_FLOPS")
    env_b = os.environ.get("GAUSS_PEAK_BYTES")
    if env_f or env_b:
        try:
            return Peaks(flops_per_s=float(env_f or 0) or 1.0,
                         bytes_per_s=float(env_b or 0) or 1.0,
                         source="env")
        except ValueError:
            pass
    with _peaks_lock:
        if _peaks_cache is not None and not refresh:
            return _peaks_cache
        try:
            import numpy as np

            a = np.ones((n, n), dtype=np.float32)
            b = np.ones((n, n), dtype=np.float32)
            a @ b  # warm the BLAS path outside the timed window
            best = float("inf")
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                a @ b
                best = min(best, time.perf_counter() - t0)
            flops = 2.0 * n * n * n / max(best, 1e-9)
            buf = np.ones(4 << 20, dtype=np.uint8)
            best_b = float("inf")
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                buf.copy()
                best_b = min(best_b, time.perf_counter() - t0)
            # A copy reads + writes the buffer once each.
            bps = 2.0 * buf.nbytes / max(best_b, 1e-9)
            _peaks_cache = Peaks(flops_per_s=flops, bytes_per_s=bps)
        except Exception:  # noqa: BLE001 — calibration must not block serving
            _peaks_cache = Peaks(flops_per_s=1.0, bytes_per_s=1.0,
                                 source="fallback")
        return _peaks_cache


def lu_flop_budget(n: int, nrhs: int, batch: int = 1,
                   refine_steps: int = 0) -> float:
    """Analytic FLOP budget for one batched LU factor+solve dispatch —
    the fallback when XLA's ``cost_analysis`` is unavailable for an
    executable (so roofline rows exist for every engine exercised, never
    silently missing). (2/3)n^3 factor + 2n^2·nrhs triangular solves per
    refinement round, per batch member."""
    per = (2.0 / 3.0) * n ** 3 + 2.0 * n * n * nrhs * (1 + refine_steps)
    return per * max(1, batch)


def lu_byte_budget(n: int, nrhs: int, batch: int = 1, itemsize: int = 4,
                   refine_steps: int = 0) -> float:
    """Analytic bytes-touched budget (matrix + rhs, once per refinement
    round plus the factor pass) — same fallback role as
    :func:`lu_flop_budget`."""
    per = (n * n + n * nrhs) * itemsize * (2 + refine_steps)
    return float(per * max(1, batch))


# -- the matrix -------------------------------------------------------------

class AttributionMatrix:
    """Thread-safe per-(phase, executable, lane) device-time accounting.

    One lock around plain dict updates (the live-aggregator discipline);
    ``observe`` is the single write path and additionally forwards the
    measurement as an ``attr`` event + ``util.*`` gauges/windows through
    the obs hooks, so every installed sink sees the same series."""

    def __init__(self, peaks: Optional[Peaks] = None):
        self.peaks = peaks if peaks is not None else calibrate_peaks()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._cells: Dict[tuple, Dict[str, Any]] = {}   # guarded by: self._lock
        self._engines: Dict[str, Dict[str, Any]] = {}   # guarded by: self._lock
        self._lanes: Dict[int, Dict[str, Any]] = {}     # guarded by: self._lock
        self._sigs: Dict[str, Dict[str, Any]] = {}      # guarded by: self._lock
        self.observes = 0                               # guarded by: self._lock

    # -- write path -------------------------------------------------------

    def observe(self, phase: str, exe: str, seconds: float, *,
                engine: str = "blocked", lane: int = 0, requests: int = 1,
                flops: Optional[float] = None,
                bytes_accessed: Optional[float] = None,
                compile_s: float = 0.0, sig: Optional[str] = None,
                stall_frac: Optional[float] = None) -> None:
        """Fold one completed dispatch into the matrix.

        ``seconds`` is the blocked (device-complete) wall of the dispatch;
        ``flops``/``bytes_accessed`` its compile-time budget
        (``obs.compile.cost_summary`` numbers, or the analytic fallback);
        ``compile_s`` any compile/cache-get wall paid to obtain the
        executable; ``sig`` the serving compat-sig the capacity model
        aggregates under; ``stall_frac`` a ledger-measured stall fraction
        (out-of-core) overriding the derived idle fraction."""
        seconds = float(seconds)
        now = time.perf_counter()
        with self._lock:
            self.observes += 1
            cell = self._cells.setdefault(
                (phase, exe, lane),
                {"phase": phase, "exe": exe, "lane": lane, "engine": engine,
                 "seconds": 0.0, "calls": 0, "requests": 0, "flops": 0.0,
                 "bytes": 0.0, "compile_s": 0.0})
            cell["seconds"] += seconds
            cell["calls"] += 1
            cell["requests"] += int(requests)
            cell["compile_s"] += float(compile_s)
            if flops:
                cell["flops"] += float(flops)
            if bytes_accessed:
                cell["bytes"] += float(bytes_accessed)
            eng = self._engines.setdefault(
                engine, {"seconds": 0.0, "calls": 0, "flops": 0.0,
                         "bytes": 0.0, "stall_s": 0.0, "stall_w": 0.0})
            eng["seconds"] += seconds
            eng["calls"] += 1
            if flops:
                eng["flops"] += float(flops)
            if bytes_accessed:
                eng["bytes"] += float(bytes_accessed)
            if stall_frac is not None:
                # seconds-weighted mean of ledger-measured stalls
                eng["stall_s"] += float(stall_frac) * seconds
                eng["stall_w"] += seconds
            ln = self._lanes.setdefault(
                lane, {"device_s": 0.0, "calls": 0, "requests": 0,
                       "flops": 0.0})
            ln["device_s"] += seconds
            ln["calls"] += 1
            ln["requests"] += int(requests)
            if flops:
                ln["flops"] += float(flops)
            if sig:
                sg = self._sigs.setdefault(
                    sig, {"requests": 0, "device_s": 0.0, "compile_s": 0.0})
                sg["requests"] += int(requests)
                sg["device_s"] += seconds
                sg["compile_s"] += float(compile_s)
            elapsed = max(now - self._t0, 1e-9)
            lane_rate = ln["device_s"] / elapsed
            lane_flops = (ln["flops"] / max(ln["device_s"], 1e-9)
                          if ln["flops"] else None)
            eng_flops = (eng["flops"] / max(eng["seconds"], 1e-9)
                         if eng["flops"] else None)
        # Forward OUTSIDE the lock: the obs hooks take the live sink's own
        # lock; holding ours across theirs would nest two sink locks.
        _spans.emit("attr", phase=phase, exe=exe, engine=engine, lane=lane,
                    seconds=round(seconds, 6), requests=int(requests),
                    **({"flops": round(float(flops), 3)} if flops else {}),
                    **({"bytes": round(float(bytes_accessed), 3)}
                       if bytes_accessed else {}),
                    **({"compile_s": round(float(compile_s), 6)}
                       if compile_s else {}),
                    **({"stall_frac": round(float(stall_frac), 4)}
                       if stall_frac is not None else {}),
                    **({"sig": sig} if sig else {}))
        _spans.histogram("util.exec_s", seconds)
        _spans.gauge(f"util.lane{lane}.device_s_per_s", round(lane_rate, 6))
        _spans.gauge(f"util.lane{lane}.stall_frac",
                     round(max(0.0, 1.0 - min(lane_rate, 1.0)), 4))
        if lane_flops is not None:
            _spans.gauge(f"util.lane{lane}.achieved_flops_per_s",
                         round(lane_flops, 3))
            _spans.gauge(
                f"util.lane{lane}.flops_frac",
                round(lane_flops / max(self.peaks.flops_per_s, 1e-9), 6))
        if eng_flops is not None:
            _spans.gauge(f"util.{engine}.achieved_flops_per_s",
                         round(eng_flops, 3))
            _spans.gauge(
                f"util.{engine}.flops_frac",
                round(eng_flops / max(self.peaks.flops_per_s, 1e-9), 6))

    # -- read path --------------------------------------------------------

    def engine_names(self) -> list:
        """The engines this matrix has attributed time to so far."""
        with self._lock:
            return list(self._engines)

    def roofline(self) -> Dict[str, Dict[str, Any]]:
        """Per-engine achieved-vs-peak rows (the roofline series)."""
        with self._lock:
            engines = {k: dict(v) for k, v in self._engines.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for engine, e in engines.items():
            secs = max(e["seconds"], 1e-9)
            row: Dict[str, Any] = {
                "device_s": round(e["seconds"], 6),
                "calls": e["calls"],
            }
            if e["flops"]:
                achieved = e["flops"] / secs
                row["achieved_flops_per_s"] = round(achieved, 3)
                row["flops_frac"] = round(
                    achieved / max(self.peaks.flops_per_s, 1e-9), 6)
            if e["bytes"]:
                bps = e["bytes"] / secs
                row["achieved_bytes_per_s"] = round(bps, 3)
                row["bytes_frac"] = round(
                    bps / max(self.peaks.bytes_per_s, 1e-9), 6)
            if e["stall_w"] > 0:
                row["stall_frac"] = round(e["stall_s"] / e["stall_w"], 4)
            out[engine] = row
        return out

    def capacity(self) -> Dict[str, Any]:
        """The per-compat-sig / per-lane capacity model: device-seconds per
        request and the sustainable requests/s each sig implies — what the
        serving tier routes/bills/autoscales on."""
        elapsed = max(time.perf_counter() - self._t0, 1e-9)
        with self._lock:
            sigs = {k: dict(v) for k, v in self._sigs.items()}
            lanes = {k: dict(v) for k, v in self._lanes.items()}
            serve_device_s = sum(
                c["seconds"] for c in self._cells.values()
                if c["phase"].startswith("serve"))
        sig_rows = {}
        for sig, s in sigs.items():
            per_req = s["device_s"] / max(s["requests"], 1)
            sig_rows[sig] = {
                "requests": s["requests"],
                "device_s": round(s["device_s"], 6),
                "compile_s": round(s["compile_s"], 6),
                "device_s_per_request": round(per_req, 6),
                "est_requests_per_s": round(1.0 / max(per_req, 1e-9), 3),
            }
        lane_rows = {}
        for lane, ln in lanes.items():
            lane_rows[str(lane)] = {
                "device_s": round(ln["device_s"], 6),
                "requests": ln["requests"],
                "device_s_per_s": round(ln["device_s"] / elapsed, 6),
                "stall_frac": round(
                    max(0.0, 1.0 - min(ln["device_s"] / elapsed, 1.0)), 4),
            }
        return {"serve_device_s": round(serve_device_s, 6),
                "sigs": sig_rows, "lanes": lane_rows}

    def top_cells(self, n: int = 10) -> list:
        """The top-N cells by device-seconds (the hot-executable table)."""
        with self._lock:
            cells = [dict(c) for c in self._cells.values()]
        cells.sort(key=lambda c: -c["seconds"])
        for c in cells:
            for k in ("seconds", "compile_s"):
                c[k] = round(c[k], 6)
            for k in ("flops", "bytes"):
                c[k] = round(c[k], 3)
        return cells[:n]

    def snapshot(self) -> Dict[str, Any]:
        """The /snapshot ``attr`` section: cells, roofline, capacity,
        peaks. Everything a scrape needs to render the utilization story
        without touching the matrix internals."""
        with self._lock:
            device_s = sum(c["seconds"] for c in self._cells.values())
            observes = self.observes
        return {
            "uptime_s": round(time.perf_counter() - self._t0, 3),
            "observes": observes,
            "device_s_total": round(device_s, 6),
            "peaks": self.peaks.to_dict(),
            "cells": self.top_cells(32),
            "roofline": self.roofline(),
            "capacity": self.capacity(),
        }


def status() -> Dict[str, Any]:
    """The exposition-facing view (mirrors ``export.flight_status``):
    ``{"recording": False}`` when no matrix is installed, otherwise the
    matrix snapshot under ``recording: True``."""
    mat = _active
    if mat is None:
        return {"recording": False}
    out: Dict[str, Any] = {"recording": True}
    out.update(mat.snapshot())
    return out
