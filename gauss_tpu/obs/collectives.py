"""Collective-traffic accounting: count + size the interconnect ops.

The distributed engines' design claims are stated in collectives-per-solve
("3 per panel, not ~4 per row" — dist/gauss_dist_blocked.py docstring) and
tests/test_dist_blocked.py proves the count from the compiled jaxpr. This
module makes the same derivation a permanent telemetry source: trace the
solver once, walk its jaxpr with scan lengths as multipliers, and emit one
``collective`` event per op kind with the per-execution count and payload
bytes. The summarizer folds these into a comms section, so every recorded
distributed run documents what it asked of the interconnect — the analog of
an MPI profiler's per-op message accounting over the reference's
Bcast/Isend/Irecv protocol (SURVEY.md §3.3), derived statically instead of
intercepted at runtime.

Bytes are the mathematical payload of each op's OUTPUT avals (shape x
itemsize, scan-weighted): the size of the value the collective materializes
per participating device, not a wire-protocol byte count (reduction trees,
ICI framing, and XLA's op fusion/decomposition are not modeled). Counts and
bytes are exact for the traced program; treat them as the budget the
formulation pays, comparable across engines and sizes.

Everything no-ops without an active recorder and never raises — accounting
must not take down a solve. Tracing costs one host-side ``jax.make_jaxpr``
per (label, shapes) per run; a per-recorder memo prevents re-tracing inside
bench loops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from gauss_tpu.obs import spans as _spans

# Substring match against primitive names (psum_p is "psum", lax.pmin/pmax
# ride reductions too; "all_gather"/"all_to_all"/"ppermute" are literal).
# Order matters only for labeling: the first match names the op kind.
COLLECTIVE_KINDS = ("all_gather", "all_to_all", "ppermute", "psum", "pmin",
                    "pmax", "pbroadcast", "pcast")


def _kind_of(primitive_name: str) -> Optional[str]:
    for kind in COLLECTIVE_KINDS:
        if kind in primitive_name:
            return kind
    return None


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        return int(size) * int(dtype.itemsize)
    except (TypeError, ValueError):
        return 0


def _walk(jaxpr, budget: Dict[str, Dict[str, int]], mult: int) -> None:
    """Accumulate collective counts/bytes over one jaxpr, weighting nested
    scan bodies by their static lengths (fori_loop with static bounds lowers
    to scan). Nested jaxprs are found by duck-typing (a ClosedJaxpr has
    .jaxpr, a Jaxpr has .eqns) rather than isinstance against jax internals
    — the same refactor-proofing as tests/test_dist_blocked.py."""
    for eqn in jaxpr.eqns:
        kind = _kind_of(eqn.primitive.name)
        if kind is not None:
            b = budget.setdefault(kind, {"count": 0, "bytes": 0})
            b["count"] += mult
            b["bytes"] += mult * sum(_aval_bytes(v) for v in eqn.outvars)
        inner_mult = mult * int(eqn.params.get("length", 1) or 1)
        for v in eqn.params.values():
            if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                _walk(v.jaxpr, budget, inner_mult)
            elif hasattr(v, "eqns"):
                _walk(v, budget, inner_mult)


def collective_budget(closed_jaxpr) -> Dict[str, Dict[str, int]]:
    """Per-execution collective budget of a traced program:
    ``{op_kind: {"count": N, "bytes": B}}`` with scan bodies weighted by
    their static lengths. Accepts the result of ``jax.make_jaxpr(fn)(args)``
    (or any object with ``.jaxpr.eqns`` / ``.eqns``)."""
    budget: Dict[str, Dict[str, int]] = {}
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    _walk(jaxpr, budget, 1)
    return budget


# HLO op name -> the jaxpr-level kind it implements, for the compiled-module
# path (XLA inserts these during SPMD partitioning of sharding-annotated
# programs like dist.matmul_dist, where the jaxpr holds no collective
# primitives at all).
_HLO_KINDS = {"all-reduce": "psum", "all-gather": "all_gather",
              "collective-permute": "ppermute", "all-to-all": "all_to_all",
              "reduce-scatter": "reduce_scatter",
              "collective-broadcast": "pbroadcast"}
_HLO_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
                 "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
                 "u64": 8, "c64": 8, "c128": 16}


def compiled_collective_budget(jitted_fn, *args) -> Dict[str, Dict[str, int]]:
    """Collective budget of the COMPILED module: lower + compile via the AOT
    API and count collective ops in the HLO text, sizing each by its output
    shape. This is the only derivation available for sharding-annotation
    programs (pjit + with_sharding_constraint), whose collectives exist only
    after SPMD partitioning. Unlike the jaxpr path, ops inside HLO while
    bodies count once (no static trip counts in HLO) — use the jaxpr path
    for loop-heavy shard_map programs."""
    import re

    text = jitted_fn.lower(*args).compile().as_text()
    budget: Dict[str, Dict[str, int]] = {}
    pat = re.compile(
        r"=\s+(?:\(?)([a-z0-9]+)\[([0-9,]*)\][^=]*?\s("
        + "|".join(_HLO_KINDS) + r")(?:-start|-done)?\(")
    for m in pat.finditer(text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        if "-done(" in m.group(0):
            continue  # async pair: count the -start, skip its -done
        kind = _HLO_KINDS[op]
        size = 1
        for d in dims.split(","):
            if d.strip():
                size *= int(d)
        b = budget.setdefault(kind, {"count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += size * _HLO_ITEMSIZE.get(dtype, 4)
    return budget


def record_collective_budget(label: str, fn, *args, via: str = "jaxpr",
                             **meta) -> Optional[Dict[str, Dict[str, int]]]:
    """Trace ``fn(*args)`` and emit one ``collective`` event per op kind
    (fields: ``label``, ``op``, ``count``, ``bytes``, ``via`` + the meta
    kwargs); returns the budget dict, or None when inactive/untraceable.

    ``via``: "jaxpr" (default) walks the traced program's explicit
    collective primitives — right for shard_map engines, scan-weighted;
    "hlo" compiles and counts the partitioner-inserted collectives — the
    only source for sharding-annotation programs (see
    :func:`compiled_collective_budget`).

    Deduplicated per recorder by (label, arg shapes): a bench loop that
    solves the same staged system repeatedly records the budget once, and
    the registry counters (``collective.<op>.count|bytes``) aggregate
    across distinct programs of one run.
    """
    rec = _spans.active()
    if rec is None:
        return None
    try:
        import jax

        key = (label, tuple((getattr(a, "shape", None),
                             str(getattr(a, "dtype", None))) for a in args))
        seen = getattr(rec, "_collective_seen", None)
        if seen is None:
            seen = rec._collective_seen = set()
        if key in seen:
            return None
        seen.add(key)
        with _spans.span(f"collective_budget:{label}"):
            if via == "hlo":
                budget = compiled_collective_budget(fn, *args)
            else:
                budget = collective_budget(jax.make_jaxpr(fn)(*args))
        for op in sorted(budget):
            b = budget[op]
            rec.emit("collective", label=label, op=op, count=b["count"],
                     bytes=b["bytes"], via=via, **meta)
            rec.counter(f"collective.{op}.count", b["count"])
            rec.counter(f"collective.{op}.bytes", b["bytes"])
        return budget
    except Exception:  # accounting must never take down a solve
        return None
