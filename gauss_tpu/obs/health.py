"""Numerical-health monitors: per-solve conditioning and stability signals.

The reference's only numerical telemetry is the external flavor's ``Error:``
line; a production solver needs the factorization-side signals too, recorded
per run (VERDICT history: the saylr4 refinement stall and the memplus f32
floor were both diagnosed by hand — these monitors make them data):

- ``min_abs_pivot`` — smallest |U diagonal| actually used; 0 means singular,
  tiny means the solve is leaning on refinement.
- ``growth_factor`` — max |entry of the factor| / max |entry of A|: the
  element-growth bound behind partial pivoting's stability argument
  (Wilkinson); large growth explains a bad residual with healthy pivots.
- ``nan`` / ``inf`` flags on the solution (device engines signal singularity
  through NaN rather than exceptions inside jit).
- ``residual`` / ``rel_residual`` — ||Ax - b||_2 in f64 on host, absolute
  (the BASELINE.json bar) and b-relative.

All device-side numbers come from cheap O(n^2) reductions (one pass over the
factor) fetched as scalars; the residual is the one O(n^2) host matvec the
refinement loop already pays.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from gauss_tpu.obs import spans as _spans


def _finite_float(v) -> float:
    return float(v)


def solution_health(x) -> Dict[str, Any]:
    """NaN/Inf flags + magnitude of a solution vector (host or device)."""
    x = np.asarray(x, dtype=np.float64)
    return {
        "nan": bool(np.isnan(x).any()),
        "inf": bool(np.isinf(x).any()),
        "max_abs_x": float(np.max(np.abs(x))) if x.size else 0.0,
    }


def residual_health(a, x, b) -> Dict[str, Any]:
    """Absolute + relative residual norms in f64 on host."""
    from gauss_tpu.verify import checks

    res = checks.residual_norm(a, x, b)
    nb = float(np.linalg.norm(np.asarray(b, np.float64)))
    return {"residual": res,
            "rel_residual": res / nb if nb > 0 else res}


def factor_health(factors, a=None, n: Optional[int] = None) -> Dict[str, Any]:
    """Pivot/growth monitors from a BlockedLU-shaped factorization.

    ``n``: the true system size — the identity padding's 1.0 diagonal
    entries would otherwise clamp the reported min |pivot| at <= 1 (same
    trap the gauss_external ``--debug`` path documents). On-device
    reductions; only scalars cross to host.
    """
    import jax.numpy as jnp

    m = factors.m
    n = int(m.shape[0]) if n is None else int(n)
    diag = jnp.abs(jnp.diagonal(m)[:n])
    out: Dict[str, Any] = {
        "min_abs_pivot": _finite_float(jnp.min(diag)),
        "max_abs_pivot": _finite_float(jnp.max(diag)),
    }
    max_factor = _finite_float(jnp.max(jnp.abs(m[:n, :n])))
    if a is not None:
        max_a = float(np.max(np.abs(np.asarray(a))))
        if max_a > 0 and math.isfinite(max_factor):
            out["growth_factor"] = max_factor / max_a
    if getattr(factors, "min_abs_pivot", None) is not None:
        # The loop-recorded minimum (includes padded steps; kept for
        # cross-checking the diagonal read).
        out["loop_min_abs_pivot"] = _finite_float(factors.min_abs_pivot)
    return out


def record_solve_health(a=None, x=None, b=None, factors=None,
                        n: Optional[int] = None, backend: Optional[str] = None,
                        **extra) -> Optional[Dict[str, Any]]:
    """Assemble whichever monitors the inputs allow and emit ONE ``health``
    event on the active recorder. Returns the metrics dict (None when no
    recorder is active — the reductions are skipped entirely, so permanent
    call sites stay free on unobserved runs)."""
    if _spans.active() is None:
        return None
    metrics: Dict[str, Any] = {}
    if x is not None:
        metrics.update(solution_health(x))
    if a is not None and x is not None and b is not None:
        metrics.update(residual_health(a, x, b))
    if factors is not None:
        try:
            metrics.update(factor_health(factors, a=a, n=n))
        except Exception:
            # Hand-built/partial factor objects must not break a solve.
            pass
    metrics.update(extra)
    _spans.emit("health", backend=backend, **metrics)
    return metrics
