"""Metrics registry + structured run events, flushed as JSONL.

The reference project's whole analysis story is observability — per-phase
``gettimeofday`` spans and gprof flat profiles (PAPER.md, SURVEY §5) — but
its numbers die in stdout. This registry is the persistent equivalent: every
layer reports counters, gauges, histograms, spans, health monitors, and
compile/memory accounting into ONE per-run event stream, written as JSON
Lines so any run can be re-analysed later (``gauss_tpu.obs.summarize``).

Design rules:

- **No jax import at module load** — the registry must be usable before the
  platform is pinned (CLI drivers import it pre-``honor_jax_platforms``).
- **Zero-cost when inactive**: every module-level hook is a no-op unless a
  recorder is active, so instrumentation can live permanently in hot setup
  paths (never inside traced code — events are host-side by construction).
- **Append-only events**: an event is one flat JSON object with ``type``,
  ``run``, ``seq`` and ``t`` (seconds since run start); consumers aggregate,
  producers never mutate.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1


def new_run_id() -> str:
    """Short unique run ID (hex; collision-safe across hosts via uuid4)."""
    return uuid.uuid4().hex[:12]


# run_start fields written by environment_fingerprint(); the summarizer
# renders these on their own "environment:" line instead of the meta header.
ENV_FINGERPRINT_KEYS = ("host", "os_pid", "python", "jax", "backend",
                        "device_kind", "device_count", "local_device_count",
                        "process_index", "process_count")


def environment_fingerprint() -> Dict[str, Any]:
    """Where this run executed: hostname, interpreter, jax version, and —
    when a jax backend is ALREADY initialized — platform, device kind/count
    and the process coordinates. Stamped into run_start at close so
    regressions are attributable to an environment epoch, not just a commit
    (the r3->r4 headline swing was an epoch, docs/BENCH_STABILITY.md).

    Never initializes anything: jax is read only if already imported, and
    device info only if a backend exists (probing would boot the default
    platform — possibly a tunneled TPU — on runs that never touched it)."""
    import platform
    import socket
    import sys

    fp: Dict[str, Any] = {"host": socket.gethostname(), "os_pid": os.getpid(),
                          "python": platform.python_version()}
    jax = sys.modules.get("jax")
    if jax is None:
        return fp
    fp["jax"] = getattr(jax, "__version__", None)
    try:  # private, so duck-typed + guarded: empty/absent -> not initialized
        from jax._src import xla_bridge

        initialized = bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        initialized = False
    if not initialized:
        return fp
    try:
        devs = jax.devices()
        fp.update(backend=devs[0].platform,
                  device_kind=getattr(devs[0], "device_kind", None),
                  device_count=jax.device_count(),
                  local_device_count=jax.local_device_count(),
                  process_index=jax.process_index(),
                  process_count=jax.process_count())
    except Exception:
        pass
    return fp


def _jsonable(v):
    """Coerce numpy/jax scalars and other oddballs to JSON-safe values."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        # NaN/Inf are not valid JSON; encode as strings so the flags survive.
        if v != v:
            return "nan"
        if v in (float("inf"), float("-inf")):
            return "inf" if v > 0 else "-inf"
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:  # numpy / jax scalars and 0-d arrays
        return _jsonable(float(v))
    except (TypeError, ValueError):
        return str(v)


class Recorder:
    """One run's event stream plus its counter/gauge/histogram registry.

    Thread-safe appends (bench sweeps may record from worker threads); the
    registry state is also folded into ``metric`` summary events at flush so
    the JSONL alone reconstructs everything.
    """

    def __init__(self, run_id: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.run_id = run_id or new_run_id()
        self.t0 = time.perf_counter()
        self.events: List[Dict[str, Any]] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, List[float]] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self.emit("run_start", time_unix=time.time(),
                  schema=SCHEMA_VERSION, **(meta or {}))

    # -- event stream -----------------------------------------------------
    def emit(self, type_: str, **fields) -> Dict[str, Any]:
        """Append one structured event; returns it (already stamped)."""
        with self._lock:
            ev = {"type": type_, "run": self.run_id, "seq": self._seq,
                  "t": round(time.perf_counter() - self.t0, 6)}
            self._seq += 1
        for k, v in fields.items():
            ev[k] = _jsonable(v)
        with self._lock:
            self.events.append(ev)
        return ev

    # -- registry ---------------------------------------------------------
    def counter(self, name: str, inc: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def histogram(self, name: str, value: float) -> None:
        with self._lock:
            self.histograms.setdefault(name, []).append(float(value))

    # -- output -----------------------------------------------------------
    def _registry_events(self) -> List[Dict[str, Any]]:
        evs = []
        for name, v in sorted(self.counters.items()):
            evs.append({"type": "metric", "kind": "counter", "name": name,
                        "value": _jsonable(v)})
        for name, v in sorted(self.gauges.items()):
            evs.append({"type": "metric", "kind": "gauge", "name": name,
                        "value": _jsonable(v)})
        for name, vals in sorted(self.histograms.items()):
            svals = sorted(vals)
            evs.append({
                "type": "metric", "kind": "histogram", "name": name,
                "count": len(vals), "min": _jsonable(svals[0]),
                "max": _jsonable(svals[-1]),
                "mean": _jsonable(sum(vals) / len(vals)),
                "p50": _jsonable(svals[len(svals) // 2])})
        for ev in evs:
            ev["run"] = self.run_id
        return evs

    def close(self) -> None:
        """Stamp the run_end event (wall-clock of the whole run) and merge
        the environment fingerprint into run_start's meta. Fingerprinting at
        close — not construction — sees the backend the run actually used
        (drivers open the run before the platform is pinned; by close, any
        backend the run touched is initialized)."""
        self.emit("run_end", wall_s=time.perf_counter() - self.t0)
        try:
            start = self.events[0]
            for k, v in environment_fingerprint().items():
                if k not in start and v is not None:
                    start[k] = _jsonable(v)
        except Exception:  # fingerprinting must never take down a run
            pass

    def flush(self, path) -> int:
        """Append every event (+ registry summaries) to ``path`` as JSONL;
        returns the number of lines written. Appending, not truncating:
        several runs (a bench sweep) can share one file and the summarizer
        splits them by run ID."""
        lines = [json.dumps(ev, sort_keys=True)
                 for ev in self.events + self._registry_events()]
        path = os.fspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "a") as f:
            f.write("\n".join(lines) + "\n")
        return len(lines)


def read_events(path) -> List[Dict[str, Any]]:
    """Parse a JSONL events file; skips blank/corrupt lines (a crashed run
    may truncate its last line — the surviving prefix is still data)."""
    events = []
    with open(os.fspath(path)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
    return events
