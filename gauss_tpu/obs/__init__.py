"""gauss_tpu.obs — unified telemetry: metrics, spans, health, accounting.

One per-run event stream that every layer reports into (the persistent
equivalent of the reference's gettimeofday spans + gprof flat profiles,
SURVEY §5), flushed as JSONL via ``--metrics-out`` and rendered offline by
``python -m gauss_tpu.obs.summarize``.

Quick tour::

    from gauss_tpu import obs

    with obs.run(metrics_out="run.jsonl", tool="my_sweep") as rec:
        with obs.span("factor"):
            fac = lu_factor_blocked(a)
        obs.record_solve_health(a=a, x=x, b=b, factors=fac, n=n)
        obs.gauge("panel_width", 128)
    # run.jsonl now holds the run; `python -m gauss_tpu.obs.summarize
    # run.jsonl` renders the flat profile + health report.

Every hook is a no-op without an active recorder, so instrumentation lives
permanently in the library's host-side setup paths at zero cost on
unobserved runs. Nothing here imports jax at module load; device-touching
helpers (health reductions, cost analysis) import it lazily.
"""

from gauss_tpu.obs.collectives import (  # noqa: F401
    collective_budget,
    compiled_collective_budget,
    record_collective_budget,
)
from gauss_tpu.obs.compile import (  # noqa: F401
    compile_span,
    cost_summary,
    record_cost,
    record_vmem_estimate,
)
from gauss_tpu.obs.health import record_solve_health  # noqa: F401
from gauss_tpu.obs.registry import Recorder, new_run_id, read_events  # noqa: F401
from gauss_tpu.obs.spans import (  # noqa: F401
    active,
    counter,
    current_trace,
    emit,
    flight_sink,
    gauge,
    histogram,
    live_sink,
    record_span,
    run,
    set_flight_sink,
    set_live_sink,
    span,
    trace_context,
)

# NOTE: gauss_tpu.obs.summarize, .doctor, .requesttrace, .top, .prof, and
# .profcheck are deliberately NOT imported here — they are `python -m`
# entry points, and importing them from the package __init__ would trip
# runpy's double-import warning. The live plane (obs.live / obs.slo /
# obs.export) is imported lazily by its users (SolverServer --live-port,
# gauss-fleet --live-port) so unobserved processes never pay for it;
# likewise the flight recorder (obs.flight / obs.postmortem) — installed
# only when a flight_dir is configured — and the attribution plane
# (obs.attr) — installed only by ServeConfig(attr=True), its call sites
# one `is None` read when off — so the crash ring and the cost matrix
# cost nothing where they aren't wanted.
