"""``gauss-top`` — a live terminal dashboard over a /metrics endpoint.

Polls the Prometheus text exposition a ``SolverServer`` (``gauss-serve
--live-port``) or ``gauss-fleet --live-port`` embeds, and renders the
numbers an operator watches during an incident: request totals and rates,
latency quantiles, queue depth and batch occupancy, cache hit-rate,
breaker state, SLO burn rates with firing alerts, and fleet heartbeat
ages. Stdlib only (urllib + ANSI clears); ``--once`` prints a single frame
and exits (the scriptable/CI form), ``--json`` dumps the parsed samples.

The parser speaks enough of the exposition format for our own exporter
(and any standard one): ``name{label="v",...} value`` lines, comments
skipped. It is intentionally NOT a full openmetrics parser.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

Sample = Tuple[str, Dict[str, str], float]


def parse_metrics(text: str) -> List[Sample]:
    """Parse exposition text into (name, labels, value) samples."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.append((m.group("name"), labels, value))
    return out


def scrape(url: str, timeout: float = 5.0) -> List[Sample]:
    with urllib.request.urlopen(f"{url.rstrip('/')}/metrics",
                                timeout=timeout) as resp:
        return parse_metrics(resp.read().decode())


class _View:
    """Indexed access over one scrape."""

    def __init__(self, samples: List[Sample]):
        self.samples = samples
        self._plain = {name: v for name, labels, v in samples if not labels}

    def get(self, name: str, default: Optional[float] = None):
        return self._plain.get(name, default)

    def labeled(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        return [(labels, v) for n, labels, v in self.samples
                if n == name and labels]

    def prefixed(self, prefix: str) -> Dict[str, float]:
        return {n: v for n, v in self._plain.items()
                if n.startswith(prefix)}


def _fmt(v: Optional[float], unit: str = "", digits: int = 3) -> str:
    if v is None:
        return "-"
    if unit == "ms":
        return f"{v * 1e3:.{digits}f}ms"
    if float(v).is_integer() and abs(v) < 1e12:
        return f"{int(v)}{unit}"
    return f"{v:.{digits}f}{unit}"


def render(view: _View, url: str,
           prev: Optional[_View] = None, dt: float = 0.0) -> str:
    g = view.get
    lines = [f"gauss-top — {url}  (uptime "
             f"{_fmt(g('gauss_live_uptime_s'), 's', 1)})"]

    def rate(name: str) -> str:
        if prev is None or dt <= 0:
            return ""
        now, before = view.get(name), prev.get(name)
        if now is None or before is None:
            return ""
        return f" ({(now - before) / dt:.1f}/s)"

    served = g("gauss_serve_served_total")
    if served is not None or g("gauss_serve_submitted_total") is not None:
        lines.append(
            "  requests: "
            f"submitted {_fmt(g('gauss_serve_submitted_total', 0))}"
            f"{rate('gauss_serve_submitted_total')}, "
            f"served {_fmt(g('gauss_serve_served_total', 0))}"
            f"{rate('gauss_serve_served_total')}, "
            f"rejected {_fmt(g('gauss_serve_rejected_total', 0))}, "
            f"expired {_fmt(g('gauss_serve_expired_total', 0))}, "
            f"failed {_fmt(g('gauss_serve_failed_total', 0))}, "
            f"cancelled {_fmt(g('gauss_serve_cancelled_total', 0))}")
        q = {labels.get("quantile"): v for labels, v
             in view.labeled("gauss_serve_latency_s")}
        if q:
            lines.append(
                f"  latency: p50 {_fmt(q.get('0.5'), 'ms')}  "
                f"p95 {_fmt(q.get('0.95'), 'ms')}  "
                f"p99 {_fmt(q.get('0.99'), 'ms')}  "
                f"(window n={_fmt(g('gauss_serve_latency_s_count'))})")
        occ = {labels.get("quantile"): v for labels, v
               in view.labeled("gauss_serve_batch_occupancy")}
        breaker = g("gauss_serve_breaker_open")
        lines.append(
            f"  lane: queue depth {_fmt(g('gauss_serve_queue_depth', 0))}, "
            f"batches {_fmt(g('gauss_serve_batches_total', 0))}"
            f"{rate('gauss_serve_batches_total')}, occupancy p50 "
            f"{_fmt(occ.get('0.5'))}, retries "
            f"{_fmt(g('gauss_serve_retries_total', 0))}, breaker "
            + ("OPEN" if breaker else "closed"))
        hits = g("gauss_serve_cache_hits_total", 0)
        misses = g("gauss_serve_cache_misses_total", 0)
        total = (hits or 0) + (misses or 0)
        lines.append(
            f"  cache: {_fmt(hits)} hits / {_fmt(misses)} misses"
            + (f" (hit-rate {hits / total:.3f})" if total else "")
            + f", evictions {_fmt(g('gauss_serve_cache_evictions_total', 0))}"
            + (f"; tune store {_fmt(g('gauss_tune_store_hits_total', 0))}h/"
               f"{_fmt(g('gauss_tune_store_misses_total', 0))}m"
               if g("gauss_tune_store_hits_total") is not None
               or g("gauss_tune_store_misses_total") is not None else ""))

    # Mesh serving plane (serve.lanes): per-lane occupancy/steal panel.
    # Lane gauges are plain-named gauss_serve_lane<i>_<stat>; one row per
    # lane index found, plus the set-wide steal/cb/active counters.
    lane_samples = view.prefixed("gauss_serve_lane")
    if lane_samples:
        per: Dict[int, Dict[str, float]] = {}
        for name, v in lane_samples.items():
            m = re.match(r"gauss_serve_lane(\d+)_(\w+)", name)
            if m:
                per.setdefault(int(m.group(1)), {})[m.group(2)] = v
        lines.append(
            f"  mesh: {_fmt(g('gauss_serve_lanes_active'))} active "
            f"lane(s), steals {_fmt(g('gauss_serve_steals_total', 0))}"
            f"{rate('gauss_serve_steals_total')}, cb admits "
            f"{_fmt(g('gauss_serve_cb_admits_total', 0))}"
            f"{rate('gauss_serve_cb_admits_total')}, scale events "
            f"{_fmt(g('gauss_serve_lane_scales_total', 0))}")
        for idx in sorted(per):
            s = per[idx]
            lines.append(
                f"    lane {idx}: depth {_fmt(s.get('queue_depth', 0))}, "
                f"served {_fmt(s.get('served', 0))}, stolen "
                f"{_fmt(s.get('stolen', 0))}, occupancy "
                f"{_fmt(s.get('occupancy'))}")

    # Utilization panel (obs.attr): per-lane achieved-vs-peak, stall
    # fraction and device-seconds per wall-second, plus the per-engine
    # roofline fractions. Gauges are plain-named gauss_util_lane<i>_<stat>
    # / gauss_util_<engine>_<stat>; absent entirely when the attribution
    # plane is off (ServeConfig(attr=None)).
    util_samples = view.prefixed("gauss_util_")
    if util_samples:
        ulanes: Dict[int, Dict[str, float]] = {}
        engines: Dict[str, Dict[str, float]] = {}
        for name, v in util_samples.items():
            m = re.match(r"gauss_util_lane(\d+)_(\w+)", name)
            if m:
                ulanes.setdefault(int(m.group(1)), {})[m.group(2)] = v
                continue
            m = re.match(r"gauss_util_(\w+?)_"
                         r"(achieved_flops_per_s|flops_frac)$", name)
            if m:
                engines.setdefault(m.group(1), {})[m.group(2)] = v
        lines.append("  utilization (attribution plane):")
        for idx in sorted(ulanes):
            s = ulanes[idx]
            frac = s.get("flops_frac")
            lines.append(
                f"    lane {idx}: "
                f"{_fmt(s.get('achieved_flops_per_s'), digits=3)} flop/s "
                f"achieved ({_fmt(frac, digits=4)} of peak), stall "
                f"{_fmt(s.get('stall_frac'), digits=4)}, device-s/s "
                f"{_fmt(s.get('device_s_per_s'), digits=4)}")
        for eng in sorted(engines):
            s = engines[eng]
            lines.append(
                f"    engine {eng}: "
                f"{_fmt(s.get('achieved_flops_per_s'), digits=3)} flop/s "
                f"achieved ({_fmt(s.get('flops_frac'), digits=4)} of peak)")

    firing = view.labeled("gauss_slo_firing")
    if firing:
        burns = {(labels.get("slo"), labels.get("window")): v
                 for labels, v in view.labeled("gauss_slo_burn_rate")}
        alerts = {labels.get("slo"): v for labels, v
                  in view.labeled("gauss_slo_alerts_total")}
        for labels, state in sorted(firing,
                                    key=lambda lv: lv[0].get("slo", "")):
            name = labels.get("slo", "?")
            flag = "FIRING" if state else "ok"
            lines.append(
                f"  slo {name}: {flag}  burn short "
                f"{_fmt(burns.get((name, 'short')), 'x', 2)} / long "
                f"{_fmt(burns.get((name, 'long')), 'x', 2)}, "
                f"{_fmt(alerts.get(name, 0))} alert(s)")

    # Flight recorder / post-mortem panel: the cause rides as a label on
    # the age gauge (Prometheus values are numeric-only).
    pm = view.labeled("gauss_postmortem_last_age_s")
    if pm:
        for labels, age in sorted(pm, key=lambda lv: lv[0].get("cause", "")):
            lines.append(
                f"  last post-mortem: {labels.get('cause', '?')} "
                f"{_fmt(age, 's', 1)} ago "
                f"({_fmt(g('gauss_postmortem_bundles_total', 0))} bundle(s) "
                f"this process; inspect with gauss-debug)")
    elif g("gauss_flight_recording"):
        lines.append(
            f"  flight recorder: on, ring at "
            f"{_fmt(g('gauss_flight_ring_wpos'))}/"
            f"{_fmt(g('gauss_flight_ring_capacity'))} bytes "
            f"({_fmt(g('gauss_flight_ring_seq'))} records), no post-mortems")

    hearts = view.prefixed("gauss_fleet_w")
    if hearts:
        ages = ", ".join(
            f"{n.removeprefix('gauss_fleet_').removesuffix('_heartbeat_age_s')}"
            f"={v:.1f}s" for n, v in sorted(hearts.items()))
        lines.append(
            f"  fleet: world {_fmt(view.get('gauss_fleet_world'))}, "
            f"heartbeat ages: {ages}; restarts "
            f"{_fmt(view.get('gauss_fleet_restarts_total', 0))}, stalls "
            f"{_fmt(view.get('gauss_fleet_stalls_total', 0))}")

    if len(lines) == 1:
        lines.append("  (no serving/fleet series yet — is traffic "
                     "flowing?)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gauss-top",
        description="Live terminal dashboard over a gauss live-telemetry "
                    "/metrics endpoint (gauss-serve --live-port / "
                    "gauss-fleet --live-port).")
    p.add_argument("--url", default="http://127.0.0.1:9100",
                   help="endpoint base URL (default http://127.0.0.1:9100)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll interval in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (scriptable form)")
    p.add_argument("--json", action="store_true",
                   help="dump the parsed samples as JSON instead of the "
                        "dashboard")
    args = p.parse_args(argv)

    prev: Optional[_View] = None
    prev_t = 0.0
    while True:
        try:
            view = _View(scrape(args.url))
        except (urllib.error.URLError, OSError) as e:
            print(f"gauss-top: cannot scrape {args.url}/metrics: {e}",
                  file=sys.stderr)
            return 2
        now = time.monotonic()
        if args.json:
            print(json.dumps(
                [{"name": n, "labels": lab, "value": v}
                 for n, lab, v in view.samples], indent=1, sort_keys=True))
        else:
            frame = render(view, args.url, prev,
                           now - prev_t if prev is not None else 0.0)
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(frame)
        if args.once:
            return 0
        prev, prev_t = view, now
        try:
            time.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:  # pragma: no cover — interactive exit
            return 0


if __name__ == "__main__":
    sys.exit(main())
