"""Automatic post-mortem bundles: freeze the crash scene into one file.

The flight ring (:mod:`gauss_tpu.obs.flight`) survives the process; this
module is the harvest step. On crash *detection* — a supervisor seeing a
dead/stalled child (``durable.supervise``, ``fleet``), a restarted server
finding unterminated admits at resume, an SLO alert firing, or an
``SDCDetectedError`` escalating in-process — :func:`capture_bundle` gathers
everything a human (or ``gauss-debug``) needs to reconstruct the final
seconds into ONE json document and writes it atomically (tmp + fsync +
rename + dir fsync, the dcheckpoint idiom) into a bundles directory:

- every flight ring in the flight dir (events, scan stats, sidecars);
- the request journal's tail — the unterminated admits (operands
  STRIPPED: a bundle is a debugging artifact, not a replay source), the
  recent terminals, torn-drop counts, clean-shutdown flag;
- heartbeat file ages;
- a ``/metrics`` snapshot when the live endpoint is still scrapable;
- the open (unterminated) trace set reconstructed from the ring.

Exactly-one-cause discipline: a bundle names ONE ``cause`` string (the
detector that fired), so attribution stays falsifiable — ``gauss-debug
--check`` asserts it. Capture sites are registered in
``gauss_tpu.analysis.driftlint.POSTMORTEM_OWNERS``: the lint fails any new
``inject`` kill/stall site that does not name its capture owner.

In-process triggers (SLO firing, SDC escalation) go through the throttled
:func:`trigger` hook — a module global configured by
:func:`install_trigger` (the server does this when ``flight_dir`` is set)
and a no-op otherwise, the same zero-cost-when-absent contract as every
other obs hook. A flapping alert produces one bundle per
:data:`TRIGGER_MIN_INTERVAL_S`, not one per transition.

Stdlib only; never imports jax.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

BUNDLE_SCHEMA = 1
BUNDLE_PREFIX = "bundle-"
BUNDLE_SUFFIX = ".json"

#: causes a capture site may name — exactly one per bundle. Every entry has
#: a registered owner in gauss_tpu.analysis.driftlint.POSTMORTEM_OWNERS.
KNOWN_CAUSES = (
    "supervisor_death",    # durable.supervise: child exited nonzero
    "supervisor_stall",    # durable.supervise: heartbeat went stale
    "fleet_worker_dead",   # fleet supervisor: worker process died
    "fleet_worker_stalled",  # fleet supervisor: worker lease went stale
    "unclean_resume",      # server start() found unterminated admits
    "slo_alert",           # a burn-rate alert transitioned to firing
    "sdc_detected",        # SDCDetectedError escalated past repair
    "poison_quarantine",   # death blamed on a poison request (uncharged)
    "manual",              # gauss-debug capture / tests
)

#: recent keyed terminals carried into a bundle's journal tail
JOURNAL_TAIL_TERMINALS = 32
TRIGGER_MIN_INTERVAL_S = 30.0

#: admit-record fields worth keeping (operands dropped — a/b are base64
#: matrices that would bloat a debugging artifact into a replay source)
_ADMIT_KEEP = ("id", "rid", "trace", "n", "k", "was_vector",
               "deadline_unix", "t_unix", "dtype", "structure")
_TERMINAL_KEEP = ("id", "rid", "trace", "status", "lane", "t_unix",
                  "rel_residual", "error")


def default_bundles_dir(flight_dir) -> str:
    """The convention: bundles live under the flight dir they explain."""
    return os.path.join(os.fspath(flight_dir), "bundles")


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    from gauss_tpu.resilience.checkpoint import fsync_dir

    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               suffix=".tmp", dir=parent)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, sort_keys=True, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(parent)


def _strip(doc: Dict[str, Any], keep) -> Dict[str, Any]:
    return {k: doc.get(k) for k in keep if k in doc}


def _journal_tail(journal_dir) -> Optional[Dict[str, Any]]:
    """The journal's view of the death: unterminated admits (= the requests
    in flight), recent terminals, damage counts. Never raises — a bundle
    about a crash must not crash over a damaged journal."""
    try:
        from gauss_tpu.serve import durable

        st = durable.scan(os.fspath(journal_dir))
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    terminals = sorted(st.terminals.values(),
                       key=lambda t: t.get("t_unix") or 0.0)
    return {
        "dir": os.fspath(journal_dir),
        "records": st.records,
        "torn_dropped": st.torn_dropped,
        "clean_shutdown": st.clean_shutdown,
        "live_admits": [_strip(d, _ADMIT_KEEP) for d in st.live_admits()],
        "recent_terminals": [_strip(d, _TERMINAL_KEEP)
                             for d in terminals[-JOURNAL_TAIL_TERMINALS:]],
    }


def _heartbeat_age(path) -> Dict[str, Any]:
    doc: Dict[str, Any] = {"path": os.fspath(path)}
    try:
        mtime = os.path.getmtime(path)
        doc["mtime_unix"] = round(mtime, 3)
        doc["age_s"] = round(time.time() - mtime, 3)
    except OSError:
        doc["age_s"] = None
    return doc


def _scrape_metrics(url: str, timeout_s: float = 0.75) -> Optional[str]:
    """GET the live /metrics exposition, or None — the endpoint usually
    died with the process; a surviving one is a bonus, never a wait."""
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")
    except Exception:
        return None


def _open_traces(ring_events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Traces present in the ring with no terminal serve_request event —
    the work that was cut off mid-flight. Compact per-trace shape (the
    full events are in the bundle's rings; gauss-debug folds them)."""
    from gauss_tpu.obs.flight import _TERMINAL_STATUSES

    by_trace: Dict[str, Dict[str, Any]] = {}
    for ev in ring_events:
        tids = [ev.get("trace")] if ev.get("trace") else []
        tids += list(ev.get("traces") or ())
        for tid in tids:
            tid = str(tid)
            d = by_trace.setdefault(tid, {"trace": tid, "events": 0,
                                          "types": [], "terminal": None})
            d["events"] += 1
            t = ev.get("type")
            if t and (not d["types"] or d["types"][-1] != t):
                d["types"].append(t)
            if (t == "serve_request"
                    and ev.get("status") in _TERMINAL_STATUSES):
                d["terminal"] = ev.get("status")
    return [d for d in by_trace.values() if d["terminal"] is None]


def capture_bundle(bundles_dir, cause: str, *,
                   flight_dir=None, journal_dir=None,
                   heartbeat_path=None, metrics_url: Optional[str] = None,
                   extra: Optional[Dict[str, Any]] = None,
                   log=None) -> Optional[str]:
    """Capture one post-mortem bundle; returns its path (None only when
    even the atomic write failed — capture must never take the SURVIVOR
    down, so every gather step degrades to a recorded error instead of
    raising)."""
    now = time.time()
    doc: Dict[str, Any] = {
        "schema": BUNDLE_SCHEMA,
        "cause": str(cause),
        "time_unix": round(now, 3),
        "captured_by": {"pid": os.getpid()},
    }
    try:
        from gauss_tpu.obs.registry import environment_fingerprint

        doc["captured_by"].update(environment_fingerprint())
    except Exception:
        pass
    if extra:
        doc["detail"] = {str(k): v for k, v in extra.items()}
    ring_events: List[Dict[str, Any]] = []
    if flight_dir is not None:
        try:
            from gauss_tpu.obs import flight

            rings = flight.scan_dir(flight_dir)
            doc["flight"] = {"dir": os.fspath(flight_dir), "rings": rings}
            for r in rings:
                ring_events.extend(r["events"])
        except Exception as e:
            doc["flight"] = {"error": f"{type(e).__name__}: {e}"}
    if journal_dir is not None:
        doc["journal"] = _journal_tail(journal_dir)
    if heartbeat_path is not None:
        doc["heartbeats"] = [_heartbeat_age(heartbeat_path)]
    if metrics_url:
        doc["metrics"] = _scrape_metrics(metrics_url)
    if ring_events:
        try:
            doc["open_traces"] = _open_traces(ring_events)
        except Exception as e:  # pragma: no cover — shape drift guard
            doc["open_traces_error"] = f"{type(e).__name__}: {e}"
    name = f"{BUNDLE_PREFIX}{int(now * 1000):013d}-{cause}-{os.getpid()}" \
           f"{BUNDLE_SUFFIX}"
    path = os.path.join(os.fspath(bundles_dir), name)
    try:
        _atomic_write_json(path, doc)
    except OSError as e:
        if log:
            log(f"postmortem: bundle write failed: {e}")
        return None
    try:
        from gauss_tpu import obs

        obs.counter("postmortem.bundles")
        obs.emit("postmortem", cause=cause, bundle=path,
                 open_traces=len(doc.get("open_traces", ())),
                 in_flight=len((doc.get("journal") or {})
                               .get("live_admits", ())))
    except Exception:  # pragma: no cover — telemetry never blocks capture
        pass
    if log:
        log(f"postmortem: captured {path} (cause={cause})")
    return path


# -- reading / checking ----------------------------------------------------

def list_bundles(bundles_dir) -> List[str]:
    """Bundle paths in a dir, oldest first (the name embeds capture ms)."""
    try:
        names = sorted(n for n in os.listdir(os.fspath(bundles_dir))
                       if n.startswith(BUNDLE_PREFIX)
                       and n.endswith(BUNDLE_SUFFIX))
    except OSError:
        return []
    return [os.path.join(os.fspath(bundles_dir), n) for n in names]


def latest_bundle(bundles_dir) -> Optional[str]:
    paths = list_bundles(bundles_dir)
    return paths[-1] if paths else None


def bundle_info(path) -> Dict[str, Any]:
    """The facts a bundle FILENAME carries (capture time, cause, writer
    pid) — the cheap per-scrape form /metrics needs, no body read."""
    name = os.path.basename(os.fspath(path))
    out: Dict[str, Any] = {"path": os.fspath(path), "time_unix": None,
                           "cause": None, "pid": None}
    if name.startswith(BUNDLE_PREFIX) and name.endswith(BUNDLE_SUFFIX):
        parts = name[len(BUNDLE_PREFIX):-len(BUNDLE_SUFFIX)].split("-")
        if len(parts) >= 3:
            try:
                out["time_unix"] = int(parts[0]) / 1000.0
            except ValueError:
                pass
            out["cause"] = "-".join(parts[1:-1]) or None
            try:
                out["pid"] = int(parts[-1])
            except ValueError:
                pass
    return out


def read_bundle(path) -> Dict[str, Any]:
    with open(os.fspath(path)) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"bundle {path} is not a json object")
    return doc


def check_bundle(doc: Dict[str, Any]) -> List[str]:
    """Integrity + exactly-one-cause assertions; returns the violations
    (empty = the bundle is trustworthy). This is ``gauss-debug --check``."""
    problems: List[str] = []
    if doc.get("schema") != BUNDLE_SCHEMA:
        problems.append(f"schema {doc.get('schema')!r} != {BUNDLE_SCHEMA}")
    cause = doc.get("cause")
    if not isinstance(cause, str) or not cause:
        problems.append("missing cause attribution")
    elif cause not in KNOWN_CAUSES:
        problems.append(f"unknown cause {cause!r} (exactly-one-cause "
                        f"registry: {KNOWN_CAUSES})")
    if "causes" in doc:
        problems.append("bundle carries a plural 'causes' field — "
                        "attribution must be exactly one cause")
    if not isinstance(doc.get("time_unix"), (int, float)):
        problems.append("missing capture time_unix")
    if not isinstance(doc.get("captured_by"), dict) \
            or "pid" not in doc.get("captured_by", {}):
        problems.append("missing captured_by.pid")
    fl = doc.get("flight")
    if isinstance(fl, dict):
        if "error" in fl:
            problems.append(f"flight harvest failed: {fl['error']}")
        for r in fl.get("rings", ()):
            st = r.get("stats") or {}
            if st.get("records", 0) != len(r.get("events", ())):
                problems.append(
                    f"ring {r.get('path')}: stats.records "
                    f"{st.get('records')} != events {len(r.get('events', ()))}")
    jn = doc.get("journal")
    if isinstance(jn, dict) and "error" in jn:
        problems.append(f"journal scan failed: {jn['error']}")
    if isinstance(jn, dict) and "error" not in jn:
        ids = [a.get("id") for a in jn.get("live_admits", ())]
        if len(ids) != len(set(ids)):
            problems.append("journal live_admits carries duplicate ids")
    return problems


# -- in-process trigger hook -----------------------------------------------

_trigger_lock = threading.Lock()
_trigger_cfg: Optional[Dict[str, Any]] = None
_last_trigger: Dict[str, float] = {}  # cause -> unix time of last capture


def install_trigger(bundles_dir, *, flight_dir=None, journal_dir=None,
                    heartbeat_path=None, metrics_url=None) -> None:
    """Arm the in-process capture hook (the server does this when a
    flight_dir is configured): later :func:`trigger` calls capture bundles
    with this context. Idempotent; ``uninstall_trigger`` disarms."""
    global _trigger_cfg
    with _trigger_lock:
        _trigger_cfg = {"bundles_dir": os.fspath(bundles_dir),
                        "flight_dir": flight_dir,
                        "journal_dir": journal_dir,
                        "heartbeat_path": heartbeat_path,
                        "metrics_url": metrics_url}


def uninstall_trigger() -> None:
    global _trigger_cfg
    with _trigger_lock:
        _trigger_cfg = None
        _last_trigger.clear()


def trigger(cause: str, **extra) -> Optional[str]:
    """Throttled in-process capture: no-op (None) when no trigger is armed
    or the same cause captured within :data:`TRIGGER_MIN_INTERVAL_S` (a
    flapping SLO alert must not write a bundle per transition)."""
    with _trigger_lock:
        cfg = _trigger_cfg
        if cfg is None:
            return None
        now = time.time()
        if now - _last_trigger.get(cause, 0.0) < TRIGGER_MIN_INTERVAL_S:
            return None
        _last_trigger[cause] = now
    return capture_bundle(cfg["bundles_dir"], cause,
                          flight_dir=cfg["flight_dir"],
                          journal_dir=cfg["journal_dir"],
                          heartbeat_path=cfg["heartbeat_path"],
                          metrics_url=cfg["metrics_url"],
                          extra=extra or None)
