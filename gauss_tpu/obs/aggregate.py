"""Merge multi-process telemetry streams into one clock-aligned run.

``python -m gauss_tpu.obs.aggregate run.p0.jsonl run.p1.jsonl [-o merged.jsonl]``

A multihost launch (dist/multihost.py) writes one JSONL stream per process —
concurrent appends to a shared file would interleave partial lines — all
stamped with one shared run id (see ``multihost.resolve_metrics_stream``).
This module is the rank-0 gather the reference got for free from mpirun's
interleaved stdout, done properly:

- **Merge by run ID** across any number of files; each stream's process lane
  comes from its ``run_start`` fingerprint (``process_index``, stamped by
  ``registry.environment_fingerprint``), falling back to distinct-stream
  order. Every merged event gains a ``proc`` field and duplicate (proc, seq)
  pairs collapse, so re-reading the same stream twice is harmless.
- **Clock alignment**: per-stream ``t`` is seconds since THAT process's run
  start; ``run_start.time_unix`` anchors each stream on the shared wall
  clock, and every merged event gains ``t_aligned`` = seconds since the
  EARLIEST process's start. (Host clocks are assumed NTP-close; skew shows
  up as a constant per-lane offset, not as wrong per-phase durations.)
- **Straggler statistics**: per span name, per-process totals plus
  max−min imbalance and relative skew ((max−min)/max) — the number that
  says which process the others waited for in each phase.

The merged stream is itself a valid events file: ``obs.summarize`` renders
it with per-lane coverage and ``obs.trace`` exports it with one timeline
lane per process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from gauss_tpu.obs import registry


def _runs_in(events: List[Dict[str, Any]]) -> List[str]:
    seen: List[str] = []
    for ev in events:
        rid = ev.get("run")
        if rid and rid not in seen:
            seen.append(rid)
    return seen


def _pick_run(streams: Sequence[List[Dict[str, Any]]],
              run_id: Optional[str]) -> str:
    """The run to merge: explicit, else the id present in the MOST streams
    (ties broken by first appearance) — a multihost run's id is the one
    every per-process file shares."""
    if run_id:
        return run_id
    counts: Dict[str, int] = {}
    order: List[str] = []
    for evs in streams:
        for rid in _runs_in(evs):
            if rid not in counts:
                order.append(rid)
            counts[rid] = counts.get(rid, 0) + 1
    if not order:
        raise ValueError("no runs found in the input streams")
    return max(order, key=lambda rid: (counts[rid], -order.index(rid)))


def merge_streams(paths: Sequence, run_id: Optional[str] = None,
                  ) -> Tuple[str, List[Dict[str, Any]]]:
    """Read every stream, select one run, and return
    ``(run_id, merged_events)`` with ``proc`` and ``t_aligned`` stamped.

    Deterministic in file order: events sort by (t_aligned, proc, seq), all
    of which are content-derived, so the same streams in any argument order
    merge to the identical list (asserted by tests/test_obs_dist.py).
    """
    streams = [registry.read_events(p) for p in paths]
    rid = _pick_run(streams, run_id)
    merged: Dict[Tuple[int, int], Dict[str, Any]] = {}
    fallback_proc = 0
    for evs in streams:
        run_evs = [ev for ev in evs if ev.get("run") == rid]
        if not run_evs:
            continue
        start = next((ev for ev in run_evs if ev.get("type") == "run_start"),
                     {})
        proc = start.get("process_index")
        if proc is None:
            proc = fallback_proc
        proc = int(proc)
        fallback_proc = max(fallback_proc, proc) + 1
        t_unix = float(start.get("time_unix") or 0.0)
        for ev in run_evs:
            key = (proc, int(ev.get("seq", -1)))
            if key in merged:
                continue
            ev = dict(ev)
            ev["proc"] = proc
            ev["_t_unix"] = t_unix + float(ev.get("t", 0.0))
            merged[key] = ev
    if not merged:
        raise ValueError(f"run '{rid}' not found in any input stream")
    t0 = min(ev["_t_unix"] for ev in merged.values())
    out = []
    for ev in merged.values():
        ev["t_aligned"] = round(ev.pop("_t_unix") - t0, 6)
        out.append(ev)
    out.sort(key=lambda ev: (ev["t_aligned"], ev["proc"], ev.get("seq", -1)))
    return rid, out


def straggler_stats(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-phase cross-process imbalance over a merged stream.

    Returns ``{"processes": [...], "wall_s": {proc: wall}, "phases":
    {name: {"per_proc_s": {proc: total}, "calls": N, "max_s", "min_s",
    "imbalance_s", "skew"}}}``. Phases missing on some process use 0 for
    the min — a phase only one process ran IS maximal imbalance (the
    others waited at the next collective).
    """
    procs = sorted({ev.get("proc", 0) for ev in events})
    wall = {p: None for p in procs}
    for ev in events:
        if ev.get("type") == "run_end" and ev.get("wall_s") is not None:
            wall[ev.get("proc", 0)] = float(ev["wall_s"])
    phases: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("type") != "span":
            continue
        ph = phases.setdefault(ev["name"],
                               {"per_proc_s": {p: 0.0 for p in procs},
                                "calls": 0})
        ph["per_proc_s"][ev.get("proc", 0)] += float(ev.get("dur_s", 0.0))
        ph["calls"] += 1
    for name, ph in phases.items():
        vals = list(ph["per_proc_s"].values())
        mx, mn = max(vals), min(vals)
        ph["max_s"] = round(mx, 6)
        ph["min_s"] = round(mn, 6)
        ph["imbalance_s"] = round(mx - mn, 6)
        ph["skew"] = round((mx - mn) / mx, 4) if mx > 0 else 0.0
        ph["per_proc_s"] = {p: round(v, 6)
                            for p, v in ph["per_proc_s"].items()}
    return {"processes": procs, "wall_s": wall, "phases": phases}


def aggregate_report(run_id: str, events: List[Dict[str, Any]],
                     stats: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable straggler report for a merged run."""
    stats = stats or straggler_stats(events)
    procs = stats["processes"]
    out = [f"run {run_id}: {len(events)} events from "
           f"{len(procs)} process(es) {procs}"]
    hosts = {}
    for ev in events:
        if ev.get("type") == "run_start":
            hosts[ev.get("proc", 0)] = ev.get("host")
    for p in procs:
        w = stats["wall_s"].get(p)
        host = f" on {hosts[p]}" if hosts.get(p) else ""
        out.append(f"  process {p}{host}: wall "
                   f"{w:.6f} s" if w is not None else
                   f"  process {p}{host}: wall (no run_end)")
    if stats["phases"]:
        out.append("")
        out.append("per-phase straggler statistics (seconds by process):")
        header = "  phase".ljust(28) + "".join(f"p{p:<10}" for p in procs) \
            + "imbalance   skew"
        out.append(header)
        for name, ph in sorted(stats["phases"].items(),
                               key=lambda kv: -kv[1]["max_s"]):
            row = f"  {name}".ljust(28)
            row += "".join(f"{ph['per_proc_s'][p]:<11.6f}" for p in procs)
            row += f"{ph['imbalance_s']:<12.6f}{ph['skew']:.1%}"
            out.append(row)
    return "\n".join(out)


def write_merged(events: List[Dict[str, Any]], path) -> int:
    """Write a merged stream as JSONL (truncate, not append: a merge is a
    derived artifact, regenerated whole)."""
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev, sort_keys=True) + "\n")
    return len(events)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.obs.aggregate",
        description="Merge per-process telemetry JSONL streams (one "
                    "multihost run) into a single clock-aligned stream "
                    "with per-phase straggler statistics.")
    p.add_argument("paths", nargs="+",
                   help="per-process JSONL streams (e.g. run.p0.jsonl "
                        "run.p1.jsonl)")
    p.add_argument("--run", default=None,
                   help="run ID to merge (default: the id shared by the "
                        "most streams)")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="write the merged stream (JSONL) here; summarize/"
                        "trace it like any events file")
    p.add_argument("--json", action="store_true",
                   help="emit the straggler statistics as JSON instead of "
                        "the text report")
    args = p.parse_args(argv)
    try:
        rid, merged = merge_streams(args.paths, args.run)
    except (OSError, ValueError) as e:
        print(f"aggregate: {e}", file=sys.stderr)
        return 1
    stats = straggler_stats(merged)
    if args.out:
        n = write_merged(merged, args.out)
        print(f"aggregate: wrote {n} merged events to {args.out}",
              file=sys.stderr)
    if args.json:
        print(json.dumps({"run": rid, **stats}, indent=1, sort_keys=True))
    else:
        print(aggregate_report(rid, merged, stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
