"""``gauss-debug`` — reconstruct a causal timeline from a post-mortem bundle.

The flight recorder (:mod:`gauss_tpu.obs.flight`) keeps the final seconds
of a killed process on disk; the capture sites (:mod:`gauss_tpu.obs.
postmortem`) freeze them into a bundle. This CLI is the read side: point it
at a bundle (or the bundles/flight dir holding one) and it answers the
questions a 3 a.m. page asks —

- **what died, and why does the detector think so** — the bundle's single
  ``cause``, its detail, and the heartbeat age at capture;
- **what was the process doing** — the last N ``serve_batch`` dispatches
  out of the ring, each with its member trace ids, bucket, and duration;
- **who is still owed an answer** — the journal's unterminated admits (the
  in-flight request set a resumed server will replay) and the ring's open
  traces (admitted, no terminal recorded);
- **what did the queues/lanes look like at death** — the sidecar's last
  gauge snapshot (queue depth, lane occupancy) plus ring position.

``--stream run.jsonl`` folds a post-restart recorder stream into the ring
events (:func:`gauss_tpu.obs.requesttrace.fold_ring_events`) so a
crash-spanning trace — admitted before the kill, resolved after the
resume — reads as ONE complete tree. ``--check`` runs the bundle
integrity + exactly-one-cause assertions (:func:`postmortem.check_bundle`)
and exits nonzero on any violation; the durable/fleet chaos campaigns run
it on every bundle they capture. ``--capture`` writes a ``manual`` bundle
from a live flight dir (the scene-freeze you run BEFORE poking a sick
process).

Stdlib only; never imports jax — safe on a machine that can't.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import postmortem


def resolve_bundle(target: str) -> Optional[str]:
    """Map a CLI target onto one bundle path: a bundle file itself, a
    directory of bundles (latest wins), or a flight dir with a ``bundles/``
    subdirectory under it."""
    target = os.fspath(target)
    if os.path.isfile(target):
        return target
    if os.path.isdir(target):
        latest = postmortem.latest_bundle(target)
        if latest:
            return latest
        return postmortem.latest_bundle(postmortem.default_bundles_dir(target))
    return None


def _ring_events(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    fl = doc.get("flight") or {}
    out: List[Dict[str, Any]] = []
    for r in fl.get("rings", ()):
        out.extend(r.get("events", ()))
    return out


def _last_sidecar(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    fl = doc.get("flight") or {}
    sidecars = [r.get("sidecar") for r in fl.get("rings", ())
                if r.get("sidecar")]
    return sidecars[-1] if sidecars else None


def reconstruct(doc: Dict[str, Any], batches: int = 5,
                stream_events: Optional[List[Dict[str, Any]]] = None,
                ) -> Dict[str, Any]:
    """Fold a bundle (plus an optional post-restart stream) into the
    timeline dict the text/JSON renderers print. Pure function of its
    inputs — the flight-check gate asserts on this shape."""
    from gauss_tpu.obs import requesttrace

    ring = _ring_events(doc)
    events = requesttrace.fold_ring_events(stream_events or [], ring)
    last_batches = [ev for ev in events if ev.get("type") == "serve_batch"]
    last_batches = last_batches[-batches:] if batches else last_batches
    jn = doc.get("journal") or {}
    in_flight = list(jn.get("live_admits", ()))
    trees = requesttrace.request_traces(events)
    open_traces = sorted(t for t, tree in trees.items()
                         if tree["terminal_count"] == 0)
    completed = sum(1 for tree in trees.values()
                    if tree["terminal_count"] > 0)
    sidecar = _last_sidecar(doc)
    fl = doc.get("flight") or {}
    return {
        "cause": doc.get("cause"),
        "time_unix": doc.get("time_unix"),
        "captured_by": doc.get("captured_by"),
        "detail": doc.get("detail"),
        "heartbeats": doc.get("heartbeats"),
        "rings": [{"path": r.get("path"), "pid": r.get("pid"),
                   "stats": r.get("stats")} for r in fl.get("rings", ())],
        "ring_events": len(ring),
        "last_batches": last_batches,
        "in_flight": in_flight,
        "open_traces": open_traces,
        "traces": len(trees),
        "traces_completed": completed,
        "gauges": (sidecar or {}).get("gauges") or {},
        "sidecar": sidecar,
        "trees": trees,
    }


def _age(then: Optional[float], now: Optional[float] = None) -> str:
    if not isinstance(then, (int, float)):
        return "?"
    age = (time.time() if now is None else now) - then
    if age >= 3600:
        return f"{age / 3600:.1f}h"
    if age >= 60:
        return f"{age / 60:.1f}m"
    return f"{age:.1f}s"


def format_timeline(path: str, rec: Dict[str, Any]) -> str:
    cap = rec.get("captured_by") or {}
    lines = [f"post-mortem bundle: {path}",
             f"cause: {rec.get('cause')}  captured {_age(rec.get('time_unix'))} ago"
             f" by pid {cap.get('pid')}"]
    if rec.get("detail"):
        kv = " ".join(f"{k}={v}" for k, v in sorted(rec["detail"].items()))
        lines.append(f"detail: {kv}")
    for hb in rec.get("heartbeats") or ():
        age = hb.get("age_s")
        lines.append(
            f"heartbeat: {hb.get('path')} "
            + (f"age {age:.3f}s at capture" if isinstance(age, (int, float))
               else "absent"))
    for ring in rec.get("rings", ()):
        st = ring.get("stats") or {}
        lines.append(f"ring: {ring.get('path')}  pid={ring.get('pid')} "
                     f"records={st.get('records')} "
                     f"torn_dropped={st.get('torn_dropped')} "
                     f"wpos={st.get('wpos')}/{st.get('capacity')}")
    gauges = rec.get("gauges") or {}
    if gauges:
        lines.append("queue/lane state at death (last sidecar write):")
        for name in sorted(gauges):
            lines.append(f"  {name} = {gauges[name]:g}")
    batches = rec.get("last_batches") or []
    lines.append(f"last {len(batches)} batch(es):")
    if not batches:
        lines.append("  (none in ring)")
    for ev in batches:
        traces = ",".join(str(t) for t in (ev.get("traces") or ()))
        lines.append(
            f"  tu={ev.get('tu', ev.get('t'))} bucket={ev.get('bucket_n')} "
            f"requests={ev.get('requests')} "
            f"seconds={ev.get('seconds')} traces={traces or '-'}")
    in_flight = rec.get("in_flight") or []
    lines.append(f"in flight at death (journal unterminated admits): "
                 f"{len(in_flight)} request(s)")
    for adm in in_flight:
        lines.append(f"  id={adm.get('id')} trace={adm.get('trace')} "
                     f"n={adm.get('n')} deadline={adm.get('deadline_unix')}")
    open_traces = rec.get("open_traces") or []
    lines.append(f"open traces (no terminal in ring"
                 f"{'+stream' if rec.get('stream_folded') else ''}): "
                 f"{len(open_traces)}"
                 + (f"  {' '.join(open_traces)}" if open_traces else ""))
    lines.append(f"traces: {rec.get('traces')} seen, "
                 f"{rec.get('traces_completed')} completed")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gauss-debug",
        description="Reconstruct the causal timeline of a crash from a "
                    "post-mortem bundle: cause, last batches with trace "
                    "ids, in-flight requests, queue/lane state at death.")
    p.add_argument("target",
                   help="bundle json, a bundles dir (latest bundle wins), "
                        "or a flight dir holding bundles/")
    p.add_argument("--batches", type=int, default=5, metavar="N",
                   help="show the last N serve_batch dispatches "
                        "(default 5; 0 = all in ring)")
    p.add_argument("--stream", default=None, metavar="JSONL",
                   help="fold a post-restart recorder stream into the ring "
                        "events so crash-spanning traces complete")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="print the folded request tree for one trace id")
    p.add_argument("--json", action="store_true",
                   help="emit the reconstruction as JSON (trees included)")
    p.add_argument("--check", action="store_true",
                   help="assert bundle integrity + exactly-one-cause "
                        "attribution (exit 1 on any violation)")
    p.add_argument("--capture", action="store_true",
                   help="capture a 'manual' bundle from --flight-dir "
                        "first, then reconstruct it (target is ignored; "
                        "pass the flight dir as target)")
    p.add_argument("--journal", default=None, metavar="DIR",
                   help="with --capture: include this request journal's "
                        "tail in the bundle")
    args = p.parse_args(argv)

    if args.capture:
        flight_dir = args.target
        path = postmortem.capture_bundle(
            postmortem.default_bundles_dir(flight_dir), "manual",
            flight_dir=flight_dir, journal_dir=args.journal)
        if path is None:
            print("gauss-debug: manual capture failed", file=sys.stderr)
            return 2
        print(f"captured: {path}")
    else:
        path = resolve_bundle(args.target)
        if path is None:
            print(f"gauss-debug: no bundle found at '{args.target}'",
                  file=sys.stderr)
            return 2
    try:
        doc = postmortem.read_bundle(path)
    except (OSError, ValueError) as e:
        print(f"gauss-debug: cannot read bundle '{path}': {e}",
              file=sys.stderr)
        return 2

    stream_events = None
    if args.stream:
        from gauss_tpu.obs import registry

        try:
            stream_events = registry.read_events(args.stream)
        except OSError as e:
            print(f"gauss-debug: cannot read stream '{args.stream}': {e}",
                  file=sys.stderr)
            return 2
    rec = reconstruct(doc, batches=args.batches,
                      stream_events=stream_events)
    rec["stream_folded"] = bool(args.stream)

    if args.check:
        problems = postmortem.check_bundle(doc)
        for prob in problems:
            print(f"gauss-debug: {prob}", file=sys.stderr)
        print(f"gauss-debug: {path}: {len(problems)} problem(s)")
        return 1 if problems else 0

    if args.trace:
        from gauss_tpu.obs import requesttrace

        tree = rec["trees"].get(args.trace)
        if tree is None:
            print(f"gauss-debug: trace '{args.trace}' not found "
                  f"({len(rec['trees'])} trace(s) in bundle)",
                  file=sys.stderr)
            return 2
        print(requesttrace.format_tree(tree))
        return 0

    if args.json:
        print(json.dumps(rec, indent=1, sort_keys=True, default=str))
    else:
        rec.pop("trees", None)
        print(format_timeline(path, rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
