"""gauss-prof: flamegraphs, top-executable tables, and roofline reports.

The read side of the attribution plane (``gauss_tpu.obs.attr``): render
WHERE the device time went from any recorded stream — or a live scrape —
without re-running anything.

``gauss-prof PATH[:run]`` — top-N table + per-engine roofline from a
recorded metrics JSONL (the ``attr`` events ``AttributionMatrix.observe``
emitted, falling back to plain spans when a stream predates the plane).

``gauss-prof --url http://HOST:PORT`` — the same tables from a running
server's ``/snapshot`` exposition (``obs.export``), no file needed.

``--folded out.folded`` — write folded-stack lines (``a;b;c <usec>``, the
flamegraph.pl / speedscope interchange format) reconstructed from the span
events' parent chains, with self-time attribution so a rendered flamegraph
sums to the measured wall, not a double-counted tree.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from gauss_tpu.obs import attr as _attr
from gauss_tpu.obs import registry


# -- folded stacks ----------------------------------------------------------

def folded_stacks(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Reconstruct folded call stacks (``root;child;leaf`` -> seconds of
    SELF time) from span events.

    Span events carry ``name``/``parent``/``dur_s``; the full ancestry is
    rebuilt by chasing parent names through the last-seen parent map (span
    names are stable phase labels, so the chain is well-defined for the
    streams this repo records; a cycle or depth blowup is cut off rather
    than trusted). Parents then have each child's total subtracted, so
    every frame carries self time and the folded file sums to the span
    total — the flamegraph convention."""
    parents: Dict[str, Optional[str]] = {}
    spans = []
    for ev in events:
        if ev.get("type") != "span":
            continue
        name = ev.get("name")
        if not name:
            continue
        par = ev.get("parent")
        if par:
            parents[name] = par
        spans.append(ev)
    totals: Dict[str, float] = {}
    for ev in spans:
        name = ev["name"]
        path = [name]
        seen = {name}
        cur = ev.get("parent")
        while cur and cur not in seen and len(path) < 64:
            path.append(cur)
            seen.add(cur)
            cur = parents.get(cur)
        stack = ";".join(reversed(path))
        totals[stack] = totals.get(stack, 0.0) + float(ev.get("dur_s") or 0.0)
    # Self-time: subtract each stack's total from its parent stack.
    folds = dict(totals)
    for stack, secs in totals.items():
        if ";" in stack:
            parent = stack.rsplit(";", 1)[0]
            if parent in folds:
                folds[parent] -= secs
    return {k: max(0.0, v) for k, v in folds.items()}


def fold_lines(folds: Dict[str, float]) -> List[str]:
    """Serialize folded stacks as flamegraph.pl lines (value = integer
    microseconds), sorted for determinism."""
    return [f"{stack} {int(round(secs * 1e6))}"
            for stack, secs in sorted(folds.items())]


def parse_folded(lines: List[str]) -> Dict[str, float]:
    """Inverse of :func:`fold_lines` (microseconds back to seconds);
    ignores blank/malformed lines. ``parse_folded(fold_lines(f))`` then
    ``fold_lines`` again is byte-identical — the prof-check round-trip."""
    out: Dict[str, float] = {}
    for line in lines:
        line = line.strip()
        if not line or " " not in line:
            continue
        stack, _, val = line.rpartition(" ")
        try:
            usec = int(val)
        except ValueError:
            continue
        out[stack] = out.get(stack, 0.0) + usec / 1e6
    return out


# -- tables -----------------------------------------------------------------

def attr_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [ev for ev in events if ev.get("type") == "attr"]


def top_executables(events: List[Dict[str, Any]], n: int = 10
                    ) -> List[Dict[str, Any]]:
    """Top-N (phase, executable, lane) rows by device-seconds from the
    ``attr`` events; falls back to per-span-name totals for streams that
    predate the attribution plane (so the table is never just empty)."""
    cells: Dict[tuple, Dict[str, Any]] = {}
    for ev in attr_events(events):
        key = (ev.get("phase"), ev.get("exe"), ev.get("lane", 0))
        c = cells.setdefault(key, {
            "phase": ev.get("phase"), "exe": ev.get("exe"),
            "lane": ev.get("lane", 0), "engine": ev.get("engine"),
            "seconds": 0.0, "calls": 0, "requests": 0, "flops": 0.0,
            "bytes": 0.0, "compile_s": 0.0})
        c["seconds"] += float(ev.get("seconds") or 0.0)
        c["calls"] += 1
        c["requests"] += int(ev.get("requests") or 0)
        c["flops"] += float(ev.get("flops") or 0.0)
        c["bytes"] += float(ev.get("bytes") or 0.0)
        c["compile_s"] += float(ev.get("compile_s") or 0.0)
    if not cells:
        for ev in events:
            if ev.get("type") != "span":
                continue
            key = (ev.get("name"), None, 0)
            c = cells.setdefault(key, {
                "phase": ev.get("name"), "exe": None, "lane": 0,
                "engine": None, "seconds": 0.0, "calls": 0, "requests": 0,
                "flops": 0.0, "bytes": 0.0, "compile_s": 0.0})
            c["seconds"] += float(ev.get("dur_s") or 0.0)
            c["calls"] += 1
    rows = sorted(cells.values(), key=lambda c: -c["seconds"])[:n]
    for c in rows:
        c["seconds"] = round(c["seconds"], 6)
        c["compile_s"] = round(c["compile_s"], 6)
        c["flops"] = round(c["flops"], 3)
        c["bytes"] = round(c["bytes"], 3)
    return rows


def roofline_series(events: List[Dict[str, Any]],
                    peaks: Optional[_attr.Peaks] = None
                    ) -> Dict[str, Dict[str, Any]]:
    """Per-engine achieved-vs-peak rows folded from the recorded ``attr``
    events (the offline twin of ``AttributionMatrix.roofline``). Peaks
    come from the stream's ``attr_plane`` start event when present (the
    ceiling the run actually measured against), else a fresh local
    calibration."""
    if peaks is None:
        plane = next((ev for ev in events
                      if ev.get("type") == "attr_plane"
                      and ev.get("flops_per_s")), None)
        if plane is not None:
            peaks = _attr.Peaks(
                flops_per_s=float(plane["flops_per_s"]),
                bytes_per_s=float(plane.get("bytes_per_s") or 1.0),
                source=str(plane.get("source") or "stream"))
        else:
            peaks = _attr.calibrate_peaks()
    engines: Dict[str, Dict[str, float]] = {}
    for ev in attr_events(events):
        engine = ev.get("engine") or "unknown"
        e = engines.setdefault(engine, {"seconds": 0.0, "calls": 0,
                                        "flops": 0.0, "bytes": 0.0,
                                        "stall_s": 0.0, "stall_w": 0.0})
        secs = float(ev.get("seconds") or 0.0)
        e["seconds"] += secs
        e["calls"] += 1
        e["flops"] += float(ev.get("flops") or 0.0)
        e["bytes"] += float(ev.get("bytes") or 0.0)
        if ev.get("stall_frac") is not None:
            e["stall_s"] += float(ev["stall_frac"]) * secs
            e["stall_w"] += secs
    out: Dict[str, Dict[str, Any]] = {}
    for engine, e in engines.items():
        secs = max(e["seconds"], 1e-9)
        row: Dict[str, Any] = {"device_s": round(e["seconds"], 6),
                               "calls": int(e["calls"])}
        if e["flops"]:
            achieved = e["flops"] / secs
            row["achieved_flops_per_s"] = round(achieved, 3)
            row["flops_frac"] = round(
                achieved / max(peaks.flops_per_s, 1e-9), 6)
        if e["bytes"]:
            bps = e["bytes"] / secs
            row["achieved_bytes_per_s"] = round(bps, 3)
            row["bytes_frac"] = round(bps / max(peaks.bytes_per_s, 1e-9), 6)
        if e["stall_w"] > 0:
            row["stall_frac"] = round(e["stall_s"] / e["stall_w"], 4)
        out[engine] = row
    return out


# -- rendering --------------------------------------------------------------

def _fmt_rate(v: Optional[float]) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.2f}"


def render_report(events: List[Dict[str, Any]], top: int = 10) -> str:
    rows = top_executables(events, top)
    roof = roofline_series(events)
    lines = ["top executables (device-seconds):",
             "   seconds   calls    reqs  lane  phase / executable"]
    for c in rows:
        exe = f"{c['phase']}" + (f" / {c['exe']}" if c.get("exe") else "")
        lines.append(f"  {c['seconds']:8.4f}  {c['calls']:6d}  "
                     f"{c['requests']:6d}  {c['lane']:4}  {exe}")
    if roof:
        lines.append("")
        lines.append("roofline (per engine, achieved vs peak):")
        for engine, r in sorted(roof.items()):
            frac = r.get("flops_frac")
            lines.append(
                f"  {engine:12s} device_s={r['device_s']:.4f} "
                f"flops/s={_fmt_rate(r.get('achieved_flops_per_s'))} "
                + (f"({100 * frac:.2f}% of peak) " if frac is not None
                   else "")
                + (f"stall={r['stall_frac']:.2f}"
                   if r.get("stall_frac") is not None else ""))
    return "\n".join(lines)


def load_events(target: str) -> List[Dict[str, Any]]:
    """Read ``path[:run_id]`` (the doctor targeting convention); the run
    suffix filters a multi-run file down to one run's events."""
    from gauss_tpu.obs import doctor as _doctor

    path, rid = _doctor.parse_target(target)
    events = registry.read_events(path)
    if rid:
        events = [ev for ev in events if ev.get("run") == rid]
    return events


def scrape_snapshot(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """Fetch a live server's ``/snapshot`` JSON (obs.export)."""
    from urllib.request import urlopen

    if not url.endswith("/snapshot"):
        url = url.rstrip("/") + "/snapshot"
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def render_live(snap: Dict[str, Any]) -> str:
    at = snap.get("attr") or {}
    if not at.get("recording"):
        return ("attribution plane is off on this server "
                "(start it with ServeConfig(attr=True))")
    lines = [f"attribution: {at.get('observes', 0)} observes, "
             f"device_s_total={at.get('device_s_total', 0.0)}, "
             f"peaks={at.get('peaks', {}).get('source', '?')}"]
    lines.append("top executables (device-seconds):")
    for c in (at.get("cells") or [])[:10]:
        lines.append(f"  {c['seconds']:8.4f}  {c['calls']:6d}  "
                     f"{c['requests']:6d}  {c['lane']:4}  "
                     f"{c['phase']} / {c['exe']}")
    roof = at.get("roofline") or {}
    if roof:
        lines.append("roofline (per engine):")
        for engine, r in sorted(roof.items()):
            frac = r.get("flops_frac")
            lines.append(
                f"  {engine:12s} device_s={r['device_s']:.4f} "
                f"flops/s={_fmt_rate(r.get('achieved_flops_per_s'))}"
                + (f" ({100 * frac:.2f}% of peak)"
                   if frac is not None else ""))
    cap = (at.get("capacity") or {}).get("sigs") or {}
    if cap:
        lines.append("capacity (per compat-sig):")
        for sig, s in sorted(cap.items()):
            lines.append(f"  {sig:24s} {s['device_s_per_request'] * 1e3:8.3f}"
                         f" ms/req  ~{s['est_requests_per_s']:.1f} req/s")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gauss-prof",
        description="Device-time attribution reports: top-executable "
                    "tables, per-engine rooflines, and folded-stack "
                    "flamegraphs from a recorded stream or a live scrape.")
    p.add_argument("stream", nargs="?", default=None,
                   help="recorded metrics JSONL: path[:run_id]")
    p.add_argument("--url", default=None, metavar="URL",
                   help="live server base URL — render from its /snapshot "
                        "attr section instead of a file")
    p.add_argument("--top", type=int, default=10,
                   help="rows in the top-executables table (default 10)")
    p.add_argument("--folded", default=None, metavar="PATH",
                   help="write folded-stack lines here ('-' = stdout) — "
                        "feed to flamegraph.pl / speedscope")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of text")
    args = p.parse_args(argv)
    if args.url:
        try:
            snap = scrape_snapshot(args.url)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"gauss-prof: scrape failed: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(snap.get("attr") or {}, indent=1,
                             sort_keys=True))
        else:
            print(render_live(snap))
        return 0
    if not args.stream:
        p.error("a stream path or --url is required")
    try:
        events = load_events(args.stream)
    except (OSError, ValueError) as e:
        print(f"gauss-prof: {e}", file=sys.stderr)
        return 2
    if args.folded:
        lines = fold_lines(folded_stacks(events))
        if args.folded == "-":
            print("\n".join(lines))
        else:
            with open(args.folded, "w") as f:
                f.write("\n".join(lines) + "\n")
            print(f"gauss-prof: wrote {len(lines)} folded stack(s) to "
                  f"{args.folded}", file=sys.stderr)
        if args.json or args.folded == "-":
            return 0
    if args.json:
        print(json.dumps({"top": top_executables(events, args.top),
                          "roofline": roofline_series(events)},
                         indent=1, sort_keys=True))
    else:
        print(render_report(events, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
