"""Span-based tracing over the active recorder: nested wall-clock regions.

Generalizes :class:`gauss_tpu.utils.profiling.PhaseTimer` (which keeps its
print-a-table surface and now ALSO reports here): a span is one named
wall-clock region with a parent, so the summarizer can render both a
gprof-style flat profile (aggregate by name) and a nesting-aware coverage
check (leaf spans vs the root's duration). Spans measure HOST wall-clock;
callers bounding device work must block/fetch before the span closes, same
rule as ``PhaseTimer.phase(block_on=...)``.

Three sinks hang off these hooks:

- the **recorder** (per-run JSONL, post-hoc analysis) — one process-global
  handed over by :func:`run`;
- the **live sink** (:class:`gauss_tpu.obs.live.LiveAggregator`) — rolling-
  window in-memory views the ``/metrics`` exposition serves while the
  process runs. Installed by :func:`set_live_sink`; every hook forwards to
  it with the same zero-cost-when-absent contract the recorder has (one
  module-global read);
- the **flight sink** (:class:`gauss_tpu.obs.flight.FlightSink`) — a
  crash-surviving mmap ring of the most recent events, harvested by
  post-mortem capture after a kill. Installed by :func:`set_flight_sink`;
  same contract again, so ``flight_dir=None`` processes pay exactly one
  ``is None`` read per hook.

Additionally, a thread-local **trace context** (:func:`trace_context`)
stamps every event emitted inside it with a ``trace`` id, so request-scoped
work that flows through library code with no trace parameter (the recovery
ladder, handoff routing) still lands in the right per-request span tree
(``gauss_tpu.obs.requesttrace``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Optional

from gauss_tpu.obs import registry as _registry

# One active recorder per process (drivers are single-run); a lock guards
# hand-over, and the span stack is thread-local so bench worker threads
# cannot corrupt each other's nesting.
_state_lock = threading.Lock()
_active: Optional[_registry.Recorder] = None
_live = None  # live sink (duck-typed: on_counter/on_gauge/... — see live.py)
_flight = None  # flight sink (same duck type — see flight.py)
_tls = threading.local()


def active() -> Optional[_registry.Recorder]:
    """The recorder events currently report into (None -> hooks no-op)."""
    return _active


def live_sink():
    """The installed live aggregator (None -> live forwarding no-ops)."""
    return _live


def set_live_sink(sink):
    """Install ``sink`` as the process's live telemetry sink; returns the
    previous sink so callers can restore it (the server install/uninstall
    pair). ``None`` uninstalls. The sink receives ``on_counter``,
    ``on_gauge``, ``on_histogram``, ``on_span``, and ``on_event`` calls
    from the same hooks the recorder gets — in-band, no second
    instrumentation path."""
    global _live
    with _state_lock:
        prev = _live
        _live = sink
    return prev


def flight_sink():
    """The installed flight recorder sink (None -> no crash ring)."""
    return _flight


def set_flight_sink(sink):
    """Install ``sink`` as the process's crash-surviving flight sink;
    returns the previous sink so callers can restore (and close) it.
    ``None`` uninstalls. Receives the same ``on_counter``/``on_gauge``/
    ``on_histogram``/``on_span``/``on_event`` calls as the live sink —
    the ring sees exactly the stream everything else sees."""
    global _flight
    with _state_lock:
        prev = _flight
        _flight = sink
    return prev


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


# -- trace context ---------------------------------------------------------

def current_trace() -> Optional[str]:
    """The trace id events on THIS thread are being stamped with."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def trace_context(trace_id: Optional[str]):
    """Stamp every event emitted on this thread inside the block with
    ``trace=trace_id`` (unless the emit already carries one). Nests: the
    innermost context wins, the outer one is restored on exit."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace_id
    try:
        yield
    finally:
        _tls.trace = prev


@contextlib.contextmanager
def run(metrics_out=None, run_id: Optional[str] = None, **meta):
    """Activate a recorder for the duration of the block; flush to
    ``metrics_out`` (JSONL, append) on exit when given. Re-entrant use nests
    harmlessly: an inner ``run`` with no ``metrics_out`` reuses the outer
    recorder instead of shadowing it, so library code can declare a run
    without stealing the driver's.

    ``run_id`` defaults to the GAUSS_OBS_RUN_ID environment variable when
    set — the multihost hook: a launcher exports one id to every process so
    their per-process streams merge as ONE run in ``obs.aggregate``."""
    global _active
    with _state_lock:
        outer = _active
        if outer is not None and metrics_out is None:
            rec = outer
        else:
            rec = _registry.Recorder(
                run_id=run_id or os.environ.get("GAUSS_OBS_RUN_ID"),
                meta=meta)
            _active = rec
    try:
        yield rec
    finally:
        if rec is not outer:
            rec.close()
            with _state_lock:
                _active = outer
            if metrics_out:
                rec.flush(metrics_out)


def emit(type_: str, **fields):
    """Record one event on the active recorder and forward it to the live
    sink (no-op when neither is present). Events emitted inside a
    :func:`trace_context` are stamped with the context's trace id."""
    rec = _active
    ls = _live
    fs = _flight
    if rec is None and ls is None and fs is None:
        return None
    tid = getattr(_tls, "trace", None)
    if tid is not None and "trace" not in fields and "traces" not in fields:
        fields["trace"] = tid
    ev = rec.emit(type_, **fields) if rec is not None else None
    if ls is not None:
        ls.on_event(type_, fields)
    if fs is not None:
        fs.on_event(type_, fields)
    return ev


def counter(name: str, inc: float = 1) -> None:
    rec = _active
    if rec is not None:
        rec.counter(name, inc)
    ls = _live
    if ls is not None:
        ls.on_counter(name, inc)
    fs = _flight
    if fs is not None:
        fs.on_counter(name, inc)


def gauge(name: str, value: float) -> None:
    rec = _active
    if rec is not None:
        rec.gauge(name, value)
    ls = _live
    if ls is not None:
        ls.on_gauge(name, value)
    fs = _flight
    if fs is not None:
        fs.on_gauge(name, value)


def histogram(name: str, value: float) -> None:
    rec = _active
    if rec is not None:
        rec.histogram(name, value)
    ls = _live
    if ls is not None:
        ls.on_histogram(name, value)
    fs = _flight
    if fs is not None:
        fs.on_histogram(name, value)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a named region; records a ``span`` event with parent/depth on
    exit. Zero-cost (two global reads) when no sink is active."""
    rec = _active
    ls = _live
    fs = _flight
    if rec is None and ls is None and fs is None:
        yield
        return
    stack = _stack()
    parent = stack[-1] if stack else None
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        tid = getattr(_tls, "trace", None)
        if tid is not None and "trace" not in attrs and "traces" not in attrs:
            attrs = dict(attrs, trace=tid)
        if rec is not None:
            rec.emit("span", name=name, dur_s=round(dur, 6), parent=parent,
                     depth=len(stack), **attrs)
            rec.histogram(f"span.{name}.s", dur)
        if ls is not None:
            ls.on_span(name, dur, parent, len(stack), attrs)
        if fs is not None:
            fs.on_span(name, dur, parent, len(stack), attrs)


def record_span(name: str, seconds: float, parent: Optional[str] = None,
                **attrs) -> None:
    """Record an externally measured duration as a span (for spans whose
    wall-clock was produced elsewhere — ``timed_fetch`` results, PhaseTimer
    phases, the reference-parity CLI timing numbers). Parent defaults to the
    currently open span of THIS thread, so these interleave correctly with
    ``with span(...)`` nesting."""
    rec = _active
    ls = _live
    fs = _flight
    if rec is None and ls is None and fs is None:
        return
    stack = _stack()
    if parent is None and stack:
        parent = stack[-1]
    if rec is not None:
        rec.emit("span", name=name, dur_s=round(float(seconds), 6),
                 parent=parent, depth=len(stack), **attrs)
        rec.histogram(f"span.{name}.s", float(seconds))
    if ls is not None:
        ls.on_span(name, float(seconds), parent, len(stack), attrs)
    if fs is not None:
        fs.on_span(name, float(seconds), parent, len(stack), attrs)
