# Top-level build/test entry points (reference C9 analog: the reference
# builds each program with documented gcc/nvcc one-liners; here one Makefile
# drives the native library, tests, benchmarks, and dataset regeneration).

PYTHON ?= python
OBS_SMOKE ?= /tmp/gauss_obs_check.jsonl

.PHONY: all native test bench datasets obs-check clean

all: native

native:
	$(MAKE) -C gauss_tpu/native/src

test: native
	$(PYTHON) -m pytest tests/ -q

bench:
	$(PYTHON) bench.py

# The observability gate (CI-callable): the regression sentinel against the
# committed history (the latest BENCH records must stay inside the epoch-
# noise band), then a live --metrics-out run smoke-tested through the
# machine-readable summarizer and the Chrome-trace exporter.
obs-check:
	$(PYTHON) -m gauss_tpu.obs.regress check BENCH_r04.json BENCH_r05.json \
	  --history reports/history.jsonl
	rm -f $(OBS_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.cli.gauss_internal -s 64 -t 2 \
	  --backend tpu-unblocked --verify --metrics-out $(OBS_SMOKE)
	$(PYTHON) -m gauss_tpu.obs.summarize $(OBS_SMOKE) --json > /dev/null
	$(PYTHON) -m gauss_tpu.obs.trace $(OBS_SMOKE) -o $(OBS_SMOKE).trace.json

datasets:
	$(PYTHON) -m gauss_tpu.cli.datasets

clean:
	$(MAKE) -C gauss_tpu/native/src clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
